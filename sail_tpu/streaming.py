"""Streaming execution (micro-batch).

Reference role: the streaming subsystem — rate/socket sources, flow-event
markers, streaming query lifecycle (SURVEY.md §3.5; sail-common-datafusion
streaming events, sail-data-source rate format). Design note: the reference
streams Chandy–Lamport-style markers through a continuous dataflow; this
engine uses Spark's own micro-batch model instead — each trigger snapshots
the source offsets, runs a normal (fully jitted) batch query over the new
slice, and commits. Markers survive as the offset/epoch bookkeeping.

v0 sources: rate (rowsPerSecond), memory-append; sinks: memory (queryable
as a temp view), console, foreachBatch.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, List, Optional

import pyarrow as pa

from .spec import plan as sp


class StreamSource:
    def next_batch(self) -> Optional[pa.Table]:
        raise NotImplementedError

    # durable-checkpoint support: serializable position + restore
    def offset(self):
        return None

    def seek(self, offset):
        pass

    @property
    def schema(self) -> pa.Schema:
        raise NotImplementedError


class RateSource(StreamSource):
    """value/timestamp rows at rowsPerSecond (reference: formats/rate)."""

    def offset(self):
        return self._emitted

    def seek(self, offset):
        self._emitted = int(offset or 0)

    def __init__(self, rows_per_second: int = 1):
        self.rows_per_second = rows_per_second
        self._start = time.time()
        self._emitted = 0

    @property
    def schema(self) -> pa.Schema:
        return pa.schema([("timestamp", pa.timestamp("us", tz="UTC")),
                          ("value", pa.int64())])

    def next_batch(self) -> Optional[pa.Table]:
        now = time.time()
        target = int((now - self._start) * self.rows_per_second)
        if target <= self._emitted:
            return None
        values = list(range(self._emitted, target))
        base_us = int(self._start * 1_000_000)
        ts = [base_us + int(v * 1_000_000 / self.rows_per_second)
              for v in values]
        self._emitted = target
        return pa.table({
            "timestamp": pa.array(ts, type=pa.int64()).cast(
                pa.timestamp("us", tz="UTC")),
            "value": pa.array(values, type=pa.int64()),
        })


class MemoryStreamSource(StreamSource):
    """Programmatic append source (for tests / foreachBatch pipelines)."""

    def __init__(self, schema: pa.Schema):
        self._schema = schema
        self._pending: List[pa.Table] = []
        self._lock = threading.Lock()

    @property
    def schema(self) -> pa.Schema:
        return self._schema

    def add(self, table: pa.Table):
        with self._lock:
            self._pending.append(table)

    def next_batch(self) -> Optional[pa.Table]:
        with self._lock:
            if not self._pending:
                return None
            out = pa.concat_tables(self._pending)
            self._pending.clear()
            return out


class FileStreamSource(StreamSource):
    """Watches a directory; each new file is a micro-batch slice
    (reference role: the file listing streaming source)."""

    def __init__(self, fmt: str, path: str, options: Dict[str, str],
                 declared_schema=None):
        self._fmt = fmt
        self._path = path
        self._options = options
        self._seen: set = set()
        self._declared = declared_schema  # spec StructType | None
        self._schema: Optional[pa.Schema] = None

    def schema(self) -> pa.Schema:
        if self._schema is None:
            if self._declared is not None:
                from .columnar.arrow_interop import spec_type_to_arrow
                self._schema = pa.schema(
                    [(f.name, spec_type_to_arrow(f.data_type))
                     for f in self._declared.fields])
            else:
                from .io.formats import read_table
                t = read_table(self._fmt, (self._path,), self._options,
                               limit=1)
                self._schema = t.schema
        return self._schema

    def offset(self):
        return sorted(self._seen)

    def seek(self, offset):
        self._seen = set(offset or [])

    def next_batch(self) -> Optional[pa.Table]:
        import os as _os
        from .io.formats import expand_paths, read_table
        files = [f for f in expand_paths((self._path,))
                 if f not in self._seen]
        if not files:
            return None
        self._seen.update(files)
        out = read_table(self._fmt, files, self._options)
        if self._declared is not None:
            target = self.schema()
            out = out.rename_columns(
                [f.name for f in target]).cast(target, safe=False)
        return out


class SocketStreamSource(StreamSource):
    """Newline-delimited text over TCP as `value` string rows (reference
    role: the socket streaming source — like Spark's, it is NOT
    replayable: offsets count consumed lines for progress reporting only
    and seek is a no-op).

    Connection is lazy (first ``next_batch``) and ``close()`` resets the
    source, so a stopped query's DataFrame can be started again — the
    restarted query reconnects (Spark connects per started query)."""

    def __init__(self, host: str, port: int):
        self._host = host
        self._port = port
        self._lines: List[str] = []
        self._lock = threading.Lock()
        self._consumed = 0
        self._closed = threading.Event()
        self._sock = None
        self._thread: Optional[threading.Thread] = None

    def _ensure_connected(self):
        import socket as _socket

        with self._lock:
            # connect once per lifecycle: a peer-closed connection does
            # NOT auto-reconnect (that could silently replay data); only
            # an explicit close() resets the source for a restart
            if self._thread is not None:
                return
            self._closed = threading.Event()
            # connect may raise — surfaced as the query's exception
            sock = _socket.create_connection((self._host, self._port),
                                             timeout=10)
            # the timeout applies to connect only — an idle (but live)
            # stream must block in recv, not trip a 10s read timeout
            sock.settimeout(None)
            self._sock = sock
            closed = self._closed

            def reader():
                buf = b""
                try:
                    while not closed.is_set():
                        chunk = sock.recv(65536)
                        if not chunk:
                            break
                        buf += chunk
                        *complete, buf = buf.split(b"\n")
                        if complete:
                            with self._lock:
                                self._lines.extend(
                                    c.decode("utf-8", "replace")
                                    for c in complete)
                except OSError:
                    pass
                finally:
                    if buf and not closed.is_set():
                        with self._lock:
                            self._lines.append(
                                buf.decode("utf-8", "replace"))

            self._thread = threading.Thread(target=reader, daemon=True)
            self._thread.start()

    @property
    def schema(self) -> pa.Schema:
        return pa.schema([("value", pa.string())])

    def offset(self):
        return self._consumed

    def next_batch(self) -> Optional[pa.Table]:
        self._ensure_connected()
        with self._lock:
            if not self._lines:
                return None
            out, self._lines = self._lines, []
        self._consumed += len(out)
        return pa.table({"value": pa.array(out, type=pa.string())})

    def close(self):
        self._closed.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        with self._lock:
            self._thread = None
            self._lines.clear()


class StreamingQuery:
    """A running micro-batch query (reference: streaming query lifecycle,
    plan_executor.rs handle_execute_streaming_query_command)."""

    def __init__(self, session, plan: sp.QueryPlan, source_name: str,
                 source: StreamSource, sink: Callable[[int, pa.Table], None],
                 interval_s: float = 0.1, query_name: Optional[str] = None,
                 output_mode: str = "append",
                 watermark: Optional[tuple] = None,
                 checkpoint_dir: Optional[str] = None):
        self.id = uuid.uuid4().hex
        self.name = query_name
        self._session = session
        self._plan = plan
        self._source_name = source_name
        self._source = source
        self._sink = sink
        self._interval = interval_s
        self._stop = threading.Event()
        self._batch_id = 0
        self.exception: Optional[Exception] = None
        self.recent_progress: List[dict] = []
        # stateful aggregation: buffer rows within the watermark horizon
        # and re-aggregate per micro-batch (Spark's complete/update modes)
        self._stateful = _has_aggregate(plan)
        self._mode = output_mode
        self._watermark = watermark  # (column, delay_seconds)
        self._watermark_ts: Optional[float] = None
        self._buffer: Optional[pa.Table] = None
        self._prev_result: Optional[pa.Table] = None
        self._checkpoint_dir = checkpoint_dir
        self._proc_lock = threading.Lock()
        # highest batch id the offsets checkpoint has DURABLY recorded —
        # commit-marker retention may only prune below this (a marker
        # for a batch the checkpoint hasn't passed is still replayable)
        self._last_ckpt_batch = 0
        if checkpoint_dir:
            self._restore_checkpoint()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @property
    def isActive(self) -> bool:
        return self._thread.is_alive()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=30)
        close = getattr(self._source, "close", None)
        if close is not None:
            close()

    def awaitTermination(self, timeout: Optional[float] = None) -> bool:
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def processAllAvailable(self):
        """Block until the source has no pending data AND any in-flight
        trigger finished (test helper)."""
        while True:
            with self._proc_lock:
                batch = self._source.next_batch()
                if batch is None or batch.num_rows == 0:
                    return
                self._process(batch)

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                with self._proc_lock:
                    batch = self._source.next_batch()
                    if batch is not None and batch.num_rows:
                        self._process(batch)
            except Exception as e:  # noqa: BLE001 — surfaced via .exception
                self.exception = e
                return

    def _process(self, batch: pa.Table):
        t0 = time.time()
        if self._stateful:
            result = self._process_stateful(batch)
        else:
            bound = _substitute_source(self._plan, self._source_name,
                                       sp.LocalRelation(batch))
            result = self._session._execute_query(bound)
        if result is not None and not self._already_committed(
                self._batch_id):
            self._sink(self._batch_id, result)
            self._mark_committed(self._batch_id)
        if self._checkpoint_dir:
            self._write_checkpoint()
        self.recent_progress.append({
            "batchId": self._batch_id,
            "numInputRows": batch.num_rows,
            "durationMs": int((time.time() - t0) * 1000),
            "watermark": self._watermark_ts,
        })
        del self.recent_progress[:-32]
        self._batch_id += 1

    # -- stateful micro-batch aggregation -------------------------------
    def _process_stateful(self, batch: pa.Table) -> Optional[pa.Table]:
        self._buffer = batch if self._buffer is None else pa.concat_tables(
            [self._buffer, batch], promote_options="permissive")
        if self._watermark is not None:
            col, delay_s = self._watermark
            if col in self._buffer.column_names:
                import pyarrow.compute as pc
                mx = pc.max(self._buffer.column(col)).as_py()
                if mx is not None:
                    ts = mx.timestamp() if hasattr(mx, "timestamp")                         else float(mx)
                    self._watermark_ts = ts - delay_s
                    # evict rows the watermark has passed (bounded state)
                    keep = pc.greater_equal(
                        _col_as_seconds(self._buffer.column(col)),
                        self._watermark_ts)
                    self._buffer = self._buffer.filter(keep)
        bound = _substitute_source(self._plan, self._source_name,
                                   sp.LocalRelation(self._buffer))
        result = self._session._execute_query(bound)
        if self._mode == "complete":
            self._prev_result = result
            return result
        # update mode: only rows that changed since the last trigger
        prev = self._prev_result
        self._prev_result = result
        if prev is None or prev.num_rows == 0:
            return result
        prev_rows = {tuple(r.values()) for r in prev.to_pylist()}
        changed = [r for r in result.to_pylist()
                   if tuple(r.values()) not in prev_rows]
        if not changed:
            return result.slice(0, 0)
        import pyarrow as _pa
        return _pa.Table.from_pylist(changed, schema=result.schema)

    # -- sink commit log (exactly-once) ---------------------------------
    # The sink write happens BEFORE the offsets checkpoint, so a crash
    # between them replays the batch on restart. The commit marker
    # (atomic create-if-absent, Spark's commits/ layout) makes the replay
    # skip the duplicate write: at-least-once processing + idempotent
    # commit = exactly-once sink output for deterministic sources.
    def _commit_marker(self, batch_id: int) -> Optional[str]:
        if not self._checkpoint_dir:
            return None
        import os as _os
        return _os.path.join(self._checkpoint_dir, "commits",
                             str(batch_id))

    def _already_committed(self, batch_id: int) -> bool:
        import os as _os
        marker = self._commit_marker(batch_id)
        return marker is not None and _os.path.exists(marker)

    def _mark_committed(self, batch_id: int):
        marker = self._commit_marker(batch_id)
        if marker is None:
            return
        import os as _os
        _os.makedirs(_os.path.dirname(marker), exist_ok=True)
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            f.write("{}")
        _os.replace(tmp, marker)
        # retention: only markers >= the last checkpointed batch id can
        # ever be consulted on restart; prune far-older ones so a
        # long-running query doesn't grow one file per trigger forever.
        # The floor is the last SUCCESSFULLY CHECKPOINTED batch id, not
        # the current one — if checkpointing stalls, every batch from
        # the stalled offset on stays replayable and must keep its
        # marker, or a restart would duplicate its sink output.
        if batch_id % 100 == 0:
            floor = self._last_ckpt_batch - 100
            commits_dir = _os.path.dirname(marker)
            for name in _os.listdir(commits_dir):
                try:
                    if int(name) < floor:
                        _os.unlink(_os.path.join(commits_dir, name))
                except (ValueError, OSError):
                    continue

    # -- durable checkpoints --------------------------------------------
    def _write_checkpoint(self):
        import json
        import os as _os
        _os.makedirs(self._checkpoint_dir, exist_ok=True)
        state = {"batch_id": self._batch_id + 1,
                 "offset": self._source.offset(),
                 "watermark": self._watermark_ts}
        if self._buffer is not None:
            sink_buf = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink_buf, self._buffer.schema) as w:
                w.write_table(self._buffer)
            with open(_os.path.join(self._checkpoint_dir, "state.arrow.tmp"),
                      "wb") as f:
                f.write(sink_buf.getvalue().to_pybytes())
            _os.replace(_os.path.join(self._checkpoint_dir,
                                      "state.arrow.tmp"),
                        _os.path.join(self._checkpoint_dir, "state.arrow"))
        tmp = _os.path.join(self._checkpoint_dir, "offsets.json.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        _os.replace(tmp, _os.path.join(self._checkpoint_dir,
                                       "offsets.json"))
        self._last_ckpt_batch = int(state["batch_id"])

    def _restore_checkpoint(self):
        import json
        import os as _os
        path = _os.path.join(self._checkpoint_dir, "offsets.json")
        if not _os.path.exists(path):
            return
        with open(path) as f:
            state = json.load(f)
        self._batch_id = int(state.get("batch_id", 0))
        self._last_ckpt_batch = self._batch_id
        self._watermark_ts = state.get("watermark")
        self._source.seek(state.get("offset"))
        spath = _os.path.join(self._checkpoint_dir, "state.arrow")
        if _os.path.exists(spath):
            with open(spath, "rb") as f:
                self._buffer = pa.ipc.open_stream(f.read()).read_all()


def _substitute_source(plan: sp.QueryPlan, name: str,
                       replacement: sp.QueryPlan) -> sp.QueryPlan:
    import dataclasses

    if isinstance(plan, sp.ReadNamedTable) and plan.name[-1].lower() == name:
        return replacement
    if isinstance(plan, _StreamRead) and plan.source_name == name:
        return replacement
    for f in dataclasses.fields(plan) if dataclasses.is_dataclass(plan) else []:
        v = getattr(plan, f.name)
        if isinstance(v, sp.QueryPlan):
            plan = dataclasses.replace(
                plan, **{f.name: _substitute_source(v, name, replacement)})
    return plan


class _StreamRead(sp.QueryPlan):
    """Marker leaf for readStream plans (pre-bind)."""

    def __init__(self, source_name: str, source: StreamSource):
        object.__setattr__(self, "source_name", source_name)
        object.__setattr__(self, "source", source)


class DataStreamReader:
    def __init__(self, session):
        self._session = session
        self._format = "rate"
        self._options: Dict[str, str] = {}
        self._declared_schema = None

    def format(self, fmt: str) -> "DataStreamReader":
        self._format = fmt.lower()
        return self

    def option(self, key, value) -> "DataStreamReader":
        self._options[str(key).lower()] = str(value)
        return self

    def schema(self, schema) -> "DataStreamReader":
        if isinstance(schema, str):
            from .session import _parse_ddl_schema
            self._declared_schema = _parse_ddl_schema(schema)
        else:
            self._declared_schema = schema
        return self

    def load(self, path: Optional[str] = None):
        from .session import DataFrame
        if self._format == "rate":
            src: StreamSource = RateSource(
                int(self._options.get("rowspersecond", 1)))
        elif self._format == "socket":
            host = self._options.get("host")
            port = self._options.get("port")
            if not host or not port:
                raise ValueError("socket source requires host and port")
            src = SocketStreamSource(host, int(port))
        elif self._format in ("parquet", "csv", "json", "text"):
            p = path or self._options.get("path")
            if not p:
                raise ValueError("file stream source requires a path")
            src = FileStreamSource(self._format, p, dict(self._options),
                                   declared_schema=self._declared_schema)
        else:
            raise ValueError(f"unsupported stream source {self._format!r}")
        name = f"__stream_{uuid.uuid4().hex[:8]}"
        plan = _StreamRead(name, src)
        df = DataFrame(plan, self._session)
        return df


class DataStreamWriter:
    def __init__(self, df):
        self._df = df
        self._format = "memory"
        self._query_name: Optional[str] = None
        self._options: Dict[str, str] = {}
        self._foreach_batch: Optional[Callable] = None
        self._output_mode = "append"

    def format(self, fmt: str) -> "DataStreamWriter":
        self._format = fmt.lower()
        return self

    def queryName(self, name: str) -> "DataStreamWriter":
        self._query_name = name
        return self

    def outputMode(self, mode: str) -> "DataStreamWriter":
        self._output_mode = mode.lower()
        return self

    def option(self, key, value) -> "DataStreamWriter":
        self._options[str(key).lower()] = str(value)
        return self

    def trigger(self, processingTime: Optional[str] = None, **_) -> "DataStreamWriter":
        if processingTime:
            num = float(processingTime.split()[0])
            unit = processingTime.split()[1] if " " in processingTime else "seconds"
            self._options["interval_s"] = str(
                num * (0.001 if unit.startswith("milli") else 1.0))
        return self

    def foreachBatch(self, fn: Callable) -> "DataStreamWriter":
        self._foreach_batch = fn
        return self

    def start(self, path: Optional[str] = None) -> StreamingQuery:
        if path is not None:
            self._options["path"] = str(path)
        session = self._df._session
        plan = self._df._plan
        src_node = _find_stream_read(plan)
        if src_node is None:
            raise ValueError("writeStream requires a readStream source")
        sink = self._make_sink(session)
        watermark = _find_watermark(plan)
        q = StreamingQuery(session, plan, src_node.source_name,
                           src_node.source, sink,
                           float(self._options.get("interval_s", 0.1)),
                           self._query_name,
                           output_mode=self._output_mode,
                           watermark=watermark,
                           checkpoint_dir=self._options.get(
                               "checkpointlocation"))
        return q

    def _make_sink(self, session):
        if self._foreach_batch is not None:
            fb = self._foreach_batch

            def sink(batch_id, table):
                fb(_as_df(session, table), batch_id)

            return sink
        if self._format == "console":
            def sink(batch_id, table):
                print(f"-------- Batch {batch_id} --------")
                print(table.to_pandas().to_string(index=False))

            return sink
        if self._format == "memory":
            name = self._query_name or "stream"
            state = {"tables": []}

            def sink(batch_id, table):
                state["tables"].append(table)
                merged = pa.concat_tables(state["tables"],
                                          promote_options="permissive")
                session.createDataFrame(merged).createOrReplaceTempView(name)

            return sink
        if self._format == "noop":
            return lambda batch_id, table: None
        if self._format in ("parquet", "csv", "json"):
            # file sink: one part file per micro-batch. Exactly-once
            # comes from the COMMIT LOG in StreamingQuery._process —
            # replayed batches whose commit marker exists skip the write
            # (reference: the reference's checkpointed sink epochs,
            # SURVEY.md §5 checkpoint/resume)
            import os as _os
            import uuid as _uuid

            out_dir = self._options.get("path")
            if not out_dir:
                raise ValueError("file sinks require a path")
            fmt = self._format

            def sink(batch_id, table):
                if table.num_rows == 0:
                    return
                _os.makedirs(out_dir, exist_ok=True)
                ext = {"parquet": "parquet", "csv": "csv",
                       "json": "json"}[fmt]
                # DETERMINISTIC per-batch name: a replay after a crash
                # between the rename and the commit marker overwrites the
                # same file instead of duplicating the batch
                name = f"part-{batch_id:05d}.{ext}"
                tmp = _os.path.join(out_dir,
                                    f".{name}.{_uuid.uuid4().hex}.tmp")
                if fmt == "parquet":
                    import pyarrow.parquet as _pq
                    _pq.write_table(table, tmp)
                elif fmt == "csv":
                    import pyarrow.csv as _pacsv
                    _pacsv.write_csv(table, tmp)
                else:
                    import json as _json
                    with open(tmp, "w") as f:
                        for row in table.to_pylist():
                            f.write(_json.dumps(row, default=str) + "\n")
                _os.replace(tmp, _os.path.join(out_dir, name))

            return sink
        raise ValueError(f"unsupported stream sink {self._format!r}")


def _find_watermark(plan):
    import dataclasses
    if isinstance(plan, sp.WithWatermark):
        return (plan.column, plan.delay_seconds)
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, sp.QueryPlan):
                r = _find_watermark(v)
                if r is not None:
                    return r
    return None


def _has_aggregate(plan) -> bool:
    import dataclasses
    if isinstance(plan, (sp.Aggregate, sp.Deduplicate)):
        return True
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, sp.QueryPlan) and _has_aggregate(v):
                return True
    return False


def _col_as_seconds(col):
    import pyarrow as _pa
    import pyarrow.compute as pc
    if _pa.types.is_timestamp(col.type):
        # normalize to microseconds regardless of the column's unit;
        # tz-naive columns are interpreted as UTC (matching _event_seconds)
        us = pc.cast(col, _pa.timestamp("us", tz=col.type.tz))
        return pc.divide(pc.cast(us, _pa.int64()), 1_000_000)
    return pc.cast(col, _pa.float64())


def _event_seconds(v) -> float:
    """Max event-time value → epoch seconds; naive datetimes are UTC."""
    import datetime as _dt
    if hasattr(v, "timestamp"):
        if v.tzinfo is None:
            v = v.replace(tzinfo=_dt.timezone.utc)
        return v.timestamp()
    return float(v)


def parse_delay(text: str) -> float:
    parts = text.strip().split()
    num = float(parts[0])
    unit = parts[1].lower() if len(parts) > 1 else "seconds"
    mult = {"millisecond": 0.001, "second": 1.0, "minute": 60.0,
            "hour": 3600.0, "day": 86400.0}
    for k, m in mult.items():
        if unit.startswith(k) or unit.rstrip("s").startswith(k):
            return num * m
    return num


def _as_df(session, table: pa.Table):
    return session.createDataFrame(table)


def _find_stream_read(plan) -> Optional[_StreamRead]:
    import dataclasses

    if isinstance(plan, _StreamRead):
        return plan
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, sp.QueryPlan):
                r = _find_stream_read(v)
                if r is not None:
                    return r
    return None
