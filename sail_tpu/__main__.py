"""CLI entry point: ``python -m sail_tpu <command>``.

Reference role: sail-cli (crates/sail-cli/src/runner.rs — spark server /
shell / worker subcommands).
"""

from __future__ import annotations

import argparse
import os
import sys


def _ensure_backend(timeout_s: float = 150.0):
    """Fall back to CPU when the default jax backend can't initialize
    (e.g. a wedged remote-TPU tunnel) instead of hanging forever."""
    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # explicit CPU request (e.g. spawned cluster workers): skip the
        # accelerator probe entirely — the image's sitecustomize overrides
        # the env var at interpreter start, so pin the config too
        import jax
        jax.config.update("jax_platforms", "cpu")
        return
    import subprocess
    try:
        r = subprocess.run([sys.executable, "-c", "import jax; jax.devices()"],
                           timeout=timeout_s, capture_output=True)
        ok = r.returncode == 0
    except subprocess.TimeoutExpired:
        ok = False
    if not ok:
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="sail_tpu",
                                     description="TPU-native Spark-capable engine")
    sub = parser.add_subparsers(dest="command", required=True)

    p_server = sub.add_parser(
        "server", help="run the Spark Connect server (+ native SQL protocol)")
    p_server.add_argument("--host", default="127.0.0.1")
    p_server.add_argument("--port", type=int, default=50051,
                          help="Spark Connect port (15002 is Spark's default)")
    p_server.add_argument("--sql-port", type=int, default=0,
                          help="also serve the native SQL protocol here")

    p_shell = sub.add_parser("shell", help="interactive SQL shell")
    p_shell.add_argument("--remote", default=None,
                         help="host:port of a running server (default: in-process)")

    p_bench = sub.add_parser("bench", help="run the benchmark")
    p_bench.add_argument("sf", nargs="?", type=float, default=1.0)

    p_flight = sub.add_parser(
        "flight", help="run the Arrow Flight SQL server")
    p_flight.add_argument("--host", default="127.0.0.1")
    p_flight.add_argument("--port", type=int, default=32010)

    sub.add_parser(
        "mcp-server",
        help="run the MCP (Model Context Protocol) server over stdio "
             "(reference: sail spark mcp-server)")

    p_compat = sub.add_parser(
        "compat",
        help="scan Python files for PySpark API usage and report this "
             "engine's support status (reference: pysail compatibility "
             "check)")
    p_compat.add_argument("paths", nargs="+",
                          help="Python files or directories to scan")

    p_worker = sub.add_parser(
        "worker", help="run a standalone cluster worker process")
    p_worker.add_argument("--driver", required=True,
                          help="host:port of the driver control plane")
    p_worker.add_argument("--host", default="127.0.0.1",
                          help="address to bind")
    p_worker.add_argument("--advertise-host", default=None,
                          help="address the driver/peers dial (defaults to "
                               "--host; set to the pod IP when binding "
                               "0.0.0.0)")
    p_worker.add_argument("--task-slots", type=int, default=2)
    p_worker.add_argument("--worker-id", default=None)

    args = parser.parse_args(argv)
    if args.command in ("server", "shell", "flight", "worker",
                        "mcp-server", "compat"):
        _ensure_backend()

    if args.command == "compat":
        from .compat import check_paths, format_report
        print(format_report(check_paths(args.paths)))
        return 0

    if args.command == "mcp-server":
        from .mcp_server import McpSparkServer
        McpSparkServer().serve()
        return 0

    if args.command == "server":
        from .spark_connect import SparkConnectServer
        server = SparkConnectServer(args.host, args.port).start()
        print(f"sail-tpu Spark Connect server listening on "
              f"sc://{args.host}:{server.port}")
        sql_server = None
        try:
            if args.sql_port:
                from .server import SqlServer
                sql_server = SqlServer(args.host, args.sql_port).start()
                print(f"sail-tpu native SQL server listening on "
                      f"{args.host}:{sql_server.port}")
            server.wait()
        except KeyboardInterrupt:
            pass
        finally:
            server.stop()
            if sql_server is not None:
                sql_server.stop()
        return 0

    if args.command == "shell":
        return _shell(args.remote)

    if args.command == "bench":
        import subprocess
        bench = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py")
        return subprocess.call([sys.executable, bench, str(args.sf)])

    if args.command == "flight":
        from .flight_sql import FlightSqlServer
        server = FlightSqlServer(args.host, args.port)
        print(f"sail-tpu Flight SQL server listening on "
              f"grpc://{args.host}:{server.port}")
        try:
            server.serve()
        except KeyboardInterrupt:
            pass
        finally:
            server.shutdown()
        return 0

    if args.command == "worker":
        import uuid as _uuid
        from .exec.cluster import WorkerActor
        worker_id = args.worker_id or f"worker-{_uuid.uuid4().hex[:8]}"
        w = WorkerActor(worker_id, args.driver, args.task_slots,
                        host=args.host,
                        advertise_host=(args.advertise_host or
                                        os.environ.get("SAIL_POD_IP")))
        w.start(worker_id)
        print(f"sail-tpu worker {worker_id} registered with {args.driver}")
        try:
            import time as _time
            while True:
                _time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            w.stop()
        return 0

    return 1


def _shell(remote):
    if remote:
        # the server speaks Spark Connect; the shell does too
        from .spark_connect.client import SparkConnectClient
        client = SparkConnectClient(remote)
        run = client.sql
    else:
        from . import SparkSession
        spark = SparkSession.builder.getOrCreate()
        run = lambda q: spark.sql(q).toArrow()  # noqa: E731
    print("sail-tpu SQL shell — ';' to run, 'exit' to quit")
    buf = []
    while True:
        try:
            prompt = "sql> " if not buf else "...> "
            line = input(prompt)
        except (EOFError, KeyboardInterrupt):
            print()
            return 0
        if line.strip().lower() in ("exit", "quit"):
            return 0
        buf.append(line)
        if line.rstrip().endswith(";"):
            query = "\n".join(buf).rstrip().rstrip(";")
            buf = []
            try:
                table = run(query)
                print(table.to_pandas().to_string(index=False, max_rows=50))
            except Exception as e:  # noqa: BLE001 — REPL surfaces all errors
                print(f"error: {e}")


if __name__ == "__main__":
    sys.exit(main())
