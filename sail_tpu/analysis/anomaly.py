"""Tail-latency forensics: per-fingerprint latency baselines, anomaly
verdicts, and tenant SLO burn-rate monitoring.

Three cooperating pieces, all deterministic and replayable from the
durable flight-recorder log alone:

- :class:`BaselineStore` — a bounded LRU of per-plan-fingerprint
  latency summaries. Each entry is a mergeable histogram state plus a
  handful of counters (compile ms, spill bytes, cache hits) — never raw
  samples, so memory is O(fingerprints × bounds) regardless of query
  volume.
- :func:`classify` — a PURE function from (query inputs, the query's
  events, the fingerprint's baseline snapshot) to an anomaly record.
  A completed query whose latency exceeds ``outlier_factor`` × the
  baseline p50 gets a ranked verdict naming WHERE the excess went:
  the query's own flight-recorder events are folded into per-category
  wait evidence (``timeline.wait_evidence``) and the largest
  contributor above ``min_evidence_ms`` wins; flag-style causes with
  no duration of their own (spill, cache invalidation, governor defer)
  break the tie, and ``unexplained`` is the honest fallback. Because
  the classifier sees only event-derived inputs, replaying the durable
  log (:func:`replay_verdicts`) reproduces the live ring bit for bit.
- :class:`SloMonitor` — per-tenant SLO burn rates over fast/slow
  windows, computed from timestamped snapshots of the fleet-merged
  ``query.latency`` histograms: ``burn = fraction_above(target) /
  (1 - objective)`` on the window delta (``HistogramState.subtract``),
  the standard multi-window multi-burn-rate alerting shape. Pull-based
  and side-effect-free apart from gauge recording, so ops endpoints,
  Prometheus scrapes, and system tables all read the same numbers.

Ordering contract: the profiler classifies a query BEFORE observing it
into the baseline (an outlier must not dilute the baseline it is judged
against) and before emitting ``query_end`` — so the durable log carries
the classifier's exact inputs ahead of the verdict it implies.
"""

from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from .. import config as app_config
from .. import events
from .. import metrics
from . import timeline

#: Verdict tie-break order: when two evidence categories carry the same
#: wait time, the earlier one here wins. The leading entries are the
#: duration-bearing categories (largest-ms wins before order matters);
#: the trailing three are flags with no duration of their own.
#: ``unexplained`` is deliberately absent — it is the fallback, never
#: evidence. Every entry must appear in events.VERDICT_CATEGORIES
#: (lint: slo-taxonomy).
EVIDENCE_ORDER: Tuple[str, ...] = (
    "retrace",
    "credit-stall",
    "admission-queue-wait",
    "fetch-wait",
    "spill",
    "cache-invalidation",
    "governor-defer",
)

#: categories that carry no duration — they win only when nothing
#: duration-bearing clears ``min_evidence_ms``
_FLAG_CATEGORIES = ("spill", "cache-invalidation", "governor-defer")

#: Baseline latency bounds in MILLISECONDS: 0.5ms × 1.25^i for 64
#: buckets (~0.5ms … ~640s). The 1.25 growth bounds the in-bucket p50
#: interpolation error to ≲12.5%, tight enough that a 2× outlier factor
#: never mistakes bucket resolution for a regression.
BASELINE_BOUNDS: Tuple[float, ...] = tuple(
    round(0.5 * 1.25 ** i, 6) for i in range(64))


def _conf() -> Dict[str, Any]:
    """Anomaly-detection knobs (telemetry.anomaly.* in
    application.yaml, SAIL_TELEMETRY__ANOMALY__* env). Read per call —
    config layers env on every read, so tests and the bench A/B knob
    can flip detection without a reload hook."""
    g = app_config.get
    return {
        "enabled": app_config.truthy("telemetry.anomaly.enabled"),
        "min_samples": int(g("telemetry.anomaly.min_samples", 5)),
        "outlier_factor": float(
            g("telemetry.anomaly.outlier_factor", 2.0)),
        "min_excess_ms": float(
            g("telemetry.anomaly.min_excess_ms", 20.0)),
        "min_evidence_ms": float(
            g("telemetry.anomaly.min_evidence_ms", 5.0)),
        "ring_capacity": int(
            g("telemetry.anomaly.ring_capacity", 256)),
        "baseline_capacity": int(
            g("telemetry.anomaly.baseline_capacity", 512)),
    }


# ---------------------------------------------------------------------------
# latency baselines — bounded per-fingerprint summaries
# ---------------------------------------------------------------------------

class _Baseline:
    """One fingerprint's summary: histogram of total latency (ms) plus
    additive counters. Everything here is derivable from the durable
    event log (``query_end`` + ``retrace`` records), which is what
    makes :func:`replay_verdicts` exact."""

    __slots__ = ("latency", "count", "compile_ms", "spill_bytes",
                 "cache_hits")

    def __init__(self) -> None:
        self.latency = metrics.HistogramState(BASELINE_BOUNDS)
        self.count = 0
        self.compile_ms = 0.0
        self.spill_bytes = 0
        self.cache_hits = 0


class BaselineStore:
    """Bounded LRU of per-fingerprint baselines. ``snapshot_for`` and
    ``observe`` are separate so the profiler can classify against the
    pre-query state and only then fold the query in."""

    def __init__(self, capacity: int = 512):
        self.capacity = max(1, int(capacity))
        self._entries: "OrderedDict[str, _Baseline]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot_for(self, fingerprint: str) -> Optional[Dict[str, Any]]:
        """The classifier's view of one fingerprint: sample count, the
        p50 estimate, and the historical cache-hit ratio (feeds the
        cache-invalidation flag). None when the fingerprint is new."""
        with self._lock:
            e = self._entries.get(fingerprint)
            if e is None:
                return None
            self._entries.move_to_end(fingerprint)
            p50 = e.latency.quantile(0.5)
            return {
                "count": e.count,
                "p50_ms": None if p50 is None else p50,
                "hit_ratio": (e.cache_hits / e.count) if e.count else 0.0,
            }

    def observe(self, inputs: Dict[str, Any],
                evs: List[dict]) -> None:
        """Fold one completed query into its fingerprint's baseline."""
        fp = inputs.get("fingerprint") or ""
        if not fp:
            return
        with self._lock:
            e = self._entries.get(fp)
            if e is None:
                e = self._entries[fp] = _Baseline()
                while len(self._entries) > self.capacity:
                    self._entries.popitem(last=False)
            self._entries.move_to_end(fp)
            e.latency.observe(float(inputs.get("total_ms", 0.0)))
            e.count += 1
            # compile cost INCLUDING the benign first-ever compile —
            # the baseline tracks total spend, the verdict evidence
            # (wait_evidence) excludes first-ever separately
            for ev in evs:
                if ev.get("type") == "retrace":
                    e.compile_ms += float(ev.get("ms", 0.0) or 0.0)
            e.spill_bytes += int(inputs.get("spill_bytes", 0) or 0)
            if inputs.get("cache_status") in ("hit", "view"):
                e.cache_hits += 1

    def p99_for(self, fingerprint: str
                ) -> Optional[Tuple[int, float]]:
        """(count, p99_ms) of one fingerprint's latency baseline — the
        read-only view the backend router's SLO feedback loop consumes
        (exec/router.py). Never touches LRU recency: a routing consult
        must not keep a fingerprint alive."""
        with self._lock:
            e = self._entries.get(fingerprint)
            if e is None:
                return None
            p99 = e.latency.quantile(0.99)
            if p99 is None:
                return None
            return e.count, float(p99)

    def snapshot(self) -> List[dict]:
        """Rows for system.telemetry / debugging: one per fingerprint."""
        with self._lock:
            rows = []
            for fp, e in self._entries.items():
                rows.append({
                    "fingerprint": fp,
                    "count": e.count,
                    "p50_ms": e.latency.quantile(0.5),
                    "p99_ms": e.latency.quantile(0.99),
                    "compile_ms": round(e.compile_ms, 3),
                    "spill_bytes": e.spill_bytes,
                    "cache_hits": e.cache_hits,
                })
            return rows

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


# ---------------------------------------------------------------------------
# the classifier — pure function of event-derived inputs
# ---------------------------------------------------------------------------

def classify(inputs: Dict[str, Any], evs: List[dict],
             baseline: Optional[Dict[str, Any]],
             conf: Optional[Dict[str, Any]] = None) -> Optional[dict]:
    """Anomaly verdict for one completed query, or None when the query
    is not an outlier (no baseline yet, too few samples, or within
    ``outlier_factor`` × p50 + ``min_excess_ms``).

    ``inputs`` carries exactly what a ``query_end`` event does —
    query_id, trace_id, fingerprint, total_ms, spill_bytes,
    cache_status — so live classification and durable-log replay see
    identical values. ``evs`` is the query's own event slice. The
    returned record contains no wall-clock timestamps: it must be bit-
    identical between the live ring and a replay of the same log.
    """
    if conf is None:
        conf = _conf()
    if baseline is None or baseline["count"] < conf["min_samples"]:
        return None
    p50 = baseline.get("p50_ms")
    if p50 is None or p50 <= 0.0:
        return None
    total_ms = float(inputs.get("total_ms", 0.0))
    if total_ms < p50 * conf["outlier_factor"]:
        return None
    p50_r = round(p50, 3)
    excess = round(total_ms - p50_r, 3)
    if excess < conf["min_excess_ms"]:
        return None

    wait = timeline.wait_evidence(evs)
    causes: Dict[str, int] = {}
    for ev in evs:
        if ev.get("type") == "retrace" and \
                ev.get("cause") != "first-ever":
            c = str(ev.get("cause", ""))
            causes[c] = causes.get(c, 0) + 1

    candidates: List[dict] = []
    for cat in ("retrace", "credit-stall", "admission-queue-wait",
                "fetch-wait"):
        d = wait[cat]
        if d["events"]:
            entry = {"category": cat, "ms": d["ms"],
                     "events": d["events"]}
            if cat == "retrace":
                entry["causes"] = {k: causes[k] for k in sorted(causes)}
            candidates.append(entry)
    if int(inputs.get("spill_bytes", 0) or 0) > 0:
        candidates.append({"category": "spill", "ms": 0.0, "events": 1,
                           "bytes": int(inputs["spill_bytes"])})
    if inputs.get("cache_status") == "miss" and \
            baseline.get("hit_ratio", 0.0) >= 0.5:
        # this fingerprint usually serves from cache; a miss on an
        # outlier run points at an invalidation paying full price
        candidates.append({"category": "cache-invalidation",
                           "ms": 0.0, "events": 1})
    if wait["governor-defer"]["events"]:
        candidates.append({"category": "governor-defer", "ms": 0.0,
                           "events": wait["governor-defer"]["events"]})

    order = {c: i for i, c in enumerate(EVIDENCE_ORDER)}
    candidates.sort(key=lambda c: (-c["ms"],
                                   order.get(c["category"], 99)))
    verdict = "unexplained"
    for c in candidates:
        if c["ms"] >= conf["min_evidence_ms"] or \
                c["category"] in _FLAG_CATEGORIES:
            verdict = c["category"]
            break
    return {
        "query_id": inputs.get("query_id") or "",
        "trace_id": inputs.get("trace_id") or "",
        "fingerprint": inputs.get("fingerprint") or "",
        "total_ms": round(total_ms, 3),
        "baseline_p50_ms": p50_r,
        "excess_ms": excess,
        "verdict": verdict,
        "evidence": candidates,
    }


# ---------------------------------------------------------------------------
# live wiring — ring, profiler hook, EXPLAIN preview
# ---------------------------------------------------------------------------

BASELINES = BaselineStore(capacity=_conf()["baseline_capacity"])

#: bounded ring of anomaly records, newest last (system table +
#: bench assertions read this)
_ANOMALIES: "deque[dict]" = deque(maxlen=_conf()["ring_capacity"])

#: serializes classify→observe so concurrent finalizes cannot
#: interleave between a query's classification and its baseline fold
_LOCK = threading.Lock()


def _inputs_from_profile(profile) -> Dict[str, Any]:
    return {
        "query_id": profile.query_id,
        "trace_id": profile.trace_id or "",
        "fingerprint": profile.plan_fingerprint,
        "total_ms": round(profile.total_ms, 3),
        "spill_bytes": profile.spill_bytes,
        "cache_status": profile.cache_status,
    }


def _cut_at_query_end(evs: List[dict]) -> List[dict]:
    """Everything before the query's ``query_end`` record: the exact
    evidence set a durable-log replay reconstructs, regardless of
    worker events racing in after finalize."""
    for i in range(len(evs) - 1, -1, -1):
        if evs[i].get("type") == "query_end":
            return evs[:i]
    return evs


def on_profile_complete(profile) -> None:
    """Profiler finalize hook: classify the completed query against its
    fingerprint baseline, land any verdict in the ring + durable log,
    THEN fold the query into the baseline. Called right after
    ``query_end`` is emitted — the classifier cuts the event stream at
    that record so live evidence equals replayed evidence."""
    conf = _conf()
    if not conf["enabled"]:
        return
    if profile.status != "succeeded" or not profile.plan_fingerprint:
        return
    inputs = _inputs_from_profile(profile)
    evs = _cut_at_query_end(events.events(query_id=profile.query_id))
    with _LOCK:
        rec = classify(inputs, evs,
                       BASELINES.snapshot_for(profile.plan_fingerprint),
                       conf)
        if rec is not None:
            profile.anomaly_verdict = rec["verdict"]
            profile.anomaly_excess_ms = rec["excess_ms"]
            _ANOMALIES.append(rec)
            try:
                events.emit(
                    events.EventType.ANOMALY,
                    query_id=profile.query_id,
                    trace_id=profile.trace_id,
                    fingerprint=rec["fingerprint"],
                    verdict=rec["verdict"],
                    excess_ms=rec["excess_ms"],
                    detail=json.dumps(rec, sort_keys=True,
                                      separators=(",", ":")))
            except Exception:  # noqa: BLE001 — log full/closed
                pass
        BASELINES.observe(inputs, evs)


def preview(profile) -> None:
    """Classify-only peek for EXPLAIN ANALYZE: stamps the verdict on
    the profile so the rendered/JSON plan carries it, WITHOUT touching
    the ring or the baseline — finalize does the real pass against the
    same pre-query baseline state."""
    conf = _conf()
    if not conf["enabled"] or not profile.plan_fingerprint:
        return
    inputs = _inputs_from_profile(profile)
    evs = events.events(query_id=profile.query_id)
    with _LOCK:
        rec = classify(inputs, evs,
                       BASELINES.snapshot_for(profile.plan_fingerprint),
                       conf)
    if rec is not None:
        profile.anomaly_verdict = rec["verdict"]
        profile.anomaly_excess_ms = rec["excess_ms"]


def anomalies() -> List[dict]:
    """Snapshot of the live anomaly ring, oldest first."""
    with _LOCK:
        return list(_ANOMALIES)


def reset() -> None:
    """Drop all baselines, verdicts, and SLO snapshots (tests/bench)."""
    with _LOCK:
        BASELINES.clear()
        _ANOMALIES.clear()
    SLO_MONITOR.reset()


# ---------------------------------------------------------------------------
# durable-log replay — verdicts from the log alone
# ---------------------------------------------------------------------------

def replay_verdicts(records: List[dict],
                    conf: Optional[Dict[str, Any]] = None) -> List[dict]:
    """Re-derive every anomaly verdict from a durable event log,
    bit-identical to what the live ring held: walk the records in file
    order, accumulate each query's events, and on its ``query_end``
    run the same classify→observe sequence against a fresh baseline
    store. Prior ``anomaly`` records in the log are ignored — they are
    the OUTPUT being reproduced, never input."""
    if conf is None:
        conf = _conf()
    store = BaselineStore(capacity=conf["baseline_capacity"])
    by_query: Dict[str, List[dict]] = {}
    out: List[dict] = []
    for rec in records:
        t = rec.get("type")
        if t == "anomaly":
            continue
        qid = rec.get("query_id") or ""
        if qid:
            by_query.setdefault(qid, []).append(rec)
        if t != "query_end":
            continue
        if rec.get("status") != "succeeded":
            by_query.pop(qid, None)
            continue
        fp = rec.get("fingerprint") or ""
        if not fp:
            by_query.pop(qid, None)
            continue
        inputs = {
            "query_id": qid,
            "trace_id": rec.get("trace_id") or "",
            "fingerprint": fp,
            "total_ms": float(rec.get("total_ms", 0.0) or 0.0),
            "spill_bytes": int(rec.get("spill_bytes", 0) or 0),
            "cache_status": rec.get("cache_status") or "",
        }
        evs = by_query.pop(qid, [])
        verdict = classify(inputs, evs, store.snapshot_for(fp), conf)
        if verdict is not None:
            out.append(verdict)
        store.observe(inputs, evs)
    return out


# ---------------------------------------------------------------------------
# SLO burn-rate monitor — multi-window, pull-based, deterministic
# ---------------------------------------------------------------------------

def _slo_conf() -> Dict[str, Any]:
    g = app_config.get
    conf: Dict[str, Any] = {
        "enabled": app_config.truthy("slo.enabled"),
        "target_ms": float(g("slo.target_ms", 1000.0)),
        "objective": float(g("slo.objective", 0.99)),
        "fast_window_s": float(g("slo.fast_window_s", 300.0)),
        "slow_window_s": float(g("slo.slow_window_s", 3600.0)),
        "tenants": {},
    }
    # slo.tenants.<name>.{target_ms,objective} from the flattened tree
    prefix = "slo.tenants."
    for key, value in app_config.app_config().items():
        if not key.startswith(prefix):
            continue
        rest = key[len(prefix):]
        tenant, _, field = rest.rpartition(".")
        if not tenant or field not in ("target_ms", "objective"):
            continue
        conf["tenants"].setdefault(tenant, {})[field] = float(value)
    return conf


class SloMonitor:
    """Per-tenant SLO burn rates over fast/slow windows.

    Each :meth:`evaluate` call snapshots the fleet-merged
    ``query.latency`` (phase=total) histogram per tenant, computes the
    windowed delta against the snapshot taken at/just before the window
    start, and reports ``fraction_above(target) / (1 - objective)`` —
    1.0 means the error budget burns exactly at the sustainable rate;
    a fast-window burn ≫ 1 alongside a slow-window burn > 1 is the
    page-worthy shape. ``now`` is injectable so tests drive window
    math against exact sample sets."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (ts, {tenant: HistogramState}) snapshots, oldest first
        self._snapshots: "deque[Tuple[float, Dict[str, object]]]" = \
            deque()
        #: rows of the LAST evaluate() call — the recorded state the
        #: router's SLO feedback reads (it never triggers a snapshot)
        self._last_rows: List[dict] = []
        #: explicit per-tenant overrides (session spark.sail.slo.*),
        #: winning over slo.tenants.* config, winning over the global
        #: target/objective
        self._objectives: Dict[str, Dict[str, float]] = {}

    def set_objective(self, tenant: str,
                      target_ms: Optional[float] = None,
                      objective: Optional[float] = None) -> None:
        with self._lock:
            cur = self._objectives.setdefault(str(tenant), {})
            if target_ms is not None:
                cur["target_ms"] = float(target_ms)
            if objective is not None:
                cur["objective"] = float(objective)

    def objective_for(self, tenant: str,
                      conf: Optional[Dict[str, Any]] = None
                      ) -> Tuple[float, float]:
        """(target_ms, objective) for one tenant after layering."""
        if conf is None:
            conf = _slo_conf()
        target = conf["target_ms"]
        objective = conf["objective"]
        layered = conf["tenants"].get(tenant, {})
        with self._lock:
            explicit = dict(self._objectives.get(tenant, {}))
        for src in (layered, explicit):
            if "target_ms" in src:
                target = float(src["target_ms"])
            if "objective" in src:
                objective = float(src["objective"])
        return target, min(0.999999, max(0.0, objective))

    def _merged_latency(self) -> Dict[str, object]:
        merged: Dict[str, object] = {}
        for _w, attrs, h in metrics.FLEET.histogram_states(
                "query.latency"):
            if attrs.get("phase") != "total":
                continue
            tenant = attrs.get("tenant", "default")
            cur = merged.get(tenant)
            if cur is None:
                merged[tenant] = h
            else:
                cur.merge(h)
        return merged

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """Take a snapshot and return burn-rate rows (one per tenant ×
        window), recording ``cluster.slo.burn_rate`` gauges as a side
        effect. Disabled → no snapshot, no rows."""
        conf = _slo_conf()
        if not conf["enabled"]:
            return []
        if now is None:
            now = time.time()
        merged = self._merged_latency()
        windows = (("fast", conf["fast_window_s"]),
                   ("slow", conf["slow_window_s"]))
        with self._lock:
            self._snapshots.append(
                (now, {t: h.copy() for t, h in merged.items()}))
            # keep one snapshot at/before the slow-window start so the
            # slow delta always has an anchor; drop anything older
            horizon = now - conf["slow_window_s"]
            while len(self._snapshots) >= 2 and \
                    self._snapshots[1][0] <= horizon:
                self._snapshots.popleft()
            snaps = list(self._snapshots)
        rows: List[dict] = []
        for tenant in sorted(merged):
            cur = merged[tenant]
            target_ms, objective = self.objective_for(tenant, conf)
            threshold_s = target_ms / 1000.0  # query.latency unit: s
            for window, span in windows:
                anchor = None
                for ts, states in snaps:
                    if ts <= now - span:
                        anchor = states.get(tenant) or anchor
                    else:
                        break
                delta = cur.subtract(anchor) if anchor is not None \
                    else cur.copy()
                frac = delta.fraction_above(threshold_s)
                burn = frac / (1.0 - objective)
                metrics.record("cluster.slo.burn_rate", burn,
                               tenant=tenant, window=window)
                rows.append({
                    "tenant": tenant,
                    "window": window,
                    "window_s": span,
                    "target_ms": target_ms,
                    "objective": objective,
                    "queries": delta.count,
                    "fraction_above": round(frac, 6),
                    "burn_rate": round(burn, 6),
                })
        with self._lock:
            self._last_rows = list(rows)
        return rows

    def burn_for(self, tenant: str) -> Optional[float]:
        """The tenant's worst burn rate across windows from the LAST
        :meth:`evaluate` — recorded state, so a router decision made
        from it is a pure function of its inputs and replays
        identically. None until an evaluation has covered the
        tenant."""
        with self._lock:
            burns = [r["burn_rate"] for r in self._last_rows
                     if r["tenant"] == tenant]
        return max(burns) if burns else None

    def reset(self) -> None:
        with self._lock:
            self._snapshots.clear()
            self._objectives.clear()
            self._last_rows = []


SLO_MONITOR = SloMonitor()
