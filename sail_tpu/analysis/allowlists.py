"""Explicit allowlists for the repo-wide drift lints.

Etiquette: an entry here is a *reviewed exception*, not an escape hatch.
Every entry carries a reason; add one only when the lint's rule is
genuinely inapplicable (a config key read through a dynamically-built
name, a host sync that is architecturally required), never to silence a
finding you haven't understood. ``scripts/sail_lint.py --fix-allowlist``
prints ready-to-paste stubs for new violations — edit the reason before
committing.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# sync-point lint: jax.device_get / block_until_ready call sites in
# exec/ and ops/ force a host<->device round trip. Each allowed site is
# (path relative to the repo root, qualified function name). A new sync
# point anywhere else fails the lint until it is reviewed: hot paths
# must not silently grow host syncs.
# ---------------------------------------------------------------------------

SYNC_POINTS = {
    # host-dictionary sort fallback needs the live selection mask
    ("sail_tpu/exec/local.py", "LocalExecutor._sort_host_fallback"),
    # group-count + overflow check sizes the aggregate output capacity
    # (two sites: fused-chain count, plain count)
    ("sail_tpu/exec/local.py", "LocalExecutor._agg_with_chain"),
    # runtime-filter build: ONE batched fetch of n/ndv/bounds/values
    ("sail_tpu/exec/local.py", "LocalExecutor._rtf_prepare"),
    # join phase results ride one batched fetch (counts + prune stats)
    ("sail_tpu/exec/local.py", "LocalExecutor._join"),
    # spill decision needs both sides' live row counts (one round trip)
    ("sail_tpu/exec/local.py", "LocalExecutor._try_partitioned_join"),
    # external-sort decision needs the input's live row count
    ("sail_tpu/exec/local.py", "LocalExecutor._try_external_sort"),
    # cross-join capacity sizing needs both side counts
    ("sail_tpu/exec/local.py", "LocalExecutor._cross_join"),
}

# ---------------------------------------------------------------------------
# capacity-policy lint: direct ``round_capacity`` calls bypass the
# pinned grow-only bucket registry (exec/capacity.py). The reviewed
# exceptions are the policy helper itself and the registry's raw
# rounding — everything else sizes through
# ``columnar.batch.bucket_capacity``.
# ---------------------------------------------------------------------------

CAPACITY_POLICY = {
    # THE policy helper: its keyless fallback is the raw rounding
    ("sail_tpu/columnar/batch.py", "bucket_capacity"),
    # the registry computes the raw bucket a pin starts from / grows to
    ("sail_tpu/exec/capacity.py", "BucketRegistry.bucket_for"),
}

# ---------------------------------------------------------------------------
# config-key lint: keys declared in application.yaml whose read sites
# build the key dynamically (the AST scanner cannot see them), plus
# prefixes that are read through f-strings / layering machinery. A
# prefix entry must end with ".".
# ---------------------------------------------------------------------------

CONFIG_DYNAMIC_KEYS = {
    # catalog.<name>.<field> keys are composed per configured catalog
    # (catalog/manager.py f-strings); catalog.list/default read literally
    "catalog.": "per-catalog keys read via f-strings in catalog/manager.py",
    # spark.* yaml keys layer wholesale into SessionConf defaults
    # (session.py: `for key ... if key.startswith("spark.")`)
    "spark.": "layered into SessionConf defaults, never read one-by-one",
}

# non-dotted top-level keys are outside the lint's grammar (a bare word
# matches too many unrelated literals to check mechanically)
CONFIG_SKIP_KEYS = {"mode"}

# ---------------------------------------------------------------------------
# metrics lint: metrics recorded with dynamically-built attribute dicts
# (record(name, value, **attrs)) — the static attribute-set check cannot
# see the keys, the runtime registry still validates them.
# ---------------------------------------------------------------------------

METRIC_DYNAMIC_ATTRS: set = set()
