"""Explicit allowlists for the repo-wide drift lints.

Etiquette: an entry here is a *reviewed exception*, not an escape hatch.
Every entry carries a reason; add one only when the lint's rule is
genuinely inapplicable (a config key read through a dynamically-built
name, a host sync that is architecturally required), never to silence a
finding you haven't understood. ``scripts/sail_lint.py --fix-allowlist``
prints ready-to-paste stubs for new violations — edit the reason before
committing.
"""

from __future__ import annotations

# ---------------------------------------------------------------------------
# sync-point lint: jax.device_get / block_until_ready call sites in
# exec/ and ops/ force a host<->device round trip. Each allowed site is
# (path relative to the repo root, qualified function name). A new sync
# point anywhere else fails the lint until it is reviewed: hot paths
# must not silently grow host syncs.
# ---------------------------------------------------------------------------

SYNC_POINTS = {
    # host-dictionary sort fallback needs the live selection mask
    ("sail_tpu/exec/local.py", "LocalExecutor._sort_host_fallback"),
    # group-count + overflow check sizes the aggregate output capacity
    # (two sites: fused-chain count, plain count)
    ("sail_tpu/exec/local.py", "LocalExecutor._agg_with_chain"),
    # runtime-filter build: ONE batched fetch of n/ndv/bounds/values
    ("sail_tpu/exec/local.py", "LocalExecutor._rtf_prepare"),
    # join phase results ride one batched fetch (counts + prune stats)
    ("sail_tpu/exec/local.py", "LocalExecutor._join"),
    # spill decision needs both sides' live row counts (one round trip)
    ("sail_tpu/exec/local.py", "LocalExecutor._try_partitioned_join"),
    # external-sort decision needs the input's live row count
    ("sail_tpu/exec/local.py", "LocalExecutor._try_external_sort"),
    # cross-join capacity sizing needs both side counts
    ("sail_tpu/exec/local.py", "LocalExecutor._cross_join"),
    # mesh program epilogue: ONE batched fetch of the retry/fatal
    # overflow flags decides recompile-vs-fail before results ship
    ("sail_tpu/parallel/mesh_exec.py", "MeshExecutor._run_program"),
    # leaf ingest re-partitions on the host: one batched fetch of
    # sel + every column, then shards upload per mesh partition
    ("sail_tpu/parallel/mesh_exec.py", "MeshExecutor._prepare_leaf"),
    # output assembly: one batched fetch, arrow built from host
    # buffers with no device re-upload
    ("sail_tpu/parallel/mesh_exec.py", "MeshExecutor._assemble"),
    # arrow egress materializes by contract; one batched fetch of
    # sel + data + validity (per-column loops would be O(cols) RTTs)
    ("sail_tpu/columnar/arrow_interop.py", "to_arrow"),
}

# ---------------------------------------------------------------------------
# capacity-policy lint: direct ``round_capacity`` calls bypass the
# pinned grow-only bucket registry (exec/capacity.py). The reviewed
# exceptions are the policy helper itself and the registry's raw
# rounding — everything else sizes through
# ``columnar.batch.bucket_capacity``.
# ---------------------------------------------------------------------------

CAPACITY_POLICY = {
    # THE policy helper: its keyless fallback is the raw rounding
    ("sail_tpu/columnar/batch.py", "bucket_capacity"),
    # the registry computes the raw bucket a pin starts from / grows to
    ("sail_tpu/exec/capacity.py", "BucketRegistry.bucket_for"),
}

# ---------------------------------------------------------------------------
# config-key lint: keys declared in application.yaml whose read sites
# build the key dynamically (the AST scanner cannot see them), plus
# prefixes that are read through f-strings / layering machinery. A
# prefix entry must end with ".".
# ---------------------------------------------------------------------------

CONFIG_DYNAMIC_KEYS = {
    # catalog.<name>.<field> keys are composed per configured catalog
    # (catalog/manager.py f-strings); catalog.list/default read literally
    "catalog.": "per-catalog keys read via f-strings in catalog/manager.py",
    # spark.* yaml keys layer wholesale into SessionConf defaults
    # (session.py: `for key ... if key.startswith("spark.")`)
    "spark.": "layered into SessionConf defaults, never read one-by-one",
}

# non-dotted top-level keys are outside the lint's grammar (a bare word
# matches too many unrelated literals to check mechanically)
CONFIG_SKIP_KEYS = {"mode"}

# ---------------------------------------------------------------------------
# metrics lint: metrics recorded with dynamically-built attribute dicts
# (record(name, value, **attrs)) — the static attribute-set check cannot
# see the keys, the runtime registry still validates them.
# ---------------------------------------------------------------------------

METRIC_DYNAMIC_ATTRS: set = set()

# ---------------------------------------------------------------------------
# guarded-fields lint (analysis/concurrency.py): reviewed lock-free
# accesses to an inferred lock-guarded attribute. Each entry is
# (relpath, "Class.attr", "Class.method…") with the reason above it.
# Prefer a `# guarded-by: <lock>` annotation when the contract is
# "every caller holds the lock"; use an entry here only for deliberate
# racy reads (monitoring snapshots, shutdown fast paths).
# ---------------------------------------------------------------------------

GUARDED_FIELDS: set = {
    # deliberate racy queue-depth snapshots feeding telemetry only
    # (enqueue metric / shed event): the admission decision itself was
    # already taken under the lock, and a stale depth label is
    # preferable to re-taking the gate lock on every metric emit
    ("sail_tpu/exec/admission.py", "SessionAdmission._waiters",
     "SessionAdmission.acquire"),
}

# ---------------------------------------------------------------------------
# actor-confinement lint: reviewed cross-thread mutations of
# actor-confined state, (relpath, "Class.attr", "Class.method…").
# The bar is high: the default fix is routing through
# ``self.handle.send`` so the mutation happens on the mailbox thread.
# ---------------------------------------------------------------------------

ACTOR_CROSS_THREAD: set = set()

# ---------------------------------------------------------------------------
# decision-purity lint: reviewed impurities in the pure decision
# functions, keyed (relpath, decision function, category) where
# category ∈ {clock, random, id, config, set-iteration}. Every entry
# MUST carry a one-line reason: why replay still converges.
# ---------------------------------------------------------------------------

DECISION_PURITY: dict = {
    # the four AQE rewrite decisions read their thresholds
    # (adaptive.broadcast.*, adaptive.coalesce.*, adaptive.skew.*,
    # adaptive.reorder.enabled) through the session conf, which is
    # immutable for a query's lifetime; each rewrite event records the
    # observed byte sizes that drove it, so replay under the same
    # session conf reproduces the decision bit-identically
    ("sail_tpu/exec/adaptive.py", "plan_graph", "config"):
        "session-conf thresholds are frozen per query; observed sizes "
        "ride the rewrite event",
    ("sail_tpu/exec/adaptive.py", "_maybe_broadcast", "config"):
        "session-conf thresholds are frozen per query; observed sizes "
        "ride the rewrite event",
    ("sail_tpu/exec/adaptive.py", "_maybe_coalesce_split", "config"):
        "session-conf thresholds are frozen per query; observed sizes "
        "ride the rewrite event",
    ("sail_tpu/exec/adaptive.py", "_maybe_reorder", "config"):
        "session-conf thresholds are frozen per query; observed sizes "
        "ride the rewrite event",
}
