"""Repo-wide AST drift lints.

Every declared-vs-used surface in the repo is checked both ways, so
declarations cannot drift from the code (the pattern
``tests/test_registry_drift.py`` proved out for metrics, generalized):

==================  ======================================================
lint id             checks
==================  ======================================================
``config-keys``     every app-config key read in code is declared in
                    ``config/application.yaml`` — and every declared key
                    is read somewhere (or allowlisted as dynamic)
``spark-keys``      every ``spark.sail.*`` session-conf literal in code
                    is documented in ``application.yaml`` (exact or via
                    a ``prefix.`` mention)
``fault-sites``     every ``faults.inject(site)`` literal is documented
                    in the README site table, and vice versa
``proto``           every message/field name in ``*.proto`` exists in
                    the checked-in regenerated ``*_pb2.py``
``sync-points``     ``device_get``/``block_until_ready`` call sites in
                    ``exec/``/``ops/``/``plan/``/``native/``/
                    ``parallel/``/``columnar/`` are on the reviewed
                    allowlist
``locks``           ``exec/cluster.py`` slice of the concurrency passes
                    (guarded-field inference + actor confinement) — the
                    historical hardcoded ``_running`` check, generalized
``guarded-fields``  per-class lock-guarded attribute inference across
                    the cluster runtime: any touch outside ``with
                    self.<lock>`` (or a ``# guarded-by:`` contract)
                    fails (analysis/concurrency.py)
``lock-order``      the acquires-while-holding graph over every
                    ``threading.Lock/RLock/Condition`` site is acyclic;
                    ``sail_lint --graph`` renders the ordering
``actor-confinement``  DriverActor/WorkerActor state in the confinement
                    table only mutates from methods reachable off the
                    mailbox entry points (call-graph aware)
``decision-purity`` the pure decision functions (autoscaler, AQE,
                    admission DRR, anomaly, router.decide_*) are closed
                    over recorded signals: no clocks/random/id()/
                    unordered-set iteration/config re-reads
``metrics``         every recorded metric is declared with the recorded
                    attribute keys, every declaration is exercised
==================  ======================================================

Run via ``scripts/sail_lint.py`` (``--fix-allowlist`` prints allowlist
stubs for new violations) or as tier-1 tests (``tests/test_lints.py``).
All lints operate on a :class:`LintContext` rooted anywhere, so tests
can seed a known drift into a tmp copy and assert the lint catches it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

from . import allowlists

REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), os.pardir, os.pardir))


@dataclass(frozen=True)
class Violation:
    lint: str
    path: str        # relative to the lint root
    line: int
    message: str

    def render(self) -> str:
        where = f"{self.path}:{self.line}" if self.line else self.path
        return f"[{self.lint}] {where}: {self.message}"


class LintContext:
    """A source tree to lint: ``root`` contains ``sail_tpu/``,
    ``README.md`` … Files parse lazily and cache per context."""

    def __init__(self, root: str = REPO_ROOT):
        self.root = os.path.abspath(root)
        self.src_root = os.path.join(self.root, "sail_tpu")
        self._text: Dict[str, Optional[str]] = {}
        self._ast: Dict[str, Optional[ast.AST]] = {}

    def rel(self, path: str) -> str:
        return os.path.relpath(path, self.root)

    def text(self, relpath: str) -> Optional[str]:
        if relpath not in self._text:
            path = os.path.join(self.root, relpath)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    self._text[relpath] = f.read()
            except OSError:
                self._text[relpath] = None
        return self._text[relpath]

    def tree(self, relpath: str) -> Optional[ast.AST]:
        if relpath not in self._ast:
            src = self.text(relpath)
            try:
                self._ast[relpath] = None if src is None \
                    else ast.parse(src, filename=relpath)
            except SyntaxError:
                self._ast[relpath] = None
        return self._ast[relpath]

    def python_sources(self, *subdirs: str) -> Iterable[str]:
        """Repo-relative paths of .py files under sail_tpu/<subdir>…"""
        roots = [os.path.join(self.src_root, d) for d in subdirs] \
            if subdirs else [self.src_root]
        for r in roots:
            for dirpath, dirnames, filenames in os.walk(r):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        yield self.rel(os.path.join(dirpath, fn))


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------

def _fold_str(node: ast.AST) -> Optional[str]:
    """Constant-fold a string expression: literals and ``"a" + "b"``
    concatenations (how prefixed config keys are built)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        a, b = _fold_str(node.left), _fold_str(node.right)
        if a is not None and b is not None:
            return a + b
    return None


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


def _string_constants(tree: ast.AST) -> Iterable[Tuple[str, int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno


# ---------------------------------------------------------------------------
# config-key drift
# ---------------------------------------------------------------------------

#: functions whose first argument is an app-config key. ``_num``/``_on``
#: are the DriverActor's local wrappers; ``app.get`` is the flattened
#: dict in SessionConf layering.
_APP_KEY_ACCESSORS = {"config_get", "truthy", "_num", "_on"}
_APP_KEY_DICTS = {"app"}

_KEY_RE = re.compile(r"^[a-z0-9_]+(?:\.[a-z0-9_]+)+$")


def _flatten_yaml(tree: dict, prefix: str = "") -> Dict[str, object]:
    out: Dict[str, object] = {}
    for k, v in (tree or {}).items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten_yaml(v, key))
        else:
            out[key] = v
    return out


def declared_config_keys(ctx: LintContext) -> Set[str]:
    import yaml
    src = ctx.text("sail_tpu/config/application.yaml")
    if src is None:
        return set()
    return set(_flatten_yaml(yaml.safe_load(src) or {}))


def read_config_keys(ctx: LintContext) -> Dict[str, List[Tuple[str, int]]]:
    """App-config keys read through a known accessor, with call sites."""
    out: Dict[str, List[Tuple[str, int]]] = {}
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _call_name(node)
            is_accessor = name in _APP_KEY_ACCESSORS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in _APP_KEY_DICTS)
            if not is_accessor:
                continue
            key = _fold_str(node.args[0])
            if key is None or key.startswith("spark.") \
                    or not _KEY_RE.match(key):
                continue
            out.setdefault(key, []).append((relpath, node.lineno))
    return out


def _config_literal_evidence(ctx: LintContext) -> Set[str]:
    """Every constant-foldable dotted string (incl. prefixes built by
    concatenation) — the loose 'is this key mentioned at all' evidence
    for the declared→used direction."""
    seen: Set[str] = set()
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            s = _fold_str(node) if isinstance(node, (ast.Constant,
                                                     ast.BinOp)) else None
            if s:
                seen.add(s)
    return seen


def lint_config_keys(ctx: LintContext) -> List[Violation]:
    declared = declared_config_keys(ctx)
    if not declared:
        return [Violation("config-keys",
                          "sail_tpu/config/application.yaml", 0,
                          "application.yaml missing or empty")]
    out: List[Violation] = []
    reads = read_config_keys(ctx)
    dynamic = allowlists.CONFIG_DYNAMIC_KEYS
    for key, sites in sorted(reads.items()):
        if key in declared:
            continue
        if any(key.startswith(p) for p in dynamic if p.endswith(".")):
            continue
        path, line = sites[0]
        out.append(Violation(
            "config-keys", path, line,
            f"config key {key!r} is read here but not declared in "
            f"config/application.yaml"))
    evidence = _config_literal_evidence(ctx)
    prefixes = {e for e in evidence if e.endswith(".")}
    for key in sorted(declared):
        if key in allowlists.CONFIG_SKIP_KEYS or "." not in key:
            continue
        if key in evidence:
            continue
        # a concatenated read: some folded prefix + the final segment
        if any(key.startswith(p) and key[len(p):] in evidence
               for p in prefixes):
            continue
        if any(key.startswith(p) for p in dynamic if p.endswith(".")):
            continue
        if key in dynamic:
            continue
        out.append(Violation(
            "config-keys", "sail_tpu/config/application.yaml", 0,
            f"config key {key!r} is declared but never read anywhere "
            f"under sail_tpu/ (wire it, remove it, or allowlist it "
            f"with a reason)"))
    return out


# ---------------------------------------------------------------------------
# spark.sail.* session-key documentation drift
# ---------------------------------------------------------------------------

_SPARK_KEY_RE = re.compile(r"spark\.sail\.[A-Za-z0-9_.]+")


def lint_spark_keys(ctx: LintContext) -> List[Violation]:
    yaml_text = ctx.text("sail_tpu/config/application.yaml") or ""
    raw_mentions = set(_SPARK_KEY_RE.findall(yaml_text))
    # a sentence-final "…spark.sail.foo.bar." mention is an exact key
    # plus punctuation, not a prefix — accept both readings
    doc_mentions = raw_mentions | {m.rstrip(".") for m in raw_mentions}
    doc_prefixes = {m for m in raw_mentions if m.endswith(".")}

    def covered(key: str) -> bool:
        if key in doc_mentions:
            return True
        # a documented "prefix." mention covers every key under it
        if any(key.startswith(p) for p in doc_prefixes):
            return True
        # a prefix literal in code is covered when the yaml documents
        # any concrete key under it
        if key.endswith(".") and any(m.startswith(key)
                                     for m in doc_mentions):
            return True
        return False

    out: List[Violation] = []
    seen: Set[str] = set()
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for value, line in _string_constants(tree):
            for key in _SPARK_KEY_RE.findall(value):
                if key in seen:
                    continue
                seen.add(key)
                if not covered(key):
                    out.append(Violation(
                        "spark-keys", relpath, line,
                        f"session conf key {key!r} is not documented in "
                        f"config/application.yaml (add the key or a "
                        f"'prefix.' mention to the relevant section)"))
    return out


# ---------------------------------------------------------------------------
# fault-site drift
# ---------------------------------------------------------------------------

# fault sites follow the `component.action` grammar; requiring the dot
# keeps other README tables (the lint catalog) out of the match
_README_SITE_RE = re.compile(r"^\|\s*`([a-z_]+\.[a-z_]+)`\s*\|",
                             re.MULTILINE)


def code_fault_sites(ctx: LintContext) -> Dict[str, Tuple[str, int]]:
    """Site literals passed to ``faults.inject``/``inject`` or as
    ``site=`` keywords (the retry helper threads them through)."""
    out: Dict[str, Tuple[str, int]] = {}
    for relpath in ctx.python_sources():
        if relpath.endswith("sail_tpu/faults.py"):
            continue  # the framework itself, not an injection site
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            site = None
            if _call_name(node) in ("inject", "maybe_inject") and node.args:
                site = _fold_str(node.args[0])
            for kw in node.keywords:
                if kw.arg == "site":
                    site = _fold_str(kw.value) or site
            if site and re.match(r"^[a-z_]+\.[a-z_]+$", site):
                out.setdefault(site, (relpath, node.lineno))
    return out


def lint_fault_sites(ctx: LintContext) -> List[Violation]:
    readme = ctx.text("README.md")
    if readme is None:
        return [Violation("fault-sites", "README.md", 0,
                          "README.md not found")]
    documented = set(_README_SITE_RE.findall(readme))
    sites = code_fault_sites(ctx)
    out: List[Violation] = []
    for site, (path, line) in sorted(sites.items()):
        if site not in documented:
            out.append(Violation(
                "fault-sites", path, line,
                f"fault-injection site {site!r} is not documented in "
                f"the README site table"))
    for site in sorted(documented - set(sites)):
        out.append(Violation(
            "fault-sites", "README.md", 0,
            f"README documents fault site {site!r} but no "
            f"faults.inject call site exists for it"))
    return out


# ---------------------------------------------------------------------------
# proto freshness
# ---------------------------------------------------------------------------

_PROTO_MESSAGE_RE = re.compile(r"^\s*message\s+(\w+)", re.MULTILINE)
_PROTO_FIELD_RE = re.compile(
    r"^\s*(?:repeated\s+|optional\s+)?[\w.]+\s+(\w+)\s*=\s*\d+\s*;",
    re.MULTILINE)
_PROTO_RPC_RE = re.compile(r"^\s*rpc\s+(\w+)", re.MULTILINE)


def _pb2_descriptor_names(pb2_src: str) -> Optional[Set[str]]:
    """Message/field/service/method names baked into a generated pb2
    module's serialized FileDescriptorProto (the longest bytes literal
    in the file). Returns None when nothing parses."""
    try:
        tree = ast.parse(pb2_src)
    except SyntaxError:
        return None
    blobs = [n.value for n in ast.walk(tree)
             if isinstance(n, ast.Constant) and isinstance(n.value, bytes)]
    if not blobs:
        return None
    from google.protobuf import descriptor_pb2
    try:
        fd = descriptor_pb2.FileDescriptorProto.FromString(
            max(blobs, key=len))
    except Exception:  # noqa: BLE001 — undecodable blob = no evidence
        return None
    names: Set[str] = set()

    def visit_message(m):
        names.add(m.name)
        for f in m.field:
            names.add(f.name)
        for nested in m.nested_type:
            visit_message(nested)
        for e in m.enum_type:
            names.add(e.name)

    for m in fd.message_type:
        visit_message(m)
    for svc in fd.service:
        names.add(svc.name)
        for meth in svc.method:
            names.add(meth.name)
    return names


def lint_proto(ctx: LintContext) -> List[Violation]:
    out: List[Violation] = []
    proto_dir = "sail_tpu/exec/proto"
    abs_dir = os.path.join(ctx.root, proto_dir)
    if not os.path.isdir(abs_dir):
        return [Violation("proto", proto_dir, 0,
                          "proto directory not found")]
    for fn in sorted(os.listdir(abs_dir)):
        if not fn.endswith(".proto"):
            continue
        proto_rel = f"{proto_dir}/{fn}"
        pb2_rel = f"{proto_dir}/{fn[:-len('.proto')]}_pb2.py"
        proto_src = ctx.text(proto_rel) or ""
        pb2_src = ctx.text(pb2_rel)
        if pb2_src is None:
            out.append(Violation("proto", proto_rel, 0,
                                 f"no regenerated module {pb2_rel}"))
            continue
        generated = _pb2_descriptor_names(pb2_src)
        if generated is None:
            out.append(Violation(
                "proto", pb2_rel, 0,
                "cannot decode the serialized descriptor from the "
                "generated module"))
            continue
        names = set(_PROTO_MESSAGE_RE.findall(proto_src)) \
            | set(_PROTO_FIELD_RE.findall(proto_src)) \
            | set(_PROTO_RPC_RE.findall(proto_src))
        for name in sorted(names):
            if name not in generated:
                out.append(Violation(
                    "proto", proto_rel, 0,
                    f"{fn} declares {name!r} but the regenerated "
                    f"{os.path.basename(pb2_rel)} does not contain it "
                    f"— re-run scripts/regen_control_plane_pb2.py"))
    return out


# ---------------------------------------------------------------------------
# sync-point allowlist (host<->device round trips in exec/ and ops/)
# ---------------------------------------------------------------------------

_SYNC_ATTRS = {"device_get", "block_until_ready"}


class _QualnameVisitor(ast.NodeVisitor):
    """Collect (qualname, attr, line) for sync-forcing calls."""

    def __init__(self):
        self.stack: List[str] = []
        self.hits: List[Tuple[str, str, int]] = []

    def _scoped(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped

    def visit_Attribute(self, node: ast.Attribute):
        if node.attr in _SYNC_ATTRS:
            qual = ".".join(self.stack) or "<module>"
            self.hits.append((qual, node.attr, node.lineno))
        self.generic_visit(node)


def sync_points(ctx: LintContext) -> List[Tuple[str, str, str, int]]:
    """(relpath, qualname, attr, line) of every sync-forcing call in
    exec/, ops/, plan/ (the stage splitter/compiler must introduce no
    unreviewed host syncs), native/ (host-kernel argument prep),
    parallel/ (mesh collect/metrics paths), and columnar/ (Arrow
    interop materialization)."""
    out = []
    for relpath in ctx.python_sources("exec", "ops", "plan", "native",
                                      "parallel", "columnar"):
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        v = _QualnameVisitor()
        v.visit(tree)
        for qual, attr, line in v.hits:
            out.append((relpath, qual, attr, line))
    return out


def lint_sync_points(ctx: LintContext) -> List[Violation]:
    out = []
    for relpath, qual, attr, line in sync_points(ctx):
        if (relpath, qual) in allowlists.SYNC_POINTS:
            continue
        out.append(Violation(
            "sync-points", relpath, line,
            f"{attr} in {qual} is a host sync not on the reviewed "
            f"allowlist (sail_tpu/analysis/allowlists.py SYNC_POINTS; "
            f"scripts/sail_lint.py --fix-allowlist prints the stub)"))
    return out


# ---------------------------------------------------------------------------
# capacity-policy: every padded-capacity derivation routes through the
# one bucket-policy helper (columnar/batch.py bucket_capacity), so the
# pinned grow-only registry (exec/capacity.py) is the single choke
# point warm paths size batches through
# ---------------------------------------------------------------------------

class _CapacityCallVisitor(ast.NodeVisitor):
    """Collect (qualname, line) for direct ``round_capacity(...)``
    calls (bare name or attribute)."""

    def __init__(self):
        self.stack: List[str] = []
        self.hits: List[Tuple[str, int]] = []

    def _scoped(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped

    def visit_Call(self, node: ast.Call):
        f = node.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else None
        if name == "round_capacity":
            qual = ".".join(self.stack) or "<module>"
            self.hits.append((qual, node.lineno))
        self.generic_visit(node)


def capacity_calls(ctx: LintContext) -> List[Tuple[str, str, int]]:
    """(relpath, qualname, line) of every direct round_capacity call
    anywhere under sail_tpu/ — the policy helper and the registry are
    the only reviewed callers."""
    out = []
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        v = _CapacityCallVisitor()
        v.visit(tree)
        for qual, line in v.hits:
            out.append((relpath, qual, line))
    return out


def lint_capacity_policy(ctx: LintContext) -> List[Violation]:
    out = []
    for relpath, qual, line in capacity_calls(ctx):
        if (relpath, qual) in allowlists.CAPACITY_POLICY:
            continue
        out.append(Violation(
            "capacity-policy", relpath, line,
            f"direct round_capacity call in {qual} bypasses the pinned "
            f"bucket policy — size through columnar.batch."
            f"bucket_capacity (or add a reviewed CAPACITY_POLICY "
            f"allowlist entry in sail_tpu/analysis/allowlists.py)"))
    return out


# ---------------------------------------------------------------------------
# lock / actor-thread discipline in exec/cluster.py
# ---------------------------------------------------------------------------

_MUTATORS = {"setdefault", "pop", "clear", "update", "append",
             "extend", "remove", "add", "discard"}


def _is_self_attr(node: ast.AST, name: str) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == name
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _class_def(tree: ast.AST, name: str) -> Optional[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def lint_locks(ctx: LintContext) -> List[Violation]:
    """exec/cluster.py slice of the generalized concurrency passes:
    guarded-field inference (which subsumes the historical hardcoded
    WorkerActor._running/_running_lock check) plus call-graph actor
    confinement for the DriverActor/WorkerActor registries."""
    from . import concurrency
    return concurrency.cluster_locks_compat(ctx)


def _parents(root: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


# ---------------------------------------------------------------------------
# metrics registry drift (the generalized test_registry_drift)
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"^[a-z0-9_]+(?:\.[a-z0-9_]+)+$")


def load_metric_registry(ctx: LintContext) -> List[dict]:
    import yaml
    src = ctx.text("sail_tpu/metrics_registry.yaml")
    return yaml.safe_load(src) if src else []


def metric_call_sites(ctx: LintContext
                      ) -> List[Tuple[str, Tuple[str, ...], str, int]]:
    """(metric name, kwarg attribute keys, relpath, line) for every
    ``record(...)``/``_record_metric(...)``/``timer(...)`` call with a
    resolvable name (plain literal or either branch of a conditional)
    — the timer context manager records into its named instrument at
    exit, so its call sites are record sites for drift purposes."""
    out = []
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node) not in ("record", "_record_metric",
                                        "timer", "_metric_timer"):
                continue
            first = node.args[0]
            names = []
            if isinstance(first, ast.IfExp):
                names = [_fold_str(first.body), _fold_str(first.orelse)]
            else:
                names = [_fold_str(first)]
            attrs = tuple(sorted(kw.arg for kw in node.keywords
                                 if kw.arg is not None))
            has_star = any(kw.arg is None for kw in node.keywords)
            for name in names:
                if name is None or not _METRIC_NAME_RE.match(name):
                    continue
                out.append((name, attrs if not has_star else None,
                            relpath, node.lineno))
    return out


def lint_metrics(ctx: LintContext) -> List[Violation]:
    entries = load_metric_registry(ctx)
    out: List[Violation] = []
    if not entries:
        return [Violation("metrics", "sail_tpu/metrics_registry.yaml", 0,
                          "metrics_registry.yaml missing or empty")]
    names = [e.get("name") for e in entries]
    if len(names) != len(set(names)):
        dupes = sorted({n for n in names if names.count(n) > 1})
        out.append(Violation(
            "metrics", "sail_tpu/metrics_registry.yaml", 0,
            f"duplicate registry entries: {dupes}"))
    from ..metrics import is_legal_prometheus_name, prometheus_name
    for e in entries:
        if e.get("type") not in ("counter", "gauge", "histogram"):
            out.append(Violation(
                "metrics", "sail_tpu/metrics_registry.yaml", 0,
                f"{e.get('name')!r}: bad type {e.get('type')!r}"))
        # every instrument must survive the Prometheus exposition
        # translation (obs_server /metrics) as a legal metric name
        prom = prometheus_name(str(e.get("name") or ""),
                               str(e.get("type") or ""))
        if not is_legal_prometheus_name(prom):
            out.append(Violation(
                "metrics", "sail_tpu/metrics_registry.yaml", 0,
                f"{e.get('name')!r}: translates to illegal Prometheus "
                f"metric name {prom!r}"))
        buckets = e.get("buckets")
        if buckets is not None:
            if e.get("type") != "histogram":
                out.append(Violation(
                    "metrics", "sail_tpu/metrics_registry.yaml", 0,
                    f"{e.get('name')!r}: buckets declared on "
                    f"non-histogram type {e.get('type')!r}"))
            elif not (float(buckets.get("base", 0)) > 0
                      and float(buckets.get("growth", 0)) > 1
                      and int(buckets.get("count", 0)) >= 1):
                out.append(Violation(
                    "metrics", "sail_tpu/metrics_registry.yaml", 0,
                    f"{e.get('name')!r}: bad bucket spec {buckets!r} "
                    f"(need base>0, growth>1, count>=1)"))
    by_name = {e["name"]: e for e in entries}
    sites = metric_call_sites(ctx)
    used_attrs: Dict[str, Set[str]] = {}
    recorded: Set[str] = set()
    for name, attrs, relpath, line in sites:
        recorded.add(name)
        if name not in by_name:
            out.append(Violation(
                "metrics", relpath, line,
                f"metric {name!r} recorded here but not declared in "
                f"metrics_registry.yaml"))
            continue
        declared_attrs = set(by_name[name].get("attributes") or ())
        if attrs is None:
            continue  # **kwargs call: runtime registry validates
        extra = set(attrs) - declared_attrs
        if extra:
            out.append(Violation(
                "metrics", relpath, line,
                f"metric {name!r} recorded with undeclared attributes "
                f"{sorted(extra)} (declared: {sorted(declared_attrs)})"))
        used_attrs.setdefault(name, set()).update(attrs)
    # orphan declarations: loose literal evidence, same as the original
    # test_registry_drift (conditional names, f-string-free sites)
    literal_evidence: Set[str] = set()
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for value, _line in _string_constants(tree):
            if _METRIC_NAME_RE.match(value):
                literal_evidence.add(value)
    for name, e in sorted(by_name.items()):
        if name not in literal_evidence:
            out.append(Violation(
                "metrics", "sail_tpu/metrics_registry.yaml", 0,
                f"metric {name!r} declared but never recorded anywhere "
                f"under sail_tpu/"))
            continue
        declared_attrs = set(e.get("attributes") or ())
        if name in used_attrs and name not in \
                allowlists.METRIC_DYNAMIC_ATTRS:
            unused = declared_attrs - used_attrs[name]
            if unused and name in recorded:
                out.append(Violation(
                    "metrics", "sail_tpu/metrics_registry.yaml", 0,
                    f"metric {name!r} declares attributes "
                    f"{sorted(unused)} that no record() call site "
                    f"passes"))
    return out


# ---------------------------------------------------------------------------
# event-vocabulary drift (the metrics lint's shape, for the flight-data
# recorder: sail_tpu/events.py)
# ---------------------------------------------------------------------------

#: envelope kwargs emit() owns — never part of a type's declared attrs
_EVENT_RESERVED_KWARGS = {"query_id", "trace_id", "ts"}


def declared_event_types(ctx: LintContext) -> Dict[str, Set[str]]:
    """EVENT_TYPES from sail_tpu/events.py: type name → attribute set
    (AST literal walk — the lint must work on seeded tree copies that
    are not importable)."""
    tree = ctx.tree("sail_tpu/events.py")
    if tree is None:
        return {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            targets = [node.target.id]
        else:
            continue
        if "EVENT_TYPES" not in targets or \
                not isinstance(node.value, ast.Dict):
            continue
        out: Dict[str, Set[str]] = {}
        for k, v in zip(node.value.keys, node.value.values):
            name = _fold_str(k) if k is not None else None
            if name is None or not isinstance(v, (ast.Tuple, ast.List)):
                continue
            attrs = {_fold_str(e) for e in v.elts}
            if None in attrs:
                continue
            out[name] = attrs
        return out
    return {}


def declared_event_symbols(ctx: LintContext) -> Dict[str, str]:
    """``EventType`` class attributes: symbol → type-name string."""
    tree = ctx.tree("sail_tpu/events.py")
    if tree is None:
        return {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "EventType":
            out: Dict[str, str] = {}
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name):
                    value = _fold_str(stmt.value)
                    if value is not None:
                        out[stmt.targets[0].id] = value
            return out
    return {}


def _event_type_symbol(node: ast.AST) -> Optional[str]:
    """The ``X`` of an ``EventType.X`` / ``mod.EventType.X`` first
    argument, else None."""
    if not isinstance(node, ast.Attribute):
        return None
    base = node.value
    if isinstance(base, ast.Name) and base.id == "EventType":
        return node.attr
    if isinstance(base, ast.Attribute) and base.attr == "EventType":
        return node.attr
    return None


def event_call_sites(ctx: LintContext
                     ) -> List[Tuple[str, Optional[Tuple[str, ...]],
                                     str, int]]:
    """(EventType symbol, kwarg attribute keys or None for **kwargs,
    relpath, line) for every ``emit(EventType.X, ...)`` call."""
    out = []
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if _call_name(node) != "emit":
                continue
            symbol = _event_type_symbol(node.args[0])
            if symbol is None:
                continue
            has_star = any(kw.arg is None for kw in node.keywords)
            attrs = tuple(sorted(
                kw.arg for kw in node.keywords
                if kw.arg is not None
                and kw.arg not in _EVENT_RESERVED_KWARGS))
            out.append((symbol, None if has_star else attrs,
                        relpath, node.lineno))
    return out


def lint_events(ctx: LintContext) -> List[Violation]:
    """Flight-recorder vocabulary drift: every ``emit(EventType.X)``
    site uses a declared type with declared attributes; every declared
    type is emitted somewhere; symbols ↔ EVENT_TYPES agree."""
    declared = declared_event_types(ctx)
    symbols = declared_event_symbols(ctx)
    out: List[Violation] = []
    if not declared:
        return [Violation("events", "sail_tpu/events.py", 0,
                          "EVENT_TYPES missing or not a literal dict")]
    for sym, name in sorted(symbols.items()):
        if name not in declared:
            out.append(Violation(
                "events", "sail_tpu/events.py", 0,
                f"EventType.{sym} = {name!r} has no EVENT_TYPES "
                f"declaration"))
    sym_values = set(symbols.values())
    for name in sorted(declared):
        if name not in sym_values:
            out.append(Violation(
                "events", "sail_tpu/events.py", 0,
                f"event type {name!r} declared in EVENT_TYPES but has "
                f"no EventType symbol"))
    sites = event_call_sites(ctx)
    emitted: Set[str] = set()
    used_attrs: Dict[str, Set[str]] = {}
    for sym, attrs, relpath, line in sites:
        name = symbols.get(sym)
        if name is None or name not in declared:
            out.append(Violation(
                "events", relpath, line,
                f"emit(EventType.{sym}) uses an undeclared event type"))
            continue
        emitted.add(name)
        if attrs is None:
            continue  # **kwargs call: runtime validation owns it
        extra = set(attrs) - declared[name]
        if extra:
            out.append(Violation(
                "events", relpath, line,
                f"event {name!r} emitted with undeclared attributes "
                f"{sorted(extra)} (declared: "
                f"{sorted(declared[name])})"))
        used_attrs.setdefault(name, set()).update(attrs)
    for name in sorted(declared):
        if name not in emitted:
            out.append(Violation(
                "events", "sail_tpu/events.py", 0,
                f"event type {name!r} declared but never emitted "
                f"anywhere under sail_tpu/"))
            continue
        unused = declared[name] - used_attrs.get(name, set())
        if unused:
            out.append(Violation(
                "events", "sail_tpu/events.py", 0,
                f"event type {name!r} declares attributes "
                f"{sorted(unused)} that no emit site passes"))
    return out


# ---------------------------------------------------------------------------
# tail-latency taxonomy drift (retrace causes + anomaly verdicts:
# sail_tpu/events.py RETRACE_CAUSES / VERDICT_CATEGORIES)
# ---------------------------------------------------------------------------

def _declared_string_tuple(ctx: LintContext, relpath: str,
                           varname: str) -> Optional[Tuple[str, ...]]:
    """A module-level ``VARNAME = ("a", "b", …)`` literal from
    ``relpath`` (AST walk — works on seeded, non-importable trees)."""
    tree = ctx.tree(relpath)
    if tree is None:
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            targets = [node.target.id]
        else:
            continue
        if varname not in targets or \
                not isinstance(node.value, (ast.Tuple, ast.List)):
            continue
        elts = [_fold_str(e) for e in node.value.elts]
        if any(e is None for e in elts):
            return None
        return tuple(elts)  # type: ignore[arg-type]
    return None


def lint_slo_taxonomy(ctx: LintContext) -> List[Violation]:
    """Forensics-taxonomy drift: every retrace cause string used in
    code (``cause=`` kwargs, ``classify_*`` return literals in
    exec/retrace.py) is declared in events.RETRACE_CAUSES; every
    verdict/evidence category used by the anomaly classifier
    (EVIDENCE_ORDER, _FLAG_CATEGORIES, ``verdict = "…"`` assignments,
    ``{"category": "…"}`` literals, ``verdict=`` kwargs) is declared
    in events.VERDICT_CATEGORIES; and every declared member of either
    tuple appears somewhere under sail_tpu/ outside events.py — a
    cause or verdict nobody can produce is dead vocabulary that
    dashboards and the SLO runbook would still document."""
    out: List[Violation] = []
    causes = _declared_string_tuple(
        ctx, "sail_tpu/events.py", "RETRACE_CAUSES")
    verdicts = _declared_string_tuple(
        ctx, "sail_tpu/events.py", "VERDICT_CATEGORIES")
    if causes is None:
        return [Violation(
            "slo-taxonomy", "sail_tpu/events.py", 0,
            "RETRACE_CAUSES missing or not a literal string tuple")]
    if verdicts is None:
        return [Violation(
            "slo-taxonomy", "sail_tpu/events.py", 0,
            "VERDICT_CATEGORIES missing or not a literal string "
            "tuple")]
    cause_set, verdict_set = set(causes), set(verdicts)

    used_causes: Dict[str, Tuple[str, int]] = {}
    used_verdicts: Dict[str, Tuple[str, int]] = {}
    all_literals: Set[str] = set()
    for relpath in ctx.python_sources():
        if relpath == "sail_tpu/events.py":
            continue
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str):
                all_literals.add(node.value)
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    v = _fold_str(kw.value)
                    if v is None:
                        continue
                    if kw.arg == "cause":
                        used_causes.setdefault(
                            v, (relpath, node.lineno))
                    elif kw.arg == "verdict":
                        used_verdicts.setdefault(
                            v, (relpath, node.lineno))
            if relpath == "sail_tpu/exec/retrace.py" and \
                    isinstance(node, ast.FunctionDef) and \
                    node.name.startswith("classify"):
                for ret in ast.walk(node):
                    if isinstance(ret, ast.Return) and \
                            ret.value is not None:
                        v = _fold_str(ret.value)
                        if v is not None:
                            used_causes.setdefault(
                                v, (relpath, ret.lineno))
            if relpath == "sail_tpu/analysis/anomaly.py":
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    targets = node.targets if isinstance(
                        node, ast.Assign) else [node.target]
                    names = {t.id for t in targets
                             if isinstance(t, ast.Name)}
                    value = node.value
                    if names & {"EVIDENCE_ORDER",
                                "_FLAG_CATEGORIES"} and \
                            isinstance(value, (ast.Tuple, ast.List)):
                        for e in value.elts:
                            v = _fold_str(e)
                            if v is not None:
                                used_verdicts.setdefault(
                                    v, (relpath, e.lineno))
                    elif "verdict" in names and value is not None:
                        v = _fold_str(value)
                        if v is not None:
                            used_verdicts.setdefault(
                                v, (relpath, node.lineno))
                if isinstance(node, ast.Dict):
                    for k, v in zip(node.keys, node.values):
                        if k is not None and \
                                _fold_str(k) == "category":
                            cat = _fold_str(v)
                            if cat is not None:
                                used_verdicts.setdefault(
                                    cat, (relpath, v.lineno))

    for cause in sorted(used_causes):
        if cause not in cause_set:
            relpath, line = used_causes[cause]
            out.append(Violation(
                "slo-taxonomy", relpath, line,
                f"retrace cause {cause!r} is produced here but not "
                f"declared in events.RETRACE_CAUSES"))
    for verdict in sorted(used_verdicts):
        if verdict not in verdict_set:
            relpath, line = used_verdicts[verdict]
            out.append(Violation(
                "slo-taxonomy", relpath, line,
                f"anomaly verdict {verdict!r} is produced here but "
                f"not declared in events.VERDICT_CATEGORIES"))
    for cause in causes:
        if cause not in all_literals:
            out.append(Violation(
                "slo-taxonomy", "sail_tpu/events.py", 0,
                f"retrace cause {cause!r} declared in RETRACE_CAUSES "
                f"but never appears in code under sail_tpu/"))
    for verdict in verdicts:
        if verdict not in all_literals:
            out.append(Violation(
                "slo-taxonomy", "sail_tpu/events.py", 0,
                f"anomaly verdict {verdict!r} declared in "
                f"VERDICT_CATEGORIES but never appears in code under "
                f"sail_tpu/"))
    return out


# ---------------------------------------------------------------------------
# registry + runner
# ---------------------------------------------------------------------------

def lint_guarded_fields(ctx: LintContext) -> List[Violation]:
    """Inferred lock-guarded attributes only touched under their guard
    (exec/cluster.py, continuous.py, shuffle.py, admission.py)."""
    from . import concurrency
    return concurrency.lint_guarded_fields(ctx)


def lint_lock_order(ctx: LintContext) -> List[Violation]:
    """Acquires-while-holding graph over every threading lock under
    sail_tpu/ is acyclic (`sail_lint --graph` renders it)."""
    from . import concurrency
    return concurrency.lint_lock_order(ctx)


def lint_actor_confinement(ctx: LintContext) -> List[Violation]:
    """Actor-confined state (concurrency.ACTOR_CONFINEMENT) is only
    mutated from methods reachable off the mailbox entry points."""
    from . import concurrency
    return concurrency.lint_actor_confinement(ctx)


def lint_decision_purity(ctx: LintContext) -> List[Violation]:
    """Pure decision functions are closed over recorded signals: no
    clocks/random/id()/set-iteration/config re-reads in their
    same-module closure."""
    from . import concurrency
    return concurrency.lint_decision_purity(ctx)


LINTS: Dict[str, Callable[[LintContext], List[Violation]]] = {
    "config-keys": lint_config_keys,
    "spark-keys": lint_spark_keys,
    "fault-sites": lint_fault_sites,
    "proto": lint_proto,
    "sync-points": lint_sync_points,
    "capacity-policy": lint_capacity_policy,
    "locks": lint_locks,
    "guarded-fields": lint_guarded_fields,
    "lock-order": lint_lock_order,
    "actor-confinement": lint_actor_confinement,
    "decision-purity": lint_decision_purity,
    "metrics": lint_metrics,
    "events": lint_events,
    "slo-taxonomy": lint_slo_taxonomy,
}


def run_lints(root: str = REPO_ROOT,
              only: Optional[Iterable[str]] = None) -> List[Violation]:
    ctx = LintContext(root)
    out: List[Violation] = []
    for name, fn in LINTS.items():
        if only is not None and name not in only:
            continue
        out.extend(fn(ctx))
    return out


def fix_allowlist_stubs(root: str = REPO_ROOT) -> str:
    """Ready-to-paste allowlist stubs for current violations (sync
    points + dynamic config keys). The reason strings are placeholders:
    edit them before committing — see the module docstring etiquette."""
    ctx = LintContext(root)
    lines: List[str] = []
    sync = [(relpath, qual) for relpath, qual, _a, _l in sync_points(ctx)
            if (relpath, qual) not in allowlists.SYNC_POINTS]
    if sync:
        lines.append("# add to SYNC_POINTS in "
                     "sail_tpu/analysis/allowlists.py:")
        for relpath, qual in sorted(set(sync)):
            lines.append(f'    ("{relpath}", "{qual}"),')
    capcalls = [(relpath, qual) for relpath, qual, _l
                in capacity_calls(ctx)
                if (relpath, qual) not in allowlists.CAPACITY_POLICY]
    if capcalls:
        lines.append("# add to CAPACITY_POLICY in "
                     "sail_tpu/analysis/allowlists.py (or route the "
                     "call through bucket_capacity):")
        for relpath, qual in sorted(set(capcalls)):
            lines.append(f'    ("{relpath}", "{qual}"),')
    declared = declared_config_keys(ctx)
    orphan = [v for v in lint_config_keys(ctx)
              if "declared but never read" in v.message]
    if orphan:
        lines.append("# add to CONFIG_DYNAMIC_KEYS in "
                     "sail_tpu/analysis/allowlists.py (or wire/remove "
                     "the key):")
        for v in orphan:
            key = v.message.split("'")[1]
            if key in declared:
                lines.append(f'    "{key}": "TODO: why is this key '
                             f'read dynamically?",')
    return "\n".join(lines)
