"""Concurrency & determinism analysis passes.

Four repo-wide AST passes over the cluster runtime, registered as drift
lints (``analysis/lints.py`` → ``scripts/sail_lint.py`` /
``tests/test_lints.py``):

``guarded-fields``
    Per-class lock-guarded attribute inference. A class attribute is
    *guarded* by ``self.<lock>`` when some structural mutation of it
    (outside ``__init__``) happens under ``with self.<lock>``, or when
    its ``__init__`` assignment carries a ``# guarded-by: <lock>``
    annotation. Every other touch — content reads AND writes — must
    then also hold the lock; only ``len()`` reads and ``__init__``
    construction are exempt. A helper method whose *callers* hold the
    lock declares the contract with ``# guarded-by: <lock>`` on its
    ``def`` line (the annotation is the review surface: it asserts
    every caller acquires the lock first). Deliberate lock-free
    accesses (racy monitoring reads) live in
    ``allowlists.GUARDED_FIELDS`` with a written reason.

``lock-order``
    The acquires-while-holding graph over every ``threading.Lock`` /
    ``RLock`` / ``Condition`` site under ``sail_tpu/``: an edge A→B
    means some code path acquires B while holding A (directly nested
    ``with`` blocks, plus one call level into same-module functions).
    Any cycle is a potential deadlock and fails the lint. The graph
    renders as a reviewable artifact via ``sail_lint --graph``.

``actor-confinement``
    Call-graph-aware generalization of the nested-def heuristic: state
    named in :data:`ACTOR_CONFINEMENT` may only be mutated from methods
    reachable from the actor thread's entry points (``__init__`` /
    ``on_start`` / ``receive`` / ``on_stop`` — the mailbox loop in
    ``exec/actor.py``). A mutation inside a nested def or lambda runs
    on whatever thread calls the closure (gRPC handlers, pool threads)
    and is flagged; so is a mutation in a method no entry point can
    reach. Known cross-thread paths are reviewed into
    ``allowlists.ACTOR_CROSS_THREAD``.

``decision-purity``
    Taint pass over the pure decision functions (autoscaler evaluate,
    AQE rewrite decisions, admission DRR arbitration, anomaly verdicts,
    ``router.decide_*``): the replay contract says each is closed over
    its recorded-signal parameters, so the pass walks the function and
    its same-module callees and flags wall-clock reads, ``random``,
    ``id()``, unordered-``set`` iteration, config/environment re-reads.
    The ONE sanctioned impurity shape is the injected-signal default
    ``now = time.time() if now is None else now`` (equivalently
    ``if conf is None: conf = _conf()``): the live path fills an
    omitted signal, the replay path passes the recorded value, and the
    filled value rides the decision record. Reviewed exceptions live in
    ``allowlists.DECISION_PURITY`` with a one-line reason each.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from . import allowlists
from .lints import (LintContext, Violation, _MUTATORS, _call_name,
                    _class_def, _is_self_attr, _parents)

# ---------------------------------------------------------------------------
# shared: lock discovery + ``# guarded-by:`` annotations
# ---------------------------------------------------------------------------

_LOCK_TYPES = {"Lock", "RLock", "Condition"}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: files the guarded-field inference enforces (the cluster runtime's
#: shared mutable state; extend as new multithreaded modules land)
GUARDED_SCAN_FILES = (
    "sail_tpu/exec/cluster.py",
    "sail_tpu/exec/continuous.py",
    "sail_tpu/exec/shuffle.py",
    "sail_tpu/exec/admission.py",
)


def guarded_by_annotations(ctx: LintContext, relpath: str) -> Dict[int, str]:
    """``# guarded-by: <lock>`` annotations by line number."""
    src = ctx.text(relpath)
    out: Dict[int, str] = {}
    if src is None:
        return out
    for i, line in enumerate(src.splitlines(), 1):
        m = _GUARDED_BY_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def _is_lock_ctor(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _LOCK_TYPES
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "threading")


def _is_lock_annotation(node: Optional[ast.AST]) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr in _LOCK_TYPES
            and isinstance(node.value, ast.Name)
            and node.value.id == "threading")


def class_lock_attrs(cls: ast.ClassDef) -> Dict[str, str]:
    """``self.<attr>`` lock attributes of one class: attr → lock type
    (``Lock``/``RLock``/``Condition``). Recognizes direct construction
    (``self._lock = threading.Lock()``), dataclass fields
    (``_lock: threading.Lock = field(...)``), and constructor
    parameters annotated ``threading.Condition``/``Lock`` assigned to
    ``self`` (a lock shared with a peer object)."""
    locks: Dict[str, str] = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and \
                isinstance(stmt.target, ast.Name) and \
                _is_lock_annotation(stmt.annotation):
            locks[stmt.target.id] = stmt.annotation.attr  # type: ignore[union-attr]
        if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        params: Dict[str, str] = {}
        a = stmt.args
        for arg in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
            if _is_lock_annotation(arg.annotation):
                params[arg.arg] = arg.annotation.attr  # type: ignore[union-attr]
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if _is_lock_ctor(node.value):
                    locks[t.attr] = node.value.func.attr  # type: ignore[union-attr]
                elif isinstance(node.value, ast.Name) and \
                        node.value.id in params:
                    locks[t.attr] = params[node.value.id]
    return locks


def module_lock_names(tree: ast.AST) -> Dict[str, str]:
    """Module-level ``NAME = threading.Lock()`` globals: name → type."""
    out: Dict[str, str] = {}
    for stmt in getattr(tree, "body", ()):
        if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    out[t.id] = stmt.value.func.attr  # type: ignore[union-attr]
    return out


def _node_lines(fn: ast.AST) -> Set[int]:
    return {n.lineno for n in ast.walk(fn) if hasattr(n, "lineno")}


def _enclosing_defs(parents: Dict[ast.AST, ast.AST], node: ast.AST,
                    stop: ast.AST) -> List[ast.AST]:
    """Innermost-first chain of defs/lambdas containing ``node``, up to
    (not including) ``stop``."""
    chain: List[ast.AST] = []
    cur = parents.get(node)
    while cur is not None and cur is not stop:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            chain.append(cur)
        cur = parents.get(cur)
    return chain


def _qualname(cls: ast.ClassDef, chain: List[ast.AST]) -> str:
    names = [getattr(f, "name", "<lambda>") for f in reversed(chain)]
    return ".".join([cls.name] + names) if names else cls.name


# ---------------------------------------------------------------------------
# attribute access classification (shared by guarded-fields + confinement)
# ---------------------------------------------------------------------------

def _self_attr_accesses(cls: ast.ClassDef, attr: str,
                        parents: Dict[ast.AST, ast.AST]
                        ) -> List[Tuple[ast.Attribute, bool]]:
    """Every ``self.<attr>`` touch in the class: (node, is_mutation).
    Mutations are rebinds (``self.x = …``, ``self.x += …``,
    ``del self.x``), element writes (``self.x[k] = …``,
    ``del self.x[k]``), and structural mutator calls
    (``self.x.pop(…)`` …)."""
    out: List[Tuple[ast.Attribute, bool]] = []
    for node in ast.walk(cls):
        if not _is_self_attr(node, attr):
            continue
        mutated = False
        ctx = getattr(node, "ctx", None)
        if isinstance(ctx, (ast.Store, ast.Del)):
            mutated = True
        parent = parents.get(node)
        if isinstance(parent, ast.Subscript) and parent.value is node and \
                isinstance(getattr(parent, "ctx", None),
                           (ast.Store, ast.Del)):
            mutated = True
        if isinstance(parent, ast.Attribute) and \
                parent.attr in _MUTATORS:
            grand = parents.get(parent)
            if isinstance(grand, ast.Call) and grand.func is parent:
                mutated = True
        out.append((node, mutated))
    return out


def _in_init(chain: List[ast.AST]) -> bool:
    return bool(chain) and \
        getattr(chain[-1], "name", "") == "__init__"


def _is_len_read(parents: Dict[ast.AST, ast.AST],
                 node: ast.AST) -> bool:
    parent = parents.get(node)
    return (isinstance(parent, ast.Call)
            and isinstance(parent.func, ast.Name)
            and parent.func.id == "len"
            and parent.args and parent.args[0] is node)


# ---------------------------------------------------------------------------
# pass 1: guarded-field inference
# ---------------------------------------------------------------------------

def class_guarded_fields(ctx: LintContext, relpath: str,
                         cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """Inferred guarded attributes of one class: attr → lock attrs it
    is guarded by (mutation under ``with self.<lock>`` outside
    ``__init__``, or a ``# guarded-by:`` annotation on its ``__init__``
    assignment)."""
    locks = class_lock_attrs(cls)
    if not locks:
        return {}
    annos = guarded_by_annotations(ctx, relpath)
    coverage = _guard_coverage(cls, set(locks), annos)
    parents = _parents(cls)
    guards: Dict[str, Set[str]] = {}
    # annotation on the __init__ assignment line declares the guard
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        lock = annos.get(node.lineno)
        if lock is None or lock not in locks:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                guards.setdefault(t.attr, set()).add(lock)
    # inference: a structural mutation under the lock, outside __init__
    seen_attrs = {node.attr for node in ast.walk(cls)
                  if isinstance(node, ast.Attribute)
                  and isinstance(node.value, ast.Name)
                  and node.value.id == "self"
                  and node.attr not in locks}
    for attr in sorted(seen_attrs):
        for node, mutated in _self_attr_accesses(cls, attr, parents):
            if not mutated:
                continue
            chain = _enclosing_defs(parents, node, cls)
            if _in_init(chain):
                continue
            for lock in locks:
                if node.lineno in coverage[lock]:
                    guards.setdefault(attr, set()).add(lock)
    return guards


def _guard_coverage(cls: ast.ClassDef, lock_attrs: Set[str],
                    annos: Dict[int, str]) -> Dict[str, Set[int]]:
    """Line numbers covered per lock: ``with self.<lock>`` blocks plus
    whole methods annotated ``# guarded-by: <lock>`` on their ``def``
    line (the caller-holds-the-lock contract)."""
    cov: Dict[str, Set[int]] = {lock: set() for lock in lock_attrs}
    for node in ast.walk(cls):
        if isinstance(node, ast.With):
            for item in node.items:
                for lock in lock_attrs:
                    if _is_self_attr(item.context_expr, lock):
                        cov[lock].update(_node_lines(node))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            lock = annos.get(node.lineno, annos.get(node.lineno - 1))
            if lock in lock_attrs:
                cov[lock].update(_node_lines(node))
    return cov


def guarded_field_violations(ctx: LintContext,
                             files: Iterable[str],
                             lint_id: str) -> List[Violation]:
    out: List[Violation] = []
    for relpath in files:
        tree = ctx.tree(relpath)
        if tree is None:
            out.append(Violation(lint_id, relpath, 0, "cannot parse"))
            continue
        annos = guarded_by_annotations(ctx, relpath)
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            locks = class_lock_attrs(cls)
            if not locks:
                continue
            guards = class_guarded_fields(ctx, relpath, cls)
            if not guards:
                continue
            coverage = _guard_coverage(cls, set(locks), annos)
            parents = _parents(cls)
            for attr in sorted(guards):
                attr_locks = sorted(guards[attr])
                for node, mutated in _self_attr_accesses(
                        cls, attr, parents):
                    if any(node.lineno in coverage[lock]
                           for lock in attr_locks):
                        continue
                    chain = _enclosing_defs(parents, node, cls)
                    if _in_init(chain):
                        continue
                    if not mutated and _is_len_read(parents, node):
                        continue
                    qual = _qualname(cls, chain)
                    if (relpath, f"{cls.name}.{attr}", qual) in \
                            allowlists.GUARDED_FIELDS:
                        continue
                    locks_desc = " / ".join(
                        f"`with self.{lock}`" for lock in attr_locks)
                    out.append(Violation(
                        lint_id, relpath, node.lineno,
                        f"self.{attr} {'mutated' if mutated else 'read'}"
                        f" in {qual} outside {locks_desc} (structural "
                        f"mutations AND content reads must hold the "
                        f"guard; only len() is exempt — annotate the "
                        f"method `# guarded-by: {attr_locks[0]}` if "
                        f"every caller holds it, or allowlist the "
                        f"reviewed racy access in "
                        f"allowlists.GUARDED_FIELDS)"))
    return out


def lint_guarded_fields(ctx: LintContext) -> List[Violation]:
    """Inferred lock-guarded attributes are only touched under their
    guard across the cluster runtime (exec/cluster.py, continuous.py,
    shuffle.py, admission.py)."""
    return guarded_field_violations(ctx, GUARDED_SCAN_FILES,
                                    "guarded-fields")


# ---------------------------------------------------------------------------
# pass 2: lock-order graph (acquires-while-holding), cycles fail
# ---------------------------------------------------------------------------

class _LockAcq:
    """Per-def lock-acquisition analysis: direct acquisitions, ordered
    edges between nested ``with`` blocks, and calls made while holding
    a lock (for one level of same-module propagation)."""

    def __init__(self, lock_ids: Dict[str, str], relpath: str,
                 cls_name: Optional[str]):
        self.lock_ids = lock_ids       # syntactic name -> lock id
        self.relpath = relpath
        self.cls_name = cls_name
        self.acquired: Set[str] = set()
        self.edges: List[Tuple[str, str, int]] = []
        self.calls_held: List[Tuple[str, str, int]] = []  # lock, callee, line

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return self.lock_ids.get(expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                expr.value.id == "self" and self.cls_name:
            return self.lock_ids.get(f"self.{expr.attr}")
        return None

    def _callee(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name):
            return f.id
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self" \
                and self.cls_name:
            return f"{self.cls_name}.{f.attr}"
        return None

    def visit_body(self, stmts: Iterable[ast.AST],
                   held: List[str]) -> None:
        for stmt in stmts:
            self._visit(stmt, held)

    def _visit(self, node: ast.AST, held: List[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs run later, on their caller's schedule
        if isinstance(node, ast.With):
            got: List[str] = []
            for item in node.items:
                lock = self._lock_of(item.context_expr)
                if lock is None:
                    continue
                self.acquired.add(lock)
                for h in held + got:
                    if h != lock:
                        self.edges.append((h, lock, node.lineno))
                got.append(lock)
            self.visit_body(node.body, held + got)
            return
        if isinstance(node, ast.Call):
            callee = self._callee(node)
            if callee is not None and held:
                for h in held:
                    self.calls_held.append((h, callee, node.lineno))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


def lock_order_graph(ctx: LintContext
                     ) -> Tuple[Dict[Tuple[str, str], List[Tuple[str, int]]],
                                Set[str]]:
    """(edges, nodes): edges map (held, acquired) → example sites
    (relpath, line); nodes are every discovered lock identity. Lock
    identities are ``relpath::Class.attr`` for instance locks and
    ``relpath::NAME`` for module globals — a static approximation (two
    instances of one class share an identity, a Condition handed to a
    peer object gets a second one), good enough to order the repo's
    lock hierarchy and catch inversions."""
    edges: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    nodes: Set[str] = set()
    for relpath in ctx.python_sources():
        tree = ctx.tree(relpath)
        if tree is None:
            continue
        mod_locks = module_lock_names(tree)
        lock_ids = {name: f"{relpath}::{name}" for name in mod_locks}
        nodes.update(lock_ids.values())
        defs: List[Tuple[Optional[str], ast.AST, Dict[str, str]]] = []
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.append((None, stmt, lock_ids))
            elif isinstance(stmt, ast.ClassDef):
                cls_ids = dict(lock_ids)
                for attr in class_lock_attrs(stmt):
                    cls_ids[f"self.{attr}"] = \
                        f"{relpath}::{stmt.name}.{attr}"
                nodes.update(cls_ids.values())
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                        defs.append((stmt.name, sub, cls_ids))
        direct: Dict[str, Set[str]] = {}
        pending: List[Tuple[str, str, int]] = []
        for cls_name, fn, ids in defs:
            acq = _LockAcq(ids, relpath, cls_name)
            acq.visit_body(fn.body, [])
            qual = f"{cls_name}.{fn.name}" if cls_name else fn.name
            direct.setdefault(qual, set()).update(acq.acquired)
            for a, b, line in acq.edges:
                edges.setdefault((a, b), []).append((relpath, line))
            pending.extend(acq.calls_held)
        # one call level: a call made while holding L reaches a
        # same-module function that directly acquires M ⇒ edge L→M
        for held, callee, line in pending:
            for target in direct.get(callee, ()):
                if target != held:
                    edges.setdefault((held, target), []).append(
                        (relpath, line))
    for a, b in edges:
        nodes.add(a)
        nodes.add(b)
    return edges, nodes


def _find_cycles(edges: Iterable[Tuple[str, str]]) -> List[List[str]]:
    """Elementary cycles (incl. self-loops) via DFS over the edge set;
    each cycle reported once, smallest-first node rotation."""
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            seen: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                i = path.index(min(path))
                cycles.add(tuple(path[i:] + path[:i]))
            elif nxt not in seen and len(path) < 12:
                dfs(start, nxt, path + [nxt], seen | {nxt})

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return [list(c) for c in sorted(cycles)]


def render_lock_graph(ctx: LintContext) -> str:
    """The ``sail_lint --graph`` artifact: every lock node, every
    acquires-while-holding edge with an example site, and any cycles."""
    edges, nodes = lock_order_graph(ctx)
    lines = ["# lock-order graph: `A -> B` means B is acquired while",
             "# holding A (nested `with`, or a same-module call made",
             "# under A into a function that acquires B)", ""]
    lines.append(f"locks ({len(nodes)}):")
    for n in sorted(nodes):
        lines.append(f"  {n}")
    lines.append("")
    lines.append(f"edges ({len(edges)}):")
    for (a, b), sites in sorted(edges.items()):
        path, line = sites[0]
        lines.append(f"  {a} -> {b}   [{path}:{line}]")
    if not edges:
        lines.append("  (none — no code path holds two locks at once)")
    cycles = _find_cycles(edges)
    lines.append("")
    if cycles:
        lines.append(f"CYCLES ({len(cycles)}):")
        for cyc in cycles:
            lines.append("  " + " -> ".join(cyc + [cyc[0]]))
    else:
        lines.append("cycles: none")
    return "\n".join(lines)


def lint_lock_order(ctx: LintContext) -> List[Violation]:
    """The acquires-while-holding graph over every threading.Lock /
    RLock / Condition under sail_tpu/ is acyclic (a cycle is a
    potential deadlock; `sail_lint --graph` renders the ordering)."""
    edges, _nodes = lock_order_graph(ctx)
    out: List[Violation] = []
    for cyc in _find_cycles(edges):
        nxt = dict(zip(cyc, cyc[1:] + cyc[:1]))
        sites = []
        for a in cyc:
            for (x, y), where in edges.items():
                if x == a and y == nxt[a]:
                    sites.append(where[0])
                    break
        path, line = sites[0] if sites else ("", 0)
        out.append(Violation(
            "lock-order", path, line,
            "lock-order cycle (potential deadlock): "
            + " -> ".join(cyc + [cyc[0]])
            + " — acquire these locks in one global order "
            "(see scripts/sail_lint.py --graph)"))
    return out


# ---------------------------------------------------------------------------
# pass 3: actor confinement (call-graph-aware)
# ---------------------------------------------------------------------------

#: (relpath, class) → actor-confined attributes and the actor thread's
#: entry points. ``__init__`` runs before the actor thread starts (the
#: handle is not public yet), so construction counts as confined;
#: ``receive``/``on_start``/``on_stop`` are the mailbox loop
#: (exec/actor.py Actor._loop). State listed here may only be mutated
#: from methods reachable from these entries via self-calls — a nested
#: def or lambda runs on whatever thread invokes it (gRPC handler, pool
#: thread) and must route mutations through ``self.handle.send``.
ACTOR_CONFINEMENT: Dict[Tuple[str, str], Dict[str, Set[str]]] = {
    ("sail_tpu/exec/cluster.py", "DriverActor"): {
        "entry": {"__init__", "on_start", "receive", "on_stop"},
        "attrs": {"workers", "jobs", "quarantined", "_readmit_info",
                  "continuous", "_continuous_drain", "draining",
                  "_starting", "_starting_ts", "pool_peak"},
    },
    ("sail_tpu/exec/cluster.py", "WorkerActor"): {
        # _running is lock-guarded (pass 1) and _crashed is a
        # cross-thread crash flag (atomic bool write from the heartbeat
        # thread); the bindings below must only change on the mailbox
        "entry": {"__init__", "on_start", "receive", "on_stop"},
        "attrs": {"_server", "_driver_channel", "port", "streams",
                  "continuous"},
    },
}


def _method_call_graph(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """method → self-methods it calls directly (calls inside nested
    defs/lambdas excluded: those run on the closure's caller thread,
    not necessarily this method's)."""
    methods = {stmt.name for stmt in cls.body
               if isinstance(stmt, (ast.FunctionDef,
                                    ast.AsyncFunctionDef))}
    graph: Dict[str, Set[str]] = {m: set() for m in methods}

    def collect(node: ast.AST, sink: Set[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id == "self" and f.attr in methods:
                sink.add(f.attr)
        for child in ast.iter_child_nodes(node):
            collect(child, sink)

    for stmt in cls.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in stmt.body:
                collect(sub, graph[stmt.name])
    return graph


def _reachable(graph: Dict[str, Set[str]],
               entries: Set[str]) -> Set[str]:
    seen = set(e for e in entries if e in graph)
    work = list(seen)
    while work:
        for nxt in graph.get(work.pop(), ()):
            if nxt not in seen:
                seen.add(nxt)
                work.append(nxt)
    return seen


def lint_actor_confinement(ctx: LintContext) -> List[Violation]:
    """Actor state named in ACTOR_CONFINEMENT is only mutated from
    methods reachable off the actor thread's entry points (mailbox
    loop); nested-def/lambda mutations run on foreign threads and must
    route through self.handle.send."""
    return actor_confinement_violations(ctx, ACTOR_CONFINEMENT,
                                        "actor-confinement")


def actor_confinement_violations(
        ctx: LintContext,
        table: Dict[Tuple[str, str], Dict[str, Set[str]]],
        lint_id: str) -> List[Violation]:
    out: List[Violation] = []
    for (relpath, cls_name), spec in sorted(table.items()):
        tree = ctx.tree(relpath)
        if tree is None:
            out.append(Violation(lint_id, relpath, 0, "cannot parse"))
            continue
        cls = _class_def(tree, cls_name)
        if cls is None:
            out.append(Violation(lint_id, relpath, 0,
                                 f"{cls_name} class not found"))
            continue
        graph = _method_call_graph(cls)
        reachable = _reachable(graph, set(spec["entry"]))
        parents = _parents(cls)
        for attr in sorted(spec["attrs"]):
            for node, mutated in _self_attr_accesses(cls, attr, parents):
                if not mutated:
                    continue
                chain = _enclosing_defs(parents, node, cls)
                if not chain:
                    continue  # class-body default
                qual = _qualname(cls, chain)
                if (relpath, f"{cls_name}.{attr}", qual) in \
                        allowlists.ACTOR_CROSS_THREAD:
                    continue
                if len(chain) > 1 or isinstance(chain[0], ast.Lambda):
                    why = "inside a lambda" if isinstance(
                        chain[0], ast.Lambda) else \
                        "inside a nested function"
                    out.append(Violation(
                        lint_id, relpath, node.lineno,
                        f"self.{attr} mutated {why} ({qual}) — the "
                        f"closure runs off the actor thread; route the "
                        f"mutation through self.handle.send (or review "
                        f"it into allowlists.ACTOR_CROSS_THREAD)"))
                elif chain[0].name not in reachable:
                    out.append(Violation(
                        lint_id, relpath, node.lineno,
                        f"self.{attr} mutated in {qual}, which is not "
                        f"reachable from the actor entry points "
                        f"{sorted(spec['entry'])} via self-calls — "
                        f"confined state may only change on the actor "
                        f"thread (or review the path into "
                        f"allowlists.ACTOR_CROSS_THREAD)"))
    return out


# ---------------------------------------------------------------------------
# pass 4: decision-purity taint
# ---------------------------------------------------------------------------

#: the pure decision functions: replay derives their output from
#: recorded signals alone, so their closure (same-module callees
#: included) must be free of clocks, randomness, identity hashes,
#: unordered-set iteration, and config/environment re-reads
DECISION_FUNCTIONS: Tuple[Tuple[str, str], ...] = (
    ("sail_tpu/exec/autoscaler.py", "evaluate"),
    ("sail_tpu/exec/adaptive.py", "plan_graph"),
    ("sail_tpu/exec/adaptive.py", "_maybe_broadcast"),
    ("sail_tpu/exec/adaptive.py", "_maybe_coalesce_split"),
    ("sail_tpu/exec/adaptive.py", "_maybe_reorder"),
    ("sail_tpu/exec/admission.py", "JobAdmissionQueue.drain"),
    ("sail_tpu/analysis/anomaly.py", "classify"),
    ("sail_tpu/exec/router.py", "decide_stage"),
    ("sail_tpu/exec/router.py", "decide_split"),
    ("sail_tpu/exec/router.py", "decide_plan"),
)

_CLOCK_ATTRS = {"time", "monotonic", "perf_counter", "process_time",
                "time_ns", "monotonic_ns", "perf_counter_ns"}
_CLOCK_MODULES = {"time", "_time"}
_DATETIME_ATTRS = {"now", "utcnow", "today"}
_CONFIG_READERS = {"config_get", "truthy", "truthy_value"}
_RANDOM_BARE = {"random", "randint", "uniform", "choice", "shuffle",
                "randrange", "sample", "gauss"}


def _classify_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """(category, description) when the call is an impurity source."""
    f = node.func
    if isinstance(f, ast.Attribute):
        base = f.value
        if isinstance(base, ast.Name):
            if base.id in _CLOCK_MODULES and f.attr in _CLOCK_ATTRS:
                return "clock", f"{base.id}.{f.attr}()"
            if base.id in ("datetime", "date") and \
                    f.attr in _DATETIME_ATTRS:
                return "clock", f"{base.id}.{f.attr}()"
            if base.id == "random":
                return "random", f"random.{f.attr}()"
            if base.id == "os" and f.attr in ("getenv", "getenvb"):
                return "config", f"os.{f.attr}()"
        if isinstance(base, ast.Attribute) and base.attr == "environ" \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "os":
            return "config", "os.environ access"
        if isinstance(base, ast.Attribute) and \
                base.attr in ("datetime", "date") and \
                f.attr in _DATETIME_ATTRS:
            return "clock", f"datetime.{f.attr}()"
    elif isinstance(f, ast.Name):
        if f.id == "id" and len(node.args) == 1:
            return "id", "id()"
        if f.id in _CONFIG_READERS:
            return "config", f"{f.id}(…) config re-read"
        if f.id in ("monotonic", "perf_counter", "process_time"):
            return "clock", f"{f.id}()"
    return None


def _module_functions(tree: ast.AST
                      ) -> Dict[str, Tuple[Optional[str], ast.AST]]:
    """qualname → (class name or None, def node) for every module-level
    function and class method."""
    out: Dict[str, Tuple[Optional[str], ast.AST]] = {}
    for stmt in getattr(tree, "body", ()):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out[stmt.name] = (None, stmt)
        elif isinstance(stmt, ast.ClassDef):
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    out[f"{stmt.name}.{sub.name}"] = (stmt.name, sub)
    return out


def _signal_default_exempt(fn: ast.AST) -> Set[ast.AST]:
    """Call nodes exempt under the injected-signal default idiom:
    ``if X is None: X = EXPR`` / ``X = EXPR if X is None else X`` for a
    parameter ``X`` — the live path fills an omitted recorded signal,
    replay passes the recorded value."""
    params = set()
    a = fn.args
    for arg in list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs):
        params.add(arg.arg)

    def _is_none_test(test: ast.AST) -> Optional[str]:
        if isinstance(test, ast.Compare) and \
                isinstance(test.left, ast.Name) and \
                test.left.id in params and \
                len(test.ops) == 1 and len(test.comparators) == 1 and \
                isinstance(test.comparators[0], ast.Constant) and \
                test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return test.left.id
            if isinstance(test.ops[0], ast.IsNot):
                return f"!{test.left.id}"
        return None

    exempt: Set[ast.AST] = set()

    def mark(expr: ast.AST) -> None:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                exempt.add(sub)

    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            name = _is_none_test(node.test)
            if name and not name.startswith("!") and \
                    len(node.body) == 1 and \
                    isinstance(node.body[0], ast.Assign) and \
                    len(node.body[0].targets) == 1 and \
                    isinstance(node.body[0].targets[0], ast.Name) and \
                    node.body[0].targets[0].id == name:
                mark(node.body[0].value)
        elif isinstance(node, ast.IfExp):
            name = _is_none_test(node.test)
            if name is None:
                continue
            if name.startswith("!"):
                name = name[1:]
                filler = node.orelse  # X if X is not None else EXPR
                kept = node.body
            else:
                filler = node.body    # EXPR if X is None else X
                kept = node.orelse
            if isinstance(kept, ast.Name) and kept.id == name:
                mark(filler)
    return exempt


def _set_iteration_sites(fn: ast.AST) -> List[Tuple[int, str]]:
    """``for`` loops iterating a value that is syntactically a set
    (literal, comprehension, or ``set(...)`` built in this function)
    without a ``sorted()`` wrap — iteration order then depends on hash
    seeding and insertion history, which replay does not record."""
    set_names: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            v = node.value
            if isinstance(v, (ast.Set, ast.SetComp)) or (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Name)
                    and v.func.id in ("set", "frozenset")):
                set_names.add(node.targets[0].id)
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        it = node.iter
        if isinstance(it, (ast.Set, ast.SetComp)):
            out.append((node.lineno, "a set literal"))
        elif isinstance(it, ast.Call) and \
                isinstance(it.func, ast.Name) and \
                it.func.id in ("set", "frozenset"):
            out.append((node.lineno, "set(...)"))
        elif isinstance(it, ast.Name) and it.id in set_names:
            out.append((node.lineno, f"set {it.id!r}"))
    return out


def decision_purity_violations(
        ctx: LintContext,
        targets: Iterable[Tuple[str, str]] = DECISION_FUNCTIONS,
        lint_id: str = "decision-purity") -> List[Violation]:
    out: List[Violation] = []
    targets = list(targets)
    target_set = set(targets)
    for relpath, root_qual in targets:
        tree = ctx.tree(relpath)
        if tree is None:
            out.append(Violation(lint_id, relpath, 0, "cannot parse"))
            continue
        index = _module_functions(tree)
        if root_qual not in index:
            out.append(Violation(
                lint_id, relpath, 0,
                f"decision function {root_qual} not found (update "
                f"concurrency.DECISION_FUNCTIONS)"))
            continue
        seen: Set[str] = set()
        queue: List[Tuple[str, Tuple[str, ...]]] = [(root_qual, ())]
        findings: Dict[Tuple[str, int, str], str] = {}
        while queue:
            qual, chain = queue.pop(0)
            if qual in seen:
                continue
            seen.add(qual)
            cls_name, fn = index[qual]
            exempt = _signal_default_exempt(fn)
            via = "".join(f" (via {c})" for c in chain[:1])
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    if node in exempt:
                        continue
                    got = _classify_call(node)
                    if got is not None:
                        cat, desc = got
                        findings.setdefault(
                            (qual, node.lineno, cat),
                            f"{desc} in {qual}{via}")
                    # same-module traversal (skip other targets:
                    # they are audited independently)
                    callee = None
                    f = node.func
                    if isinstance(f, ast.Name) and f.id in index:
                        callee = f.id
                    elif isinstance(f, ast.Attribute) and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id == "self" and cls_name and \
                            f"{cls_name}.{f.attr}" in index:
                        callee = f"{cls_name}.{f.attr}"
                    if callee is not None and \
                            (relpath, callee) not in target_set:
                        queue.append((callee, chain + (qual,)))
            for line, desc in _set_iteration_sites(fn):
                findings.setdefault(
                    (qual, line, "set-iteration"),
                    f"iteration over {desc} in {qual}{via}")
        for (qual, line, cat), desc in sorted(findings.items()):
            key = (relpath, root_qual, cat)
            if key in allowlists.DECISION_PURITY:
                continue
            out.append(Violation(
                lint_id, relpath, line,
                f"decision function {root_qual} is not closed over its "
                f"recorded signals: {desc} [{cat}] — route the value "
                f"in as a signal argument (the `x = read() if x is "
                f"None else x` default-fill is the sanctioned shape) "
                f"or allowlist with a reason in "
                f"allowlists.DECISION_PURITY"))
    return out


def lint_decision_purity(ctx: LintContext) -> List[Violation]:
    """The pure decision functions (autoscaler evaluate, AQE rewrites,
    admission DRR, anomaly verdicts, router.decide_*) are closed over
    their recorded-signal parameters: no clocks, random, id(),
    unordered-set iteration, or config re-reads in their same-module
    closure."""
    return decision_purity_violations(ctx)


# ---------------------------------------------------------------------------
# compat: the historical ``locks`` lint, now a cluster.py slice of the
# generalized passes (the hardcoded _running/registry checks it used to
# hand-roll are exactly what passes 1 and 3 infer)
# ---------------------------------------------------------------------------

def cluster_locks_compat(ctx: LintContext) -> List[Violation]:
    out = guarded_field_violations(
        ctx, ("sail_tpu/exec/cluster.py",), "locks")
    table = {key: spec for key, spec in ACTOR_CONFINEMENT.items()
             if key[0] == "sail_tpu/exec/cluster.py"}
    out.extend(actor_confinement_violations(ctx, table, "locks"))
    return out
