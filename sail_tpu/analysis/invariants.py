"""Plan-invariant validator.

``validate_plan`` walks a resolved plan tree (``plan/nodes.py``) and
checks, per node, the structural invariants every optimizer pass must
preserve:

- output-schema arity/dtype consistency with child schemas;
- every ``BoundRef`` index in range of the child schema (and its
  recorded dtype in the same type family as the child field);
- join-key arity and dtype agreement on both sides;
- ``RuntimeFilterTarget`` edges pointing at live ``ScanExec`` leaves in
  the named subtree, with in-range key/column ordinals (and no orphan
  scan-side edges whose join vanished);
- scan ``predicates``/``runtime_predicates`` conjuncts referencing real
  (projected) columns;
- no duplicate/dangling scan projection names after ``prune_columns``
  remapping.

A violation raises :class:`PlanInvariantError` naming the offending
pass (``after``), node type, and invariant id — a bad remap surfaces at
the pass that introduced it instead of as a wrong answer or an opaque
jit shape error deep in ``exec/local.py``.

``validate_job_graph`` mirrors a lighter stage-boundary check for
``exec/job_graph.py``: shuffle channel counts and stage input schemas
must agree before tasks ship.

Gated by ``analysis.validate_plans`` (surfaced as
``spark.sail.analysis.validatePlans``): ``off`` disables, ``full``
validates after every pass, the default (``true``/``auto``) validates
after every pass under pytest and once — after the final pass — in
production, so steady-state queries pay one cheap walk.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Set, Tuple

from ..spec import data_type as dt

_PN = None
_RX = None


def _mods():
    """plan.nodes / plan.rex, imported lazily (plan/ imports us)."""
    global _PN, _RX
    if _PN is None:
        from ..plan import nodes as pn
        from ..plan import rex as rx
        _PN, _RX = pn, rx
    return _PN, _RX


class PlanInvariantError(RuntimeError):
    """A plan failed structural validation.

    ``invariant`` is a stable short id (e.g. ``boundref.range``),
    ``after`` names the pass whose output was being checked, and
    ``node_type`` the offending plan node class."""

    def __init__(self, invariant: str, message: str, *, node=None,
                 after: str = ""):
        self.invariant = invariant
        self.after = after
        self.node_type = type(node).__name__ if node is not None else ""
        where = f" [after {after}]" if after else ""
        at = f" at {self.node_type}" if self.node_type else ""
        super().__init__(f"{invariant}{where}{at}: {message}")


# ---------------------------------------------------------------------------
# gating
# ---------------------------------------------------------------------------

VALIDATE_OFF = "off"        # never validate
VALIDATE_FINAL = "final"    # one walk after the last optimizer pass
VALIDATE_FULL = "full"      # after resolve and after every pass


def validation_mode(override: Optional[str] = None) -> str:
    """Resolve the validation mode from ``analysis.validate_plans``
    (or the session-conf ``override`` string when given). Default
    ``true``/``auto`` → every pass under pytest, final-only otherwise."""
    value = override
    if value is None:
        from ..config import get as config_get
        value = config_get("analysis.validate_plans", "auto")
    value = str(value).strip().lower()
    if value in ("0", "false", "no", "off"):
        return VALIDATE_OFF
    if value == "full":
        return VALIDATE_FULL
    if value == "final":
        return VALIDATE_FINAL
    # PYTEST_CURRENT_TEST is set only while a test runs — checking
    # sys.modules for pytest would escalate any process that merely
    # imports it (dev tooling, embedded runners) to full validation
    under_pytest = "PYTEST_CURRENT_TEST" in os.environ
    return VALIDATE_FULL if under_pytest else VALIDATE_FINAL


# ---------------------------------------------------------------------------
# dtype families — the agreement granularity for join keys / unions.
# Exact dtype equality is too strict for plans the resolver legitimately
# produces (decimal precisions differ across branches; int widths mix
# under literal folding), but family drift (int key joined to a string
# key after a bad remap) is always a bug.
# ---------------------------------------------------------------------------

def _family(d: dt.DataType) -> str:
    if isinstance(d, dt.NullType):
        return "null"
    if isinstance(d, dt.BooleanType):
        return "bool"
    if isinstance(d, (dt.ByteType, dt.ShortType, dt.IntegerType,
                      dt.LongType)):
        return "int"
    if isinstance(d, (dt.FloatType, dt.DoubleType)):
        return "float"
    if isinstance(d, dt.DecimalType):
        return "decimal"
    if isinstance(d, dt.StringType):
        return "string"
    if isinstance(d, dt.BinaryType):
        return "binary"
    if isinstance(d, dt.DateType):
        return "date"
    if isinstance(d, dt.TimestampType):
        return "timestamp"
    if isinstance(d, dt.TimeType):
        return "time"
    if isinstance(d, (dt.DayTimeIntervalType, dt.YearMonthIntervalType,
                      dt.CalendarIntervalType)):
        return "interval"
    return "nested"  # struct / array / map / variant / udt


def _compatible(a: dt.DataType, b: dt.DataType) -> bool:
    fa, fb = _family(a), _family(b)
    return fa == fb or "null" in (fa, fb)


# ---------------------------------------------------------------------------
# expression checks
# ---------------------------------------------------------------------------

def _check_rex(r, arity: int, schema, *, after: str, node,
               invariant: str = "boundref.range",
               validate_subplans: bool = True) -> None:
    """Every BoundRef under ``r`` must index into ``schema`` (length
    ``arity``) and agree with the bound field's type family; embedded
    scalar-subquery plans validate recursively."""
    pn, rx = _mods()
    for sub in rx.walk(r):
        if isinstance(sub, rx.BoundRef):
            if not (0 <= sub.index < arity):
                raise PlanInvariantError(
                    invariant,
                    f"BoundRef #{sub.index} ({sub.name!r}) out of range "
                    f"of a {arity}-column child schema",
                    node=node, after=after)
            if schema is not None and \
                    not _compatible(sub.dtype, schema[sub.index].dtype):
                raise PlanInvariantError(
                    "boundref.dtype",
                    f"BoundRef #{sub.index} ({sub.name!r}) recorded as "
                    f"{sub.dtype.simple_string()} but the child column is "
                    f"{schema[sub.index].dtype.simple_string()}",
                    node=node, after=after)
        elif isinstance(sub, rx.RScalarSubquery) and validate_subplans:
            if sub.plan is not None:
                validate_plan(sub.plan, after=after)


# ---------------------------------------------------------------------------
# node checks
# ---------------------------------------------------------------------------

def _child_schema(child, *, after: str, node):
    try:
        return tuple(child.schema)
    except Exception as e:  # noqa: BLE001 — a broken child schema IS the finding
        raise PlanInvariantError(
            "schema.computable",
            f"child {type(child).__name__} schema raises "
            f"{type(e).__name__}: {e}", node=node, after=after)


def _check_scan(p, *, after: str) -> None:
    pn, rx = _mods()
    names = [f.name for f in p.out_schema]
    if p.projection is not None:
        seen: Set[str] = set()
        for n in p.projection:
            if n not in names:
                raise PlanInvariantError(
                    "scan.projection",
                    f"projected column {n!r} is not in the scan's base "
                    f"schema {names}", node=p, after=after)
            if n in seen:
                raise PlanInvariantError(
                    "scan.duplicate_names",
                    f"duplicate projected column {n!r}", node=p,
                    after=after)
            seen.add(n)
    schema = tuple(p.schema)
    for which, preds in (("scan.predicates", p.predicates),
                        ("scan.runtime_predicates", p.runtime_predicates)):
        for c in preds:
            _check_rex(c, len(schema), schema, after=after, node=p,
                       invariant=which)
    for t in p.runtime_filters:
        if not (0 <= t.column < len(schema)):
            raise PlanInvariantError(
                "rtf.column",
                f"runtime-filter edge rf{t.fid} targets column "
                f"#{t.column} of a {len(schema)}-column scan",
                node=p, after=after)
        if schema[t.column].name != t.name:
            raise PlanInvariantError(
                "rtf.column",
                f"runtime-filter edge rf{t.fid} names column {t.name!r} "
                f"but scan column #{t.column} is "
                f"{schema[t.column].name!r}", node=p, after=after)


def _check_join(p, *, after: str) -> None:
    pn, rx = _mods()
    if p.join_type not in ("inner", "left", "right", "full", "semi",
                           "anti", "cross"):
        raise PlanInvariantError(
            "join.type", f"unknown join type {p.join_type!r}", node=p,
            after=after)
    left_schema = _child_schema(p.left, after=after, node=p)
    right_schema = _child_schema(p.right, after=after, node=p)
    if len(p.left_keys) != len(p.right_keys):
        raise PlanInvariantError(
            "join.keys_arity",
            f"{len(p.left_keys)} left keys vs {len(p.right_keys)} right "
            f"keys", node=p, after=after)
    for k in p.left_keys:
        _check_rex(k, len(left_schema), left_schema, after=after, node=p)
    for k in p.right_keys:
        _check_rex(k, len(right_schema), right_schema, after=after,
                   node=p)
    for lk, rk in zip(p.left_keys, p.right_keys):
        lt, rt = rx.rex_type(lk), rx.rex_type(rk)
        if not _compatible(lt, rt):
            raise PlanInvariantError(
                "join.key_dtype",
                f"join key dtypes disagree: {lt.simple_string()} vs "
                f"{rt.simple_string()}", node=p, after=after)
    if p.residual is not None:
        combined = left_schema + right_schema
        _check_rex(p.residual, len(combined), combined, after=after,
                   node=p)
    for t in p.runtime_filters:
        if t.side not in ("probe", "build"):
            raise PlanInvariantError(
                "rtf.side",
                f"runtime-filter edge rf{t.fid} has side {t.side!r} "
                f"(expected probe|build)", node=p, after=after)
        if not (0 <= t.key < len(p.left_keys)):
            raise PlanInvariantError(
                "rtf.key",
                f"runtime-filter edge rf{t.fid} names key ordinal "
                f"#{t.key} of a {len(p.left_keys)}-key join", node=p,
                after=after)
        subtree = p.left if t.side == "probe" else p.right
        scan = _scan_with_fid(subtree, t.fid)
        if scan is None:
            raise PlanInvariantError(
                "rtf.dangling",
                f"runtime-filter edge rf{t.fid} ({t.side}:{t.name}) has "
                f"no live ScanExec target in the {t.side} subtree",
                node=p, after=after)


def _scan_with_fid(p, fid: int):
    pn, _rx = _mods()
    for node in pn.walk_plan(p):
        if isinstance(node, pn.ScanExec) and \
                any(t.fid == fid for t in node.runtime_filters):
            return node
    return None


def _check_aggregate(p, *, after: str) -> None:
    in_schema = _child_schema(p.input, after=after, node=p)
    arity = len(in_schema)
    if len(p.out_names) != len(p.group_indices) + len(p.aggs):
        raise PlanInvariantError(
            "agg.out_names",
            f"{len(p.out_names)} output names for "
            f"{len(p.group_indices)} groups + {len(p.aggs)} aggregates",
            node=p, after=after)
    for gi in p.group_indices:
        if not (0 <= gi < arity):
            raise PlanInvariantError(
                "agg.group_range",
                f"group index #{gi} out of range of a {arity}-column "
                f"input", node=p, after=after)
    for a in p.aggs:
        if a.arg is not None and not (0 <= a.arg < arity):
            raise PlanInvariantError(
                "agg.arg_range",
                f"{a.fn} argument #{a.arg} out of range of a "
                f"{arity}-column input", node=p, after=after)
        if a.filter is not None:
            _check_rex(a.filter, arity, in_schema, after=after, node=p)


def _check_union(p, *, after: str) -> None:
    if not p.inputs:
        raise PlanInvariantError("union.arity", "UNION of zero inputs",
                                 node=p, after=after)
    first = _child_schema(p.inputs[0], after=after, node=p)
    for child in p.inputs[1:]:
        s = _child_schema(child, after=after, node=p)
        if len(s) != len(first):
            raise PlanInvariantError(
                "union.arity",
                f"UNION branches disagree on arity: {len(first)} vs "
                f"{len(s)}", node=p, after=after)
        for i, (fa, fb) in enumerate(zip(first, s)):
            if not _compatible(fa.dtype, fb.dtype):
                raise PlanInvariantError(
                    "union.dtype",
                    f"UNION column #{i} dtypes disagree: "
                    f"{fa.dtype.simple_string()} vs "
                    f"{fb.dtype.simple_string()}", node=p, after=after)


def _check_window(p, *, after: str) -> None:
    in_schema = _child_schema(p.input, after=after, node=p)
    arity = len(in_schema)
    if len(p.out_names) != len(p.windows):
        raise PlanInvariantError(
            "window.out_names",
            f"{len(p.out_names)} output names for {len(p.windows)} "
            f"window functions", node=p, after=after)
    for w in p.windows:
        if w.arg is not None and not (0 <= w.arg < arity):
            raise PlanInvariantError(
                "window.arg_range",
                f"{w.function} argument #{w.arg} out of range of a "
                f"{arity}-column input", node=p, after=after)
        for pi in w.partition_indices:
            if not (0 <= pi < arity):
                raise PlanInvariantError(
                    "window.partition_range",
                    f"partition index #{pi} out of range", node=p,
                    after=after)
        for k in w.order_keys:
            _check_rex(k.expr, arity, in_schema, after=after, node=p)


def _validate_node(p, *, after: str) -> None:
    pn, rx = _mods()
    if isinstance(p, pn.ScanExec):
        _check_scan(p, after=after)
        return
    if isinstance(p, pn.JoinExec):
        _check_join(p, after=after)
        return
    if isinstance(p, pn.AggregateExec):
        _check_aggregate(p, after=after)
        return
    if isinstance(p, pn.UnionExec):
        _check_union(p, after=after)
        return
    if isinstance(p, pn.WindowExec):
        _check_window(p, after=after)
        return
    if isinstance(p, pn.ProjectExec):
        in_schema = _child_schema(p.input, after=after, node=p)
        for _n, e in p.exprs:
            _check_rex(e, len(in_schema), in_schema, after=after, node=p)
        return
    if isinstance(p, pn.FilterExec):
        in_schema = _child_schema(p.input, after=after, node=p)
        if p.condition is None:
            raise PlanInvariantError("filter.condition",
                                     "Filter without a condition",
                                     node=p, after=after)
        _check_rex(p.condition, len(in_schema), in_schema, after=after,
                   node=p)
        if _family(rx.rex_type(p.condition)) not in ("bool", "null"):
            raise PlanInvariantError(
                "filter.dtype",
                f"filter condition has dtype "
                f"{rx.rex_type(p.condition).simple_string()}, expected "
                f"boolean", node=p, after=after)
        return
    if isinstance(p, pn.SortExec):
        in_schema = _child_schema(p.input, after=after, node=p)
        for k in p.keys:
            _check_rex(k.expr, len(in_schema), in_schema, after=after,
                       node=p)
        return
    if isinstance(p, pn.LimitExec):
        if p.limit is not None and p.limit < 0:
            raise PlanInvariantError("limit.negative",
                                     f"negative limit {p.limit}",
                                     node=p, after=after)
        if p.offset < 0:
            raise PlanInvariantError("limit.negative",
                                     f"negative offset {p.offset}",
                                     node=p, after=after)
        return
    if isinstance(p, pn.GenerateExec):
        in_schema = _child_schema(p.input, after=after, node=p)
        for r in p.args:
            _check_rex(r, len(in_schema), in_schema, after=after, node=p)
        for _n, r in p.passthrough:
            _check_rex(r, len(in_schema), in_schema, after=after, node=p)
        return
    if isinstance(p, pn.GroupMapExec):
        in_schema = _child_schema(p.input, after=after, node=p)
        for ki in p.key_indices:
            if not (0 <= ki < len(in_schema)):
                raise PlanInvariantError(
                    "groupmap.key_range",
                    f"key index #{ki} out of range", node=p, after=after)
        return
    if isinstance(p, pn.CoGroupMapExec):
        ls = _child_schema(p.left, after=after, node=p)
        rs = _child_schema(p.right, after=after, node=p)
        for ki in p.left_keys:
            if not (0 <= ki < len(ls)):
                raise PlanInvariantError(
                    "groupmap.key_range",
                    f"left key index #{ki} out of range", node=p,
                    after=after)
        for ki in p.right_keys:
            if not (0 <= ki < len(rs)):
                raise PlanInvariantError(
                    "groupmap.key_range",
                    f"right key index #{ki} out of range", node=p,
                    after=after)
        return
    # OneRow/Values/Range/Udtf/MapPartitions/StageInputExec…: leaf or
    # schema-opaque nodes with nothing positional to get wrong


def validate_plan(plan, *, after: str = "resolve") -> None:
    """Validate every node of ``plan`` (recursing into scalar-subquery
    plans). Raises :class:`PlanInvariantError` on the first violation;
    returns None when the plan is well-formed."""
    pn, rx = _mods()
    join_fids: Set[int] = set()
    scan_edges: List = []
    for node in pn.walk_plan(plan):
        _validate_node(node, after=after)
        if isinstance(node, pn.JoinExec):
            join_fids.update(t.fid for t in node.runtime_filters)
        elif isinstance(node, pn.ScanExec):
            scan_edges.extend((node, t) for t in node.runtime_filters)
    for scan, t in scan_edges:
        if t.fid not in join_fids:
            raise PlanInvariantError(
                "rtf.orphan",
                f"scan edge rf{t.fid} ({t.name}) has no JoinExec "
                f"carrying the same filter id", node=scan, after=after)


# ---------------------------------------------------------------------------
# fused-stage validation (plan/stages.py)
# ---------------------------------------------------------------------------

def validate_stage_split(plan, split) -> None:
    """The fused-stage invariant: the stage splitter must place every
    plan node in exactly one stage, and pipeline breakers may appear
    only at stage edges — a stage's interior (everything below its
    root) is exclusively Filter/Project operators and source leaves, so
    fusing a stage into one program can never swallow a materialization
    point."""
    pn, _rx = _mods()
    from ..plan import stages as st

    seen: Dict[int, int] = {}
    for stage in split.stages:
        if not stage.nodes or stage.nodes[0] is not stage.root:
            raise PlanInvariantError(
                "fusion.root",
                f"stage {stage.sid} nodes do not start at its root",
                node=stage.root, after="split_stages")
        for node in stage.nodes:
            if id(node) in seen:
                raise PlanInvariantError(
                    "fusion.duplicate",
                    f"{type(node).__name__} assigned to both stage "
                    f"{seen[id(node)]} and stage {stage.sid}",
                    node=node, after="split_stages")
            seen[id(node)] = stage.sid
        for node in stage.nodes[1:]:
            if not (isinstance(node, st.FUSABLE_OPS) or st.is_leaf(node)):
                raise PlanInvariantError(
                    "fusion.interior_breaker",
                    f"{type(node).__name__} (a pipeline breaker) sits "
                    f"inside stage {stage.sid} instead of at a stage "
                    f"edge", node=node, after="split_stages")
        # connectivity: every non-root member hangs off another member
        # (a disconnected member would be compiled into a program whose
        # dataflow never reaches it)
        for node in stage.nodes[1:]:
            if not any(any(c is node for c in m.children)
                       for m in stage.nodes if m is not node):
                raise PlanInvariantError(
                    "fusion.disconnected",
                    f"stage {stage.sid} member {type(node).__name__} "
                    f"is not a child of any other stage member",
                    node=node, after="split_stages")
    for node in pn.walk_plan(plan):
        if id(node) not in seen:
            raise PlanInvariantError(
                "fusion.coverage",
                f"{type(node).__name__} is in no stage", node=node,
                after="split_stages")


# ---------------------------------------------------------------------------
# stage-boundary validation (exec/job_graph.py)
# ---------------------------------------------------------------------------

def validate_job_graph(graph) -> None:
    """Lighter distributed-boundary check run by ``split_job`` before
    tasks ship: stage input schemas must agree with their producer's
    output schema, shuffle channel counts with the consumer's partition
    count, and shuffle keys must be in range of the producer schema."""
    pn, _rx = _mods()
    from ..exec.job_graph import InputMode, StageInputExec

    stages_by_id: Dict[int, object] = {}
    for stage in graph.stages:
        if stage.stage_id in stages_by_id:
            raise PlanInvariantError(
                "stage.duplicate_id",
                f"duplicate stage id {stage.stage_id}",
                after="split_job")
        stages_by_id[stage.stage_id] = stage
    for stage in graph.stages:
        inputs_by_id = {i.stage_id: i for i in stage.inputs}
        input_modes = {i.stage_id: i.mode for i in stage.inputs}
        for b in getattr(stage, "launch_after", ()):
            if b not in stages_by_id:
                raise PlanInvariantError(
                    "stage.unknown_input",
                    f"stage {stage.stage_id} barriered on unknown stage "
                    f"{b}", after="split_job")
        for sid in input_modes:
            if sid not in stages_by_id:
                raise PlanInvariantError(
                    "stage.unknown_input",
                    f"stage {stage.stage_id} consumes unknown stage "
                    f"{sid}", after="split_job")
            if sid >= stage.stage_id:
                raise PlanInvariantError(
                    "stage.cycle",
                    f"stage {stage.stage_id} consumes a later/equal "
                    f"stage {sid}", after="split_job")
        for node in pn.walk_plan(stage.plan):
            if not isinstance(node, StageInputExec):
                continue
            producer = stages_by_id.get(node.stage_id)
            if producer is None or node.stage_id not in input_modes:
                raise PlanInvariantError(
                    "stage.unknown_input",
                    f"stage {stage.stage_id} plan reads stage "
                    f"{node.stage_id} which is not among its declared "
                    f"inputs", after="split_job")
            prod_schema = _child_schema(producer.plan, after="split_job",
                                        node=node)
            leaf_schema = tuple(node.out_schema)
            if len(leaf_schema) != len(prod_schema):
                raise PlanInvariantError(
                    "stage.input_schema",
                    f"stage {stage.stage_id} expects "
                    f"{len(leaf_schema)} columns from stage "
                    f"{node.stage_id} which produces "
                    f"{len(prod_schema)}", after="split_job")
            for i, (fa, fb) in enumerate(zip(leaf_schema, prod_schema)):
                if not _compatible(fa.dtype, fb.dtype):
                    raise PlanInvariantError(
                        "stage.input_schema",
                        f"stage {stage.stage_id} input column #{i} "
                        f"({fa.name}) is {fa.dtype.simple_string()} but "
                        f"stage {node.stage_id} produces "
                        f"{fb.dtype.simple_string()}", after="split_job")
            mode = input_modes[node.stage_id]
            fetch_plan = getattr(inputs_by_id[node.stage_id],
                                 "fetch_plan", None)
            if fetch_plan is not None:
                _check_fetch_plan(stage, producer, fetch_plan)
            elif mode == InputMode.SHUFFLE:
                if producer.shuffle_keys is None:
                    raise PlanInvariantError(
                        "stage.channels",
                        f"stage {stage.stage_id} consumes stage "
                        f"{node.stage_id} over SHUFFLE but the producer "
                        f"declares no shuffle keys", after="split_job")
                if producer.num_channels < stage.num_partitions:
                    raise PlanInvariantError(
                        "stage.channels",
                        f"stage {stage.stage_id} runs "
                        f"{stage.num_partitions} tasks but producer "
                        f"stage {node.stage_id} routes only "
                        f"{producer.num_channels} channels",
                        after="split_job")
            elif mode == InputMode.BROADCAST:
                if producer.num_partitions != 1:
                    raise PlanInvariantError(
                        "stage.channels",
                        f"BROADCAST producer stage {node.stage_id} has "
                        f"{producer.num_partitions} partitions "
                        f"(expected 1)", after="split_job")
            elif mode == InputMode.FORWARD:
                # FORWARD task p reads producer partition p: the task
                # counts must agree or consumer tasks wait forever on
                # partitions the producer never makes (fewer) / extra
                # producer partitions are silently dropped (more)
                if producer.num_partitions != stage.num_partitions:
                    raise PlanInvariantError(
                        "stage.forward_arity",
                        f"stage {stage.stage_id} reads stage "
                        f"{node.stage_id} FORWARD with "
                        f"{stage.num_partitions} tasks but the producer "
                        f"runs {producer.num_partitions}",
                        after="split_job")
        if stage.shuffle_keys is not None:
            arity = len(_child_schema(stage.plan, after="split_job",
                                      node=stage.plan))
            for k in stage.shuffle_keys:
                if not (0 <= k < arity):
                    raise PlanInvariantError(
                        "stage.shuffle_keys",
                        f"stage {stage.stage_id} shuffle key #{k} out "
                        f"of range of its {arity}-column output",
                        after="split_job")


def _check_fetch_plan(stage, producer, fetch_plan) -> None:
    """Adaptive fetch assignments: one non-empty pair list per consumer
    task, every pair naming a real producer partition and a channel the
    producer actually routes (-1 = the whole unsplit task output, valid
    only for a producer that does not shuffle-write; -2 = every channel
    of the producer partition in one stream). Beyond per-pair range
    checks, COVERAGE must hold: every routed channel is consumed either
    exactly once across all tasks (whole channels and partition-splits
    — the per-task partition sets are disjoint and union to the full
    producer set) or replicated (every fetching task reads the FULL
    producer set, the split build side / converted broadcast shape) —
    a rewrite that drops or double-reads a channel slice would return
    silently wrong rows."""
    if len(fetch_plan) != stage.num_partitions:
        raise PlanInvariantError(
            "adaptive.fetch_plan",
            f"stage {stage.stage_id} has {stage.num_partitions} tasks "
            f"but the fetch plan for input stage {producer.stage_id} "
            f"covers {len(fetch_plan)}", after="adaptive")
    single_output = producer.shuffle_keys is None \
        or producer.num_channels <= 1
    by_channel: Dict[int, List[Set[int]]] = {}
    for task, pairs in enumerate(fetch_plan):
        if not pairs:
            raise PlanInvariantError(
                "adaptive.fetch_plan",
                f"stage {stage.stage_id} task {task} has an empty "
                f"fetch list for input stage {producer.stage_id}",
                after="adaptive")
        if len(set(pairs)) != len(pairs):
            raise PlanInvariantError(
                "adaptive.fetch_plan",
                f"stage {stage.stage_id} task {task} fetches a "
                f"(partition, channel) pair of stage "
                f"{producer.stage_id} twice", after="adaptive")
        task_channels: Dict[int, Set[int]] = {}
        for p, c in pairs:
            if not (0 <= p < producer.num_partitions):
                raise PlanInvariantError(
                    "adaptive.fetch_plan",
                    f"stage {stage.stage_id} task {task} fetches "
                    f"partition {p} of stage {producer.stage_id} which "
                    f"has {producer.num_partitions} partitions",
                    after="adaptive")
            if c == -1 and not single_output:
                raise PlanInvariantError(
                    "adaptive.fetch_plan",
                    f"stage {stage.stage_id} task {task} fetches "
                    f"channel -1 of shuffle-writing stage "
                    f"{producer.stage_id}", after="adaptive")
            if c >= producer.num_channels or c < -2:
                raise PlanInvariantError(
                    "adaptive.fetch_plan",
                    f"stage {stage.stage_id} task {task} fetches "
                    f"channel {c} of stage {producer.stage_id} which "
                    f"routes {producer.num_channels} channels",
                    after="adaptive")
            task_channels.setdefault(c, set()).add(p)
        for c, parts in task_channels.items():
            by_channel.setdefault(c, []).append(parts)
    full = set(range(producer.num_partitions))
    routed = {c for c in by_channel if c >= 0}
    if routed and routed != set(range(producer.num_channels)):
        raise PlanInvariantError(
            "adaptive.fetch_plan",
            f"stage {stage.stage_id} consumes channels "
            f"{sorted(routed)} of stage {producer.stage_id} but the "
            f"producer routes channels 0..{producer.num_channels - 1}",
            after="adaptive")
    for c, task_sets in by_channel.items():
        if all(parts == full for parts in task_sets):
            continue  # replicated channel (or a single full-set task)
        seen: Set[int] = set()
        for parts in task_sets:
            if seen & parts:
                raise PlanInvariantError(
                    "adaptive.fetch_plan",
                    f"stage {stage.stage_id} channel {c} of stage "
                    f"{producer.stage_id}: partition slices overlap "
                    f"without full replication", after="adaptive")
            seen |= parts
        if seen != full:
            raise PlanInvariantError(
                "adaptive.fetch_plan",
                f"stage {stage.stage_id} channel {c} of stage "
                f"{producer.stage_id}: producer partitions "
                f"{sorted(full - seen)} are fetched by no task",
                after="adaptive")


# ---------------------------------------------------------------------------
# adaptive-rewrite validation (exec/adaptive.py)
# ---------------------------------------------------------------------------

def stage_signature(stage) -> tuple:
    """The launch-relevant contract of a stage: its plan identity,
    partitioning, shuffle routing, and input wiring. A stage whose
    signature is unchanged is untouched by an adaptive rewrite."""
    return (id(stage.plan), stage.num_partitions, stage.shuffle_keys,
            stage.num_channels,
            tuple((i.stage_id, i.mode,
                   getattr(i, "fetch_plan", None)) for i in stage.inputs))


def validate_adaptive_rewrite(graph, frozen, before) -> None:
    """The adaptive invariant: a mid-flight plan rewrite may only touch
    the NOT-yet-launched suffix of the job graph — every frozen stage
    (scheduled, launched, or completed) must keep its exact signature —
    and the rewritten graph must still pass the full stage-boundary
    check before it replaces the pending suffix."""
    for stage in graph.stages:
        if stage.stage_id in frozen and \
                stage_signature(stage) != before.get(stage.stage_id):
            raise PlanInvariantError(
                "adaptive.frozen",
                f"adaptive rewrite touched launched/completed stage "
                f"{stage.stage_id}", after="adaptive")
    validate_job_graph(graph)
