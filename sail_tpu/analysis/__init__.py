"""Static analysis: plan-invariant validation + repo-wide drift lints.

Two pillars (reference role: DataFusion's plan sanity/invariant checker
that keeps a multi-pass optimizer honest, and the registry-drift test
pattern generalized to every declared-vs-used surface in the repo):

- :mod:`.invariants` — ``validate_plan`` walks a resolved plan tree and
  checks structural well-formedness (BoundRef ranges, schema agreement,
  join-key dtypes, runtime-filter edge liveness); the optimizer runs it
  after resolve and after every pass, and ``validate_job_graph`` mirrors
  a lighter stage-boundary check before distributed tasks ship.
- :mod:`.lints` — AST/text lints over the repo itself (config-key
  drift, fault-site drift, proto freshness, host-sync allowlisting,
  lock discipline, metrics-registry drift), run by
  ``scripts/sail_lint.py`` and as tier-1 tests.
"""

from .invariants import (  # noqa: F401
    PlanInvariantError,
    validate_job_graph,
    validate_plan,
    validate_stage_split,
    validation_mode,
)
