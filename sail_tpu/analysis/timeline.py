"""Derived views over the event stream: task timeline, decision
sequence, and critical-path attribution.

Everything here operates on a plain list of event records (dicts in the
:mod:`sail_tpu.events` shape) so it works identically on the live
in-memory ring (``system.telemetry.task_timeline``), on a durable JSONL
log replayed offline (``scripts/sail_timeline.py``), and in tests — the
event log is the single source of truth, the live run holds no
privileged state.

Critical-path attribution walks the task/fetch dependency edges the
events record: starting from the last-finishing task of a query's job,
each hop charges the task's wall time to categories —

- ``queue``      dispatch → worker start (slot/governor wait)
- ``fetch-wait`` time the task blocked on stage-input fetches
- ``compile``    JIT compile events inside the task's execution window
- ``compute``    the execution remainder
- ``replan``     gap between the gating producer's finish and this
                 task's dispatch when adaptive decisions fired inside it
                 (otherwise the gap is ``queue``)

and follows the fetch edge to the producer task that finished LAST (the
fetch that actually gated), until a leaf task with no inputs.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

#: decision-bearing event types, in the order the replay reports them
DECISION_TYPES = ("adaptive_applied", "adaptive_rollback",
                  "speculation_launch", "speculation_win",
                  "worker_evict", "worker_quarantine",
                  "epoch_stage", "epoch_commit", "epoch_replay",
                  "admission_enqueue", "admission_admit",
                  "admission_defer", "admission_shed", "quota_debit",
                  "deadline_cancel", "backend_route",
                  "task_resident", "marker_inject", "marker_align",
                  "backpressure")

CATEGORIES = ("compute", "fetch-wait", "queue", "compile", "replan",
              "credit-stall")


def _for_query(events: List[dict],
               query_id: Optional[str]) -> List[dict]:
    if query_id is None:
        return list(events)
    return [e for e in events if e.get("query_id") == query_id]


def query_ids(events: List[dict]) -> List[str]:
    """Distinct non-empty query ids, in first-appearance order."""
    seen: Dict[str, None] = {}
    for e in events:
        q = e.get("query_id")
        if q:
            seen.setdefault(q, None)
    return list(seen)


# ---------------------------------------------------------------------------
# task timeline
# ---------------------------------------------------------------------------

def task_timeline(events: List[dict],
                  query_id: Optional[str] = None) -> List[dict]:
    """One row per task ATTEMPT: dispatch/start/finish timestamps and
    the derived queue/run/fetch-wait durations, ordered by (query, job,
    stage, partition, attempt)."""
    rows: Dict[Tuple, dict] = {}
    for e in _for_query(events, query_id):
        t = e.get("type")
        if t not in ("task_dispatch", "task_start", "task_finish"):
            continue
        key = (e.get("query_id", ""), e.get("job_id", ""),
               e.get("stage"), e.get("partition"), e.get("attempt"))
        row = rows.setdefault(key, {
            "query_id": key[0], "job_id": key[1], "stage": key[2],
            "partition": key[3], "attempt": key[4], "worker": "",
            "dispatch_time": None, "start_time": None,
            "finish_time": None, "state": "", "rows_out": 0,
            "fetch_wait_ms": 0.0})
        if t == "task_dispatch":
            row["dispatch_time"] = e.get("ts")
            row["worker"] = e.get("worker", "") or row["worker"]
        elif t == "task_start":
            row["start_time"] = e.get("ts")
            row["worker"] = e.get("worker", "") or row["worker"]
        else:
            row["finish_time"] = e.get("ts")
            row["state"] = e.get("state", "")
            row["rows_out"] = int(e.get("rows", 0) or 0)
            row["fetch_wait_ms"] = float(e.get("fetch_wait_ms", 0.0)
                                         or 0.0)
            row["worker"] = e.get("worker", "") or row["worker"]
    out = []
    for key in sorted(rows, key=lambda k: tuple(
            (v is None, v) for v in k)):
        row = rows[key]
        d, s, f = (row["dispatch_time"], row["start_time"],
                   row["finish_time"])
        row["queue_ms"] = round((s - d) * 1000.0, 3) \
            if d is not None and s is not None else None
        row["run_ms"] = round((f - s) * 1000.0, 3) \
            if s is not None and f is not None else None
        out.append(row)
    return out


# ---------------------------------------------------------------------------
# decision sequence
# ---------------------------------------------------------------------------

def decisions(events: List[dict],
              query_id: Optional[str] = None) -> List[dict]:
    """Decision events in log (append) order — the sequence a replay
    must reproduce bit-identically for a fixed fault seed."""
    return [e for e in _for_query(events, query_id)
            if e.get("type") in DECISION_TYPES]


def adaptive_decisions(events: List[dict],
                       query_id: Optional[str] = None) -> List[dict]:
    """The adaptive decision records exactly as the live profile stores
    them (``QueryProfile.adaptive_events``): the ``detail`` payload of
    every ``adaptive_applied`` event, in order."""
    out = []
    for e in _for_query(events, query_id):
        if e.get("type") != "adaptive_applied":
            continue
        try:
            out.append(json.loads(e.get("detail", "") or "{}"))
        except ValueError:
            out.append({"kind": e.get("kind", ""), "detail": "malformed"})
    return out


# ---------------------------------------------------------------------------
# critical-path attribution
# ---------------------------------------------------------------------------

def _winning_tasks(evs: List[dict]) -> Dict[Tuple, dict]:
    """Per (job_id, stage, partition): the attempt whose ``task_finish``
    the driver accepted as succeeded (first in log order), merged with
    its dispatch/start events. Keys carry the job id — one query
    profile can span several jobs (a streaming trigger dispatches more
    than one graph, each numbering stages from 0), and their tasks must
    never collide."""
    finishes: Dict[Tuple, dict] = {}
    for e in evs:
        if e.get("type") == "task_finish" and \
                e.get("state") == "succeeded":
            key = (e.get("job_id", ""), e.get("stage"),
                   e.get("partition"))
            finishes.setdefault(key, dict(e))
    for e in evs:
        t = e.get("type")
        if t not in ("task_dispatch", "task_start"):
            continue
        key = (e.get("job_id", ""), e.get("stage"), e.get("partition"))
        win = finishes.get(key)
        if win is None or e.get("attempt") != win.get("attempt"):
            continue
        win["dispatch_ts" if t == "task_dispatch" else "start_ts"] = \
            e.get("ts")
    return finishes


def _fetch_edges(evs: List[dict]) -> Dict[Tuple, List[Tuple]]:
    """(job_id, dst_stage, dst_partition) → fetched (job_id, producer
    stage, producer partition) keys, from ``fetch_end`` events."""
    edges: Dict[Tuple, List[Tuple]] = {}
    for e in evs:
        if e.get("type") != "fetch_end":
            continue
        job = e.get("job_id", "")
        dst = (job, e.get("dst_stage"), e.get("dst_partition"))
        edges.setdefault(dst, []).append(
            (job, e.get("stage"), e.get("partition")))
    return edges


def _compiles_in(evs: List[dict], t0: float, t1: float,
                 task: Optional[str]) -> float:
    """JIT compile ms attributable to one task's execution window.
    Worker-shipped compile events carry the driver-stamped ``task``
    envelope ("s<stage>p<partition>a<attempt>") and match by identity;
    unstamped events (driver/local compiles) fall back to the time
    window."""
    ms = 0.0
    for e in evs:
        if e.get("type") != "compile" or e.get("ts") is None:
            continue
        if e.get("source") == "persistent":
            # a persistent-cache load bound a stored executable:
            # nothing compiled, and the profile's compile phase agrees
            # (note_compile_loaded charges no compile time)
            continue
        stamped = e.get("task")
        if stamped is not None:
            if task is None or stamped != task:
                continue
        elif not (t0 <= e["ts"] <= t1):
            continue
        ms += float(e.get("ms", 0.0) or 0.0)
    return ms


def _credit_stalls_in(evs: List[dict], t0: float, t1: float,
                      task: Optional[str]) -> float:
    """Credit-stall ms attributable to one task's execution window:
    worker-shipped ``backpressure`` events carry the driver-stamped
    ``task`` envelope and match by identity; unstamped (driver-side)
    events fall back to the time window."""
    ms = 0.0
    for e in evs:
        if e.get("type") != "backpressure" or e.get("ts") is None:
            continue
        stamped = e.get("task")
        if stamped is not None:
            if task is None or stamped != task:
                continue
        elif not (t0 <= e["ts"] <= t1):
            continue
        ms += float(e.get("stall_ms", 0.0) or 0.0)
    return ms


def wait_evidence(events: List[dict],
                  query_id: Optional[str] = None) -> Dict[str, dict]:
    """Per-category wait evidence for one query, from its events alone:
    the raw material the anomaly classifier (analysis/anomaly.py) ranks
    a verdict from. Each entry is ``{"ms", "events"}`` — the wall time
    the events themselves carry (retrace compile ms excluding the
    benign first-ever cold compile, backpressure ``stall_ms``,
    ``admission_admit`` ``waited_ms``, ``task_finish``
    ``fetch_wait_ms``) and how many events contributed;
    ``governor_defer`` carries no duration, so it contributes a count
    only. Works identically on the live ring and a replayed durable
    log."""
    out: Dict[str, dict] = {
        "retrace": {"ms": 0.0, "events": 0},
        "credit-stall": {"ms": 0.0, "events": 0},
        "admission-queue-wait": {"ms": 0.0, "events": 0},
        "fetch-wait": {"ms": 0.0, "events": 0},
        "governor-defer": {"ms": 0.0, "events": 0},
    }
    for e in _for_query(events, query_id):
        t = e.get("type")
        if t == "retrace":
            if e.get("cause") == "first-ever":
                continue
            out["retrace"]["ms"] += float(e.get("ms", 0.0) or 0.0)
            out["retrace"]["events"] += 1
        elif t == "backpressure":
            ms = float(e.get("stall_ms", 0.0) or 0.0)
            if ms > 0.0:
                out["credit-stall"]["ms"] += ms
                out["credit-stall"]["events"] += 1
        elif t == "admission_admit":
            ms = float(e.get("waited_ms", 0.0) or 0.0)
            if ms > 0.0:
                out["admission-queue-wait"]["ms"] += ms
                out["admission-queue-wait"]["events"] += 1
        elif t == "task_finish":
            ms = float(e.get("fetch_wait_ms", 0.0) or 0.0)
            if ms > 0.0:
                out["fetch-wait"]["ms"] += ms
                out["fetch-wait"]["events"] += 1
        elif t == "governor_defer":
            out["governor-defer"]["events"] += 1
    for v in out.values():
        v["ms"] = round(v["ms"], 3)
    return out


def continuous_progress(events: List[dict],
                        query_id: Optional[str] = None) -> List[dict]:
    """Marker progress of a continuous pipeline, replayable from the
    log alone: per marker, the inject time, every mid-flight alignment
    (stage/partition, wait, buffered bytes), and the credit stalls that
    landed between this inject and the next."""
    evs = _for_query(events, query_id)
    markers: Dict[int, dict] = {}
    order: List[int] = []
    for e in evs:
        t = e.get("type")
        if t == "marker_inject":
            m = int(e.get("marker", 0) or 0)
            if m not in markers:
                order.append(m)
            markers.setdefault(m, {"marker": m,
                                   "inject_ts": e.get("ts"),
                                   "aligns": [],
                                   "stall_ms": 0.0})
        elif t == "marker_align":
            m = int(e.get("marker", 0) or 0)
            rec = markers.get(m)
            if rec is None:
                order.append(m)
                rec = markers.setdefault(
                    m, {"marker": m, "inject_ts": None, "aligns": [],
                        "stall_ms": 0.0})
            rec["aligns"].append({
                "stage": e.get("stage"), "partition": e.get("partition"),
                "wait_ms": float(e.get("wait_ms", 0.0) or 0.0),
                "buffered_bytes": int(e.get("buffered_bytes", 0) or 0),
                "ts": e.get("ts")})
    stalls = [e for e in evs if e.get("type") == "backpressure"]
    bounds = sorted((m, markers[m].get("inject_ts")) for m in markers
                    if markers[m].get("inject_ts") is not None)
    for e in stalls:
        ts = e.get("ts")
        target = None
        for m, t0 in bounds:
            if t0 is not None and ts is not None and ts >= t0:
                target = m
        if target is None and bounds:
            target = bounds[0][0]
        if target is not None:
            markers[target]["stall_ms"] += float(
                e.get("stall_ms", 0.0) or 0.0)
    out = []
    for m in order:
        rec = markers[m]
        aligned_ts = [a["ts"] for a in rec["aligns"]
                      if a["ts"] is not None]
        if rec["inject_ts"] is not None and aligned_ts:
            rec["align_ms"] = round(
                (max(aligned_ts) - rec["inject_ts"]) * 1000.0, 3)
        else:
            rec["align_ms"] = None
        rec["stall_ms"] = round(rec["stall_ms"], 3)
        out.append(rec)
    return out


def critical_path(events: List[dict],
                  query_id: Optional[str] = None) -> Optional[dict]:
    """Walk the gating chain of a query's distributed job. Returns
    ``{"total_ms", "categories": {cat: ms}, "chain": [...], "top":
    [{"category", "ms", "at"}]}`` (top-3 contributors, largest first)
    or None when the events carry no finished tasks."""
    evs = _for_query(events, query_id)
    tasks = _winning_tasks(evs)
    if not tasks:
        return None
    edges = _fetch_edges(evs)
    adaptive_ts = [e.get("ts") for e in evs
                   if e.get("type") in ("adaptive_applied",
                                        "adaptive_rollback")
                   and e.get("ts") is not None]
    entries: List[dict] = []
    chain: List[dict] = []

    def charge(at: str, category: str, ms: float) -> None:
        if ms > 0.0:
            entries.append({"at": at, "category": category,
                            "ms": round(ms, 3)})

    # the driver's root-stage merge (dst_partition -1) gates on the
    # last-finishing producer overall; start the walk there
    cur = max(tasks, key=lambda k: tasks[k].get("ts", 0.0))
    visited = set()
    while cur is not None and cur not in visited:
        visited.add(cur)
        win = tasks[cur]
        at = f"s{cur[1]}p{cur[2]}"
        finish = float(win.get("ts", 0.0) or 0.0)
        start = win.get("start_ts")
        dispatch = win.get("dispatch_ts")
        chain.append({"job_id": cur[0], "stage": cur[1],
                      "partition": cur[2],
                      "attempt": win.get("attempt"),
                      "worker": win.get("worker", "")})
        if start is not None:
            window_ms = max(0.0, (finish - start) * 1000.0)
            fetch_wait = min(window_ms, float(
                win.get("fetch_wait_ms", 0.0) or 0.0))
            task_label = (f"{cur[0]}/s{cur[1]}p{cur[2]}"
                          f"a{win.get('attempt')}")
            compile_ms = min(window_ms - fetch_wait,
                             _compiles_in(evs, start, finish,
                                          task_label))
            stall_ms = min(window_ms - fetch_wait - compile_ms,
                           _credit_stalls_in(evs, start, finish,
                                             task_label))
            charge(at, "fetch-wait", fetch_wait)
            charge(at, "compile", compile_ms)
            charge(at, "credit-stall", stall_ms)
            charge(at, "compute",
                   window_ms - fetch_wait - compile_ms - stall_ms)
        if dispatch is not None and start is not None:
            charge(at, "queue", max(0.0, (start - dispatch) * 1000.0))
        # follow the fetch edge to the producer that finished last (the
        # fetch that actually gated this task's start)
        preds = [p for p in edges.get(cur, ()) if p in tasks]
        nxt = max(preds, key=lambda k: tasks[k].get("ts", 0.0)) \
            if preds else None
        if nxt is not None and dispatch is not None:
            pred_finish = float(tasks[nxt].get("ts", 0.0) or 0.0)
            gap_ms = max(0.0, (dispatch - pred_finish) * 1000.0)
            replanned = any(pred_finish <= t <= dispatch
                            for t in adaptive_ts)
            charge(at, "replan" if replanned else "queue", gap_ms)
        cur = nxt

    if not entries:
        return None
    categories = {c: 0.0 for c in CATEGORIES}
    for entry in entries:
        categories[entry["category"]] += entry["ms"]
    categories = {c: round(ms, 3) for c, ms in categories.items() if ms}
    top = sorted(entries, key=lambda e: -e["ms"])[:3]
    return {"total_ms": round(sum(e["ms"] for e in entries), 3),
            "categories": categories, "chain": chain, "top": top}


def render_critical_path(cp: Optional[dict]) -> str:
    """The EXPLAIN ANALYZE line: top-3 contributors with category."""
    if not cp or not cp.get("top"):
        return ""
    parts = [f"{e['category']} {e['ms']:.1f}ms ({e['at']})"
             for e in cp["top"]]
    return f"critical path: {', '.join(parts)}"


# ---------------------------------------------------------------------------
# offline reconstruction (scripts/sail_timeline.py)
# ---------------------------------------------------------------------------

def reconstruct(events: List[dict], query_id: str) -> dict:
    """Everything the replay tool derives for one query."""
    evs = _for_query(events, query_id)
    stages = []
    for e in evs:
        if e.get("type") == "stage_submit":
            stages.append({"stage": e.get("stage"),
                           "partitions": e.get("partitions"),
                           "pipelined": bool(e.get("pipelined")),
                           "submit_time": e.get("ts"),
                           "complete_time": None, "rows": None})
        elif e.get("type") == "stage_complete":
            for s in stages:
                if s["stage"] == e.get("stage") and \
                        s["complete_time"] is None:
                    s["complete_time"] = e.get("ts")
                    s["rows"] = e.get("rows")
                    break
    start = next((e for e in evs if e.get("type") == "query_start"), None)
    end = next((e for e in evs if e.get("type") == "query_end"), None)
    return {
        "query_id": query_id,
        "trace_id": next((e.get("trace_id") for e in evs
                          if e.get("trace_id")), None),
        "statement": (start or {}).get("statement", ""),
        "status": (end or {}).get("status", ""),
        "stages": stages,
        "tasks": task_timeline(evs),
        "decisions": decisions(evs),
        "adaptive_decisions": adaptive_decisions(evs),
        "continuous": continuous_progress(evs),
        "critical_path": critical_path(evs),
    }


def render_timeline(events: List[dict], query_id: str,
                    width: int = 60) -> str:
    """Text Gantt of one query's stages/tasks plus the decision log and
    critical-path line — the human view of a replayed run."""
    rec = reconstruct(events, query_id)
    lines = [f"query {query_id}"
             + (f" [{rec['status']}]" if rec["status"] else "")]
    if rec["statement"]:
        lines.append(f"  {rec['statement'][:100]}")
    tasks = [t for t in rec["tasks"] if t["dispatch_time"] is not None
             and t["finish_time"] is not None]
    if tasks:
        t0 = min(t["dispatch_time"] for t in tasks)
        t1 = max(t["finish_time"] for t in tasks)
        span = max(t1 - t0, 1e-9)

        def bar(a: float, b: float) -> str:
            lo = int((a - t0) / span * width)
            hi = max(lo + 1, int((b - t0) / span * width))
            return " " * lo + "#" * (hi - lo)

        lines.append(f"  timeline ({span * 1000.0:.1f}ms across "
                     f"{len(tasks)} task attempts)")
        for t in tasks:
            label = (f"  s{t['stage']}p{t['partition']}"
                     f"a{t['attempt']}").ljust(12)
            state = "" if t["state"] == "succeeded" else f" {t['state']}"
            lines.append(
                f"{label}|{bar(t['dispatch_time'], t['finish_time'])}"
                f"|{state} {t['worker']}")
    if rec["decisions"]:
        lines.append(f"  decisions ({len(rec['decisions'])}):")
        for d in rec["decisions"]:
            attrs = {k: v for k, v in d.items()
                     if k not in ("v", "seq", "ts", "type", "query_id",
                                  "trace_id")}
            lines.append(f"    {d['type']}: "
                         f"{json.dumps(attrs, sort_keys=True)}")
    if rec["continuous"]:
        lines.append(f"  markers ({len(rec['continuous'])}):")
        for m in rec["continuous"]:
            align = f"{m['align_ms']:.1f}ms" \
                if m.get("align_ms") is not None else "?"
            buffered = sum(a["buffered_bytes"] for a in m["aligns"])
            lines.append(
                f"    m{m['marker']}: inject→align {align}, "
                f"{len(m['aligns'])} align point(s), "
                f"{buffered}B buffered, "
                f"credit stalls {m['stall_ms']:.1f}ms")
    cp_line = render_critical_path(rec["critical_path"])
    if cp_line:
        lines.append("  " + cp_line)
    return "\n".join(lines)
