"""Telemetry: spans, per-operator execution metrics, EXPLAIN ANALYZE.

Reference role: sail-telemetry — fastrace spans around actors/RPC plus
DataFusion operator metrics harvested into OTel gauges per {job, stage,
partition, operator} (SURVEY.md §5). Here the executor wraps every operator
with a metrics recorder (rows out, batch capacity, wall time) and exports
through the opentelemetry-api when a provider is configured; without one,
metrics stay queryable in-process via EXPLAIN ANALYZE.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .metrics import record as _record_metric

try:  # the api package is always importable; an SDK may or may not be wired
    from opentelemetry import trace as _otel_trace
    _TRACER = _otel_trace.get_tracer("sail_tpu")
except Exception:  # pragma: no cover - otel not installed
    _TRACER = None


@dataclass
class OperatorMetrics:
    operator: str
    detail: str = ""
    output_rows: int = 0
    capacity: int = 0
    elapsed_ms: float = 0.0
    children: List["OperatorMetrics"] = field(default_factory=list)
    # free-form key=value counters (e.g. prefetch overlap stats); rendered
    # after the standard fields so EXPLAIN ANALYZE surfaces them
    extra: Dict[str, object] = field(default_factory=dict)

    def render(self, indent: int = 0) -> str:
        pad = "  " * indent
        more = "".join(f" {k}={v}" for k, v in self.extra.items())
        line = (f"{pad}{self.operator}{' ' + self.detail if self.detail else ''}"
                f"  [rows={self.output_rows} cap={self.capacity} "
                f"time={self.elapsed_ms:.1f}ms{more}]")
        return "\n".join([line] + [c.render(indent + 1) for c in self.children])

    def to_dict(self) -> dict:
        """JSON-safe shape — the wire format for cluster task metrics and
        the EXPLAIN ANALYZE FORMAT JSON operator tree."""
        out = {"operator": self.operator, "output_rows": self.output_rows,
               "capacity": self.capacity,
               "elapsed_ms": round(self.elapsed_ms, 3)}
        if self.detail:
            out["detail"] = self.detail
        if self.extra:
            out["extra"] = {k: (v if isinstance(v, (int, float, bool,
                                                    str, type(None)))
                                else str(v))
                            for k, v in self.extra.items()}
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "OperatorMetrics":
        m = cls(str(d.get("operator", "?")), str(d.get("detail", "")))
        m.output_rows = int(d.get("output_rows", 0))
        m.capacity = int(d.get("capacity", 0))
        m.elapsed_ms = float(d.get("elapsed_ms", 0.0))
        m.extra = dict(d.get("extra") or {})
        m.children = [cls.from_dict(c) for c in d.get("children") or ()]
        return m


_local = threading.local()


def current_collector() -> Optional[List]:
    return getattr(_local, "collector", None)


@contextmanager
def collect_metrics():
    """Enable metrics collection on this thread for one query."""
    prev = getattr(_local, "collector", None)
    _local.collector = []
    try:
        yield _local.collector
    finally:
        _local.collector = prev


def note(operator: str, detail: str = "", **extra) -> None:
    """Attach a zero-duration informational entry (e.g. prefetch overlap
    counters) at the current nesting level; no-op without a collector."""
    collector = current_collector()
    if collector is None:
        return
    m = OperatorMetrics(operator, detail)
    m.extra = dict(extra)
    collector.append(m)


@contextmanager
def operator_span(name: str, detail: str = ""):
    """Wrap one operator execution; nests into the thread's collector."""
    collector = current_collector()
    if collector is None:
        yield None
        return
    m = OperatorMetrics(name, detail)
    # children recorded during this span land in a fresh list
    parent = collector
    own: List[OperatorMetrics] = []
    _local.collector = own
    t0 = time.perf_counter()
    span_cm = _TRACER.start_as_current_span(f"op:{name}") if _TRACER else None
    if span_cm is not None:
        span_cm.__enter__()
    try:
        yield m
    except BaseException as e:
        # aborted spans (e.g. a fused attempt that fell back) don't record
        # metrics, but the OTel span must carry the exception and error
        # status — exiting with the real exc_info makes start_as_current_span
        # record the exception and set ERROR status; exiting with
        # (None, None, None) silently reported failed operators as OK
        if span_cm is not None:
            span_cm.__exit__(type(e), e, e.__traceback__)
        _local.collector = parent
        raise
    else:
        if span_cm is not None:
            span_cm.__exit__(None, None, None)
        m.elapsed_ms = (time.perf_counter() - t0) * 1000
        m.children = own
        parent.append(m)
        _local.collector = parent
        _record_metric("execution.output_row_count", m.output_rows,
                       operator=name)
        _record_metric("execution.elapsed_compute_time",
                       m.elapsed_ms / 1000.0, operator=name)
