"""`system` catalog: engine introspection as SQL-queryable tables.

Reference role: crates/sail-catalog-system/src/service.rs:37-124 —
system.session.sessions, system.execution.{jobs,stages,tasks},
system.cluster.workers, fed from live runtime state.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple


class SystemRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self.sessions: Dict[str, dict] = {}
        self.jobs: Dict[str, dict] = {}
        self.tasks: List[dict] = []
        self.workers: Dict[str, dict] = {}

    # -- recorders (called by session manager / driver) ------------------
    def record_session(self, session_id: str):
        with self._lock:
            now = time.time()
            s = self.sessions.setdefault(
                session_id, {"session_id": session_id, "start_time": now,
                             "queries": 0})
            s["last_access"] = now
            s["queries"] += 1

    def end_session(self, session_id: str):
        with self._lock:
            self.sessions.pop(session_id, None)

    def record_job(self, job_id: str, stages: int, status: str,
                   rows_by_stage: Optional[Dict[int, int]] = None):
        with self._lock:
            self.jobs[job_id] = {
                "job_id": job_id, "stages": stages, "status": status,
                "updated": time.time(),
                "rows_by_stage": dict(rows_by_stage or {})}

    def record_task(self, job_id: str, stage: int, partition: int,
                    attempt: int, status: str, worker_id: str,
                    rows_out: int = 0):
        with self._lock:
            self.tasks.append({
                "job_id": job_id, "stage": stage, "partition": partition,
                "attempt": attempt, "status": status,
                "worker_id": worker_id, "rows_out": rows_out,
                "time": time.time()})
            del self.tasks[:-10_000]

    def record_worker(self, worker_id: str, addr: str, slots: int,
                      status: str):
        with self._lock:
            self.workers[worker_id] = {
                "worker_id": worker_id, "addr": addr, "slots": slots,
                "status": status, "updated": time.time()}

    # -- table surface ---------------------------------------------------
    def table(self, database: str, name: str):
        import pyarrow as pa

        with self._lock:
            if (database, name) == ("session", "sessions"):
                rows = list(self.sessions.values())
                return pa.table({
                    "session_id": pa.array([r["session_id"] for r in rows]),
                    "start_time": pa.array(
                        [r["start_time"] for r in rows], pa.float64()),
                    "last_access": pa.array(
                        [r.get("last_access") for r in rows], pa.float64()),
                    "queries": pa.array(
                        [r["queries"] for r in rows], pa.int64()),
                })
            if (database, name) == ("execution", "jobs"):
                rows = list(self.jobs.values())
                return pa.table({
                    "job_id": pa.array([r["job_id"] for r in rows]),
                    "stages": pa.array([r["stages"] for r in rows],
                                       pa.int32()),
                    "status": pa.array([r["status"] for r in rows]),
                    "updated": pa.array([r["updated"] for r in rows],
                                        pa.float64()),
                })
            if (database, name) == ("execution", "stages"):
                rows = []
                for j in self.jobs.values():
                    for sid, n in j.get("rows_by_stage", {}).items():
                        rows.append((j["job_id"], int(sid), int(n)))
                return pa.table({
                    "job_id": pa.array([r[0] for r in rows]),
                    "stage_id": pa.array([r[1] for r in rows], pa.int32()),
                    "rows_out": pa.array([r[2] for r in rows], pa.int64()),
                })
            if (database, name) == ("execution", "tasks"):
                rows = list(self.tasks)
                return pa.table({
                    "job_id": pa.array([r["job_id"] for r in rows]),
                    "stage": pa.array([r["stage"] for r in rows],
                                      pa.int32()),
                    "partition": pa.array([r["partition"] for r in rows],
                                          pa.int32()),
                    "attempt": pa.array([r["attempt"] for r in rows],
                                        pa.int32()),
                    "status": pa.array([r["status"] for r in rows]),
                    "worker_id": pa.array([r["worker_id"] for r in rows]),
                    "rows_out": pa.array([r["rows_out"] for r in rows],
                                         pa.int64()),
                })
            if (database, name) == ("telemetry", "query_profiles"):
                import json
                from ..profiler import FLIGHT_RECORDER
                rows = [p.to_dict() for p in FLIGHT_RECORDER.profiles()]
                phase_ms = lambda r, n: float(  # noqa: E731
                    r["phases"].get(n, 0.0))
                return pa.table({
                    "query_id": pa.array(
                        [r["query_id"] for r in rows]),
                    "statement": pa.array(
                        [r["statement"] for r in rows]),
                    "session": pa.array([r["session"] for r in rows]),
                    "tenant": pa.array(
                        [r.get("tenant", "") for r in rows]),
                    "status": pa.array([r["status"] for r in rows]),
                    "start_time": pa.array(
                        [r["start_time"] for r in rows], pa.float64()),
                    "total_ms": pa.array(
                        [r["total_ms"] for r in rows], pa.float64()),
                    "parse_ms": pa.array(
                        [phase_ms(r, "parse") for r in rows],
                        pa.float64()),
                    "resolve_ms": pa.array(
                        [phase_ms(r, "resolve") for r in rows],
                        pa.float64()),
                    "optimize_ms": pa.array(
                        [phase_ms(r, "optimize") for r in rows],
                        pa.float64()),
                    "compile_ms": pa.array(
                        [phase_ms(r, "compile") for r in rows],
                        pa.float64()),
                    "execute_ms": pa.array(
                        [phase_ms(r, "execute") for r in rows],
                        pa.float64()),
                    "fetch_ms": pa.array(
                        [phase_ms(r, "fetch") for r in rows],
                        pa.float64()),
                    "compile_cache_hits": pa.array(
                        [r["compile"]["cache_hits"] for r in rows],
                        pa.int64()),
                    "compile_cache_misses": pa.array(
                        [r["compile"]["cache_misses"] for r in rows],
                        pa.int64()),
                    "retrace_count": pa.array(
                        [(r.get("retraces") or {}).get("count", 0)
                         for r in rows], pa.int64()),
                    "anomaly_verdict": pa.array(
                        [r.get("anomaly_verdict", "") for r in rows]),
                    "transfer_bytes": pa.array(
                        [r["transfer_bytes"] for r in rows], pa.int64()),
                    "spill_bytes": pa.array(
                        [r["spill_bytes"] for r in rows], pa.int64()),
                    "shuffle_skew_ratio": pa.array(
                        [max((e.get("ratio", 0.0)
                              for e in r.get("skew", ())), default=0.0)
                         for r in rows], pa.float64()),
                    "adaptive_decisions": pa.array(
                        [sum((r.get("adaptive") or {}).get(k, 0)
                             for k in ("coalesced", "split", "broadcast",
                                       "reordered")) for r in rows],
                        pa.int64()),
                    "rows_out": pa.array(
                        [r["rows_out"] for r in rows], pa.int64()),
                    "slow": pa.array([r["slow"] for r in rows],
                                     pa.bool_()),
                    "error": pa.array([r["error"] for r in rows]),
                    "profile_json": pa.array(
                        [json.dumps(r, default=str) for r in rows]),
                })
            if (database, name) == ("telemetry", "active_queries"):
                from ..profiler import FLIGHT_RECORDER
                active = FLIGHT_RECORDER.active()
                return pa.table({
                    "query_id": pa.array([p.query_id for p in active]),
                    "statement": pa.array(
                        [p.statement for p in active]),
                    "session": pa.array([p.session for p in active]),
                    "phase": pa.array(
                        [p.current_phase() for p in active]),
                    "start_time": pa.array(
                        [p.start_time for p in active], pa.float64()),
                    "elapsed_ms": pa.array(
                        [p.total_ms for p in active], pa.float64()),
                })
            if (database, name) == ("telemetry", "metrics"):
                from ..metrics import FLEET, REGISTRY
                # scope=process: this process's registry (worker "");
                # scope=fleet: the driver's cluster-wide view keyed by
                # worker id ("driver" = this process; remote workers
                # from heartbeat-shipped deltas). Histogram rows carry
                # observation count + estimated p50/p95/p99 (seconds).
                rows = [dict(r, scope="process", worker="")
                        for r in REGISTRY.snapshot()]
                rows += [dict(r, scope="fleet")
                         for r in FLEET.snapshot()]
                return pa.table({
                    "name": pa.array([r["name"] for r in rows]),
                    "scope": pa.array([r["scope"] for r in rows]),
                    "worker": pa.array(
                        [r.get("worker", "") for r in rows]),
                    "type": pa.array([r["type"] for r in rows]),
                    "unit": pa.array([r["unit"] for r in rows]),
                    "description": pa.array(
                        [r["description"] for r in rows]),
                    "attributes": pa.array(
                        [r["attributes"] for r in rows]),
                    "value": pa.array([r["value"] for r in rows],
                                      pa.float64()),
                    "count": pa.array(
                        [r.get("count") for r in rows], pa.int64()),
                    "p50": pa.array(
                        [r.get("p50") for r in rows], pa.float64()),
                    "p95": pa.array(
                        [r.get("p95") for r in rows], pa.float64()),
                    "p99": pa.array(
                        [r.get("p99") for r in rows], pa.float64()),
                })
            if (database, name) == ("telemetry", "tenant_slo"):
                import json
                from ..metrics import FLEET, HistogramState
                # live per-tenant serving SLOs: fleet-merged
                # query.latency (phase=total) percentiles + shed and
                # deadline-cancel counters — the numbers the admission
                # layer's isolation promises are stated against
                merged: Dict[str, HistogramState] = {}
                for _w, attrs, h in FLEET.histogram_states(
                        "query.latency"):
                    if attrs.get("phase") != "total":
                        continue
                    tenant = attrs.get("tenant", "default")
                    cur = merged.get(tenant)
                    if cur is None:
                        merged[tenant] = h
                    else:
                        cur.merge(h)
                sheds: Dict[str, float] = {}
                cancels: Dict[str, float] = {}
                for r in FLEET.snapshot():
                    attrs = json.loads(r["attributes"])
                    tenant = attrs.get("tenant")
                    if tenant is None:
                        continue
                    if r["name"] == "cluster.admission.shed_count":
                        sheds[tenant] = sheds.get(tenant, 0.0) \
                            + r["value"]
                    elif r["name"] == \
                            "cluster.admission.deadline_cancel_count":
                        cancels[tenant] = cancels.get(tenant, 0.0) \
                            + r["value"]
                tenants = sorted(set(merged) | set(sheds) | set(cancels))
                def ms(h, q):
                    v = h.quantile(q) if h is not None else None
                    return None if v is None else v * 1000.0
                # declared objectives + burn rates from the SLO
                # monitor (analysis/anomaly.py): reading this table IS
                # an evaluation tick, same as a /metrics scrape
                slo_rows: Dict[str, Dict[str, dict]] = {}
                objectives: Dict[str, tuple] = {}
                try:
                    from ..analysis.anomaly import SLO_MONITOR
                    for r in SLO_MONITOR.evaluate():
                        slo_rows.setdefault(
                            r["tenant"], {})[r["window"]] = r
                    for t in tenants:
                        objectives[t] = SLO_MONITOR.objective_for(t)
                except Exception:  # noqa: BLE001 — monitor disabled
                    pass
                def burn(t, w):
                    r = slo_rows.get(t, {}).get(w)
                    return None if r is None else r["burn_rate"]
                return pa.table({
                    "tenant": pa.array(tenants),
                    "queries": pa.array(
                        [merged[t].count if t in merged else 0
                         for t in tenants], pa.int64()),
                    "p50_ms": pa.array(
                        [ms(merged.get(t), 0.50) for t in tenants],
                        pa.float64()),
                    "p95_ms": pa.array(
                        [ms(merged.get(t), 0.95) for t in tenants],
                        pa.float64()),
                    "p99_ms": pa.array(
                        [ms(merged.get(t), 0.99) for t in tenants],
                        pa.float64()),
                    "shed_count": pa.array(
                        [int(sheds.get(t, 0)) for t in tenants],
                        pa.int64()),
                    "deadline_cancel_count": pa.array(
                        [int(cancels.get(t, 0)) for t in tenants],
                        pa.int64()),
                    "slo_target_ms": pa.array(
                        [objectives.get(t, (None,))[0]
                         for t in tenants], pa.float64()),
                    "slo_objective": pa.array(
                        [objectives.get(t, (None, None))[1]
                         for t in tenants], pa.float64()),
                    "burn_rate_fast": pa.array(
                        [burn(t, "fast") for t in tenants],
                        pa.float64()),
                    "burn_rate_slow": pa.array(
                        [burn(t, "slow") for t in tenants],
                        pa.float64()),
                })
            if (database, name) == ("telemetry", "retraces"):
                from ..exec.retrace import LEDGER
                rows = LEDGER.snapshot()
                return pa.table({
                    "fingerprint": pa.array(
                        [r["fingerprint"] for r in rows]),
                    "key": pa.array([r["key"] for r in rows]),
                    "cause": pa.array([r["cause"] for r in rows]),
                    "count": pa.array(
                        [r["count"] for r in rows], pa.int64()),
                    "signatures": pa.array(
                        [r["signatures"] for r in rows], pa.int64()),
                    "evictions": pa.array(
                        [r["evictions"] for r in rows], pa.int64()),
                    "first_ts": pa.array(
                        [r["first_ts"] for r in rows], pa.float64()),
                    "last_ts": pa.array(
                        [r["last_ts"] for r in rows], pa.float64()),
                })
            if (database, name) == ("telemetry", "anomalies"):
                import json
                from ..analysis import anomaly as _anomaly
                rows = _anomaly.anomalies()
                return pa.table({
                    "query_id": pa.array(
                        [r["query_id"] for r in rows]),
                    "trace_id": pa.array(
                        [r["trace_id"] for r in rows]),
                    "fingerprint": pa.array(
                        [r["fingerprint"] for r in rows]),
                    "verdict": pa.array([r["verdict"] for r in rows]),
                    "total_ms": pa.array(
                        [r["total_ms"] for r in rows], pa.float64()),
                    "baseline_p50_ms": pa.array(
                        [r["baseline_p50_ms"] for r in rows],
                        pa.float64()),
                    "excess_ms": pa.array(
                        [r["excess_ms"] for r in rows], pa.float64()),
                    "evidence": pa.array(
                        [json.dumps(r["evidence"], sort_keys=True,
                                    default=str) for r in rows]),
                })
            if (database, name) == ("telemetry", "events"):
                import json
                from .. import events as ev
                rows = ev.events()
                reserved = set(ev.RESERVED_KEYS)
                return pa.table({
                    "seq": pa.array(
                        [r.get("seq") for r in rows], pa.int64()),
                    "ts": pa.array(
                        [r.get("ts") for r in rows], pa.float64()),
                    "type": pa.array([r.get("type") for r in rows]),
                    "query_id": pa.array(
                        [r.get("query_id", "") for r in rows]),
                    "trace_id": pa.array(
                        [r.get("trace_id") for r in rows]),
                    "attributes": pa.array(
                        [json.dumps({k: v for k, v in r.items()
                                     if k not in reserved},
                                    sort_keys=True, default=str)
                         for r in rows]),
                })
            if (database, name) == ("telemetry", "task_timeline"):
                from .. import events as ev
                from ..analysis.timeline import task_timeline
                rows = task_timeline(ev.events())
                return pa.table({
                    "query_id": pa.array(
                        [r["query_id"] for r in rows]),
                    "job_id": pa.array([r["job_id"] for r in rows]),
                    "stage": pa.array(
                        [r["stage"] for r in rows], pa.int32()),
                    "partition": pa.array(
                        [r["partition"] for r in rows], pa.int32()),
                    "attempt": pa.array(
                        [r["attempt"] for r in rows], pa.int32()),
                    "worker": pa.array([r["worker"] for r in rows]),
                    "state": pa.array([r["state"] for r in rows]),
                    "dispatch_time": pa.array(
                        [r["dispatch_time"] for r in rows],
                        pa.float64()),
                    "start_time": pa.array(
                        [r["start_time"] for r in rows], pa.float64()),
                    "finish_time": pa.array(
                        [r["finish_time"] for r in rows],
                        pa.float64()),
                    "queue_ms": pa.array(
                        [r["queue_ms"] for r in rows], pa.float64()),
                    "run_ms": pa.array(
                        [r["run_ms"] for r in rows], pa.float64()),
                    "fetch_wait_ms": pa.array(
                        [r["fetch_wait_ms"] for r in rows],
                        pa.float64()),
                    "rows_out": pa.array(
                        [r["rows_out"] for r in rows], pa.int64()),
                })
            if (database, name) == ("telemetry", "result_cache"):
                from ..exec.result_cache import (FRAGMENT_CACHE,
                                                 RESULT_CACHE, VIEWS)
                rows = RESULT_CACHE.snapshot() + FRAGMENT_CACHE.snapshot()
                for vname in VIEWS.names():
                    view = VIEWS.get(vname)
                    if view is None:
                        continue
                    data = view.entry.data
                    rows.append({
                        "tier": "view", "id": f"mv-{vname}",
                        "key": vname, "tables": sorted(view.depends),
                        "bytes": int(getattr(data, "nbytes", 0) or 0),
                        "rows": int(getattr(data, "num_rows", 0) or 0),
                        "hit_count": view.marker,
                        "cost_ms": 0.0, "versions": "",
                        "last_access": 0.0})
                import json as _json
                return pa.table({
                    "tier": pa.array([r["tier"] for r in rows]),
                    "id": pa.array([r["id"] for r in rows]),
                    "key": pa.array([str(r["key"]) for r in rows]),
                    "tables": pa.array(
                        [",".join(r["tables"]) for r in rows]),
                    "bytes": pa.array(
                        [r["bytes"] for r in rows], pa.int64()),
                    "rows": pa.array(
                        [r["rows"] for r in rows], pa.int64()),
                    "hit_count": pa.array(
                        [r["hit_count"] for r in rows], pa.int64()),
                    "cost_ms": pa.array(
                        [float(r["cost_ms"]) for r in rows],
                        pa.float64()),
                    "table_versions": pa.array(
                        [_json.dumps(r["versions"], default=str)
                         for r in rows]),
                    "last_access": pa.array(
                        [r["last_access"] for r in rows], pa.float64()),
                })
            if (database, name) == ("cluster", "workers"):
                rows = list(self.workers.values())
                return pa.table({
                    "worker_id": pa.array([r["worker_id"] for r in rows]),
                    "addr": pa.array([r["addr"] for r in rows]),
                    "slots": pa.array([r["slots"] for r in rows],
                                      pa.int32()),
                    "status": pa.array([r["status"] for r in rows]),
                    "updated": pa.array([r["updated"] for r in rows],
                                        pa.float64()),
                })
        raise KeyError(f"unknown system table system.{database}.{name}")


SYSTEM = SystemRegistry()
