"""In-memory catalog manager.

Reference role: crates/sail-catalog/src/manager/ (multi-catalog resolution,
current database, temp views) + crates/sail-catalog-memory.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..spec import data_type as dt
from ..spec import plan as sp


@dataclasses.dataclass
class TableEntry:
    name: Tuple[str, ...]
    schema: dt.StructType = None
    data: object = None                    # pa.Table for in-memory tables
    paths: Tuple[str, ...] = ()
    format: str = "memory"
    view_plan: Optional[sp.QueryPlan] = None
    options: Tuple[Tuple[str, str], ...] = ()
    partition_by: Tuple[str, ...] = ()
    comment: Optional[str] = None


class CatalogManager:
    def __init__(self):
        from ..functions.udf import UDFRegistry
        self.current_catalog = "spark_catalog"
        self.current_database = "default"
        self.databases: Dict[str, dict] = {"default": {}}
        self.tables: Dict[Tuple[str, str], TableEntry] = {}
        self.temp_views: Dict[str, TableEntry] = {}
        self.udfs = UDFRegistry()

    # -- resolution ------------------------------------------------------
    def _db_and_name(self, name: Tuple[str, ...]) -> Tuple[str, str]:
        parts = [p for p in name]
        if len(parts) == 1:
            return self.current_database, parts[0].lower()
        if len(parts) == 2:
            return parts[0].lower(), parts[1].lower()
        # catalog.db.table — single catalog in v0
        return parts[-2].lower(), parts[-1].lower()

    def lookup_table(self, name: Tuple[str, ...]) -> Optional[TableEntry]:
        if len(name) == 1 and name[0].lower() in self.temp_views:
            return self.temp_views[name[0].lower()]
        db, tbl = self._db_and_name(name)
        return self.tables.get((db, tbl))

    # -- mutation ---------------------------------------------------------
    def create_database(self, name: str, if_not_exists: bool = False,
                        comment: Optional[str] = None,
                        location: Optional[str] = None):
        key = name.lower()
        if key in self.databases:
            if if_not_exists:
                return
            raise ValueError(f"database {name!r} already exists")
        self.databases[key] = {"comment": comment, "location": location}

    def drop_database(self, name: str, if_exists: bool, cascade: bool):
        key = name.lower()
        if key not in self.databases:
            if if_exists:
                return
            raise ValueError(f"database {name!r} not found")
        tables = [k for k in self.tables if k[0] == key]
        if tables and not cascade:
            raise ValueError(f"database {name!r} is not empty")
        for k in tables:
            del self.tables[k]
        del self.databases[key]

    def register_table(self, entry: TableEntry, replace: bool = False,
                       if_not_exists: bool = False):
        db, tbl = self._db_and_name(entry.name)
        if db not in self.databases:
            raise ValueError(f"database {db!r} not found")
        if (db, tbl) in self.tables and not replace:
            if if_not_exists:
                return
            raise ValueError(f"table {'.'.join(entry.name)!r} already exists")
        self.tables[(db, tbl)] = entry

    def register_temp_view(self, name: str, plan: sp.QueryPlan, replace: bool = True):
        key = name.lower()
        if key in self.temp_views and not replace:
            raise ValueError(f"temp view {name!r} already exists")
        self.temp_views[key] = TableEntry((name,), view_plan=plan)

    def drop_table(self, name: Tuple[str, ...], if_exists: bool = False,
                   is_view: bool = False):
        if len(name) == 1 and name[0].lower() in self.temp_views:
            del self.temp_views[name[0].lower()]
            return
        db, tbl = self._db_and_name(name)
        if (db, tbl) not in self.tables:
            if if_exists:
                return
            raise ValueError(f"table {'.'.join(name)!r} not found")
        del self.tables[(db, tbl)]

    def list_tables(self, database: Optional[str] = None) -> List[TableEntry]:
        db = (database or self.current_database).lower()
        out = [e for (d, _), e in self.tables.items() if d == db]
        out.extend(self.temp_views.values())
        return out

    def list_databases(self) -> List[str]:
        return sorted(self.databases)
