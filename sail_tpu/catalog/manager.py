"""Multi-catalog manager.

Reference role: crates/sail-catalog/src/manager/ (multi-catalog resolution,
current catalog/database, temp views) with pluggable CatalogProvider
backends (memory, Iceberg REST, HMS, Glue, Unity, OneLake — SURVEY.md
§2.6). Identifier resolution: ``catalog.db.table`` routes to the named
provider; 1/2-part names resolve in the current catalog; session temp
views shadow everything.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..spec import data_type as dt
from ..spec import plan as sp


@dataclasses.dataclass
class TableEntry:
    name: Tuple[str, ...]
    schema: dt.StructType = None
    data: object = None                    # pa.Table for in-memory tables
    paths: Tuple[str, ...] = ()
    format: str = "memory"
    view_plan: Optional[sp.QueryPlan] = None
    options: Tuple[Tuple[str, str], ...] = ()
    partition_by: Tuple[str, ...] = ()
    comment: Optional[str] = None


class CatalogManager:
    def __init__(self, configure: bool = True):
        from ..functions.udf import UDFRegistry
        from .provider import MemoryCatalogProvider
        self.current_catalog = "spark_catalog"
        self.current_database = "default"
        self.providers: Dict[str, object] = {
            "spark_catalog": MemoryCatalogProvider("spark_catalog")}
        self.temp_views: Dict[str, TableEntry] = {}
        self.udfs = UDFRegistry()
        if configure:
            configure_catalogs(self)

    # -- provider registry ----------------------------------------------
    def register_catalog(self, name: str, provider) -> None:
        provider.name = name
        self.providers[name.lower()] = provider

    def provider(self, name: Optional[str] = None):
        key = (name or self.current_catalog).lower()
        p = self.providers.get(key)
        if p is None:
            raise ValueError(f"catalog {key!r} not found")
        return p

    def list_catalogs(self) -> List[str]:
        return sorted(self.providers)

    # -- compatibility views of the default provider ---------------------
    @property
    def databases(self) -> Dict[str, dict]:
        return self.provider().databases \
            if hasattr(self.provider(), "databases") else {}

    @property
    def tables(self) -> Dict[Tuple[str, str], TableEntry]:
        p = self.provider()
        return p.tables if hasattr(p, "tables") else {}

    # -- resolution ------------------------------------------------------
    def _route(self, name: Tuple[str, ...]) -> Tuple[object, str, str]:
        """identifier → (provider, database, table)."""
        parts = [p for p in name]
        if len(parts) == 1:
            return self.provider(), self.current_database, parts[0].lower()
        if len(parts) == 2:
            # could be catalog.table? Spark treats 2-part as db.table
            return self.provider(), parts[0].lower(), parts[1].lower()
        cat = parts[-3].lower()
        if cat in self.providers:
            return self.providers[cat], parts[-2].lower(), parts[-1].lower()
        return self.provider(), parts[-2].lower(), parts[-1].lower()

    def lookup_table(self, name: Tuple[str, ...]) -> Optional[TableEntry]:
        if len(name) == 1 and name[0].lower() in self.temp_views:
            return self.temp_views[name[0].lower()]
        prov, db, tbl = self._route(name)
        return prov.get_table(db, tbl)

    def _db_and_name(self, name: Tuple[str, ...]) -> Tuple[str, str]:
        _, db, tbl = self._route(name)
        return db, tbl

    # -- mutation ---------------------------------------------------------
    def create_database(self, name: str, if_not_exists: bool = False,
                        comment: Optional[str] = None,
                        location: Optional[str] = None):
        self.provider().create_database(name, if_not_exists, comment,
                                        location)

    def drop_database(self, name: str, if_exists: bool, cascade: bool):
        self.provider().drop_database(name, if_exists, cascade)

    def register_table(self, entry: TableEntry, replace: bool = False,
                       if_not_exists: bool = False):
        prov, db, _ = self._route(entry.name)
        prov.create_table(db, entry, replace, if_not_exists)

    def register_temp_view(self, name: str, plan: sp.QueryPlan,
                           replace: bool = True):
        key = name.lower()
        if key in self.temp_views and not replace:
            raise ValueError(f"temp view {name!r} already exists")
        self.temp_views[key] = TableEntry((name,), view_plan=plan)

    def drop_table(self, name: Tuple[str, ...], if_exists: bool = False,
                   is_view: bool = False):
        if len(name) == 1 and name[0].lower() in self.temp_views:
            del self.temp_views[name[0].lower()]
            return
        prov, db, tbl = self._route(name)
        prov.drop_table(db, tbl, if_exists)

    def list_tables(self, database: Optional[str] = None) -> List[TableEntry]:
        prov = self.provider()
        db = (database or self.current_database).lower()
        out = []
        for t in prov.list_tables(db):
            e = prov.get_table(db, t)
            if e is not None:
                out.append(e)
        out.extend(self.temp_views.values())
        return out

    def list_databases(self) -> List[str]:
        return self.provider().list_databases()


def configure_catalogs(manager: CatalogManager) -> None:
    """Register catalogs declared in config (reference: the reference's
    ``catalog.*`` AppConfig keys wiring providers into every session).

    ``catalog.list`` names the catalogs (comma separated); each gets a
    ``catalog.<name>.type`` plus type-specific keys — e.g.

        SAIL_CATALOG__LIST=prod
        SAIL_CATALOG__PROD__TYPE=iceberg_rest
        SAIL_CATALOG__PROD__URI=http://rest:8181

    Provider construction never touches the network (clients are lazy),
    so a down catalog server fails at first use, not session start.
    """
    from ..config import get as config_get

    names = str(config_get("catalog.list", "") or "")
    for nm in [s.strip() for s in names.split(",") if s.strip()]:
        key = nm.lower()
        ctype = str(config_get(f"catalog.{key}.type", "") or "").lower()
        try:
            if ctype in ("iceberg_rest", "iceberg-rest", "rest"):
                from .iceberg_rest import IcebergRestCatalog
                provider = IcebergRestCatalog(
                    nm,
                    uri=str(config_get(f"catalog.{key}.uri", "")),
                    warehouse=config_get(f"catalog.{key}.warehouse"),
                    token=config_get(f"catalog.{key}.token"),
                    prefix=config_get(f"catalog.{key}.prefix"))
            elif ctype in ("hms", "hive", "hive_metastore"):
                from .hms import HiveMetastoreCatalog
                provider = HiveMetastoreCatalog(
                    nm,
                    host=str(config_get(f"catalog.{key}.host",
                                        "localhost")),
                    port=int(config_get(f"catalog.{key}.port", 9083)))
            elif ctype == "glue":
                from .glue import GlueCatalog
                provider = GlueCatalog(
                    nm,
                    region=str(config_get(f"catalog.{key}.region",
                                          "us-east-1")),
                    endpoint=config_get(f"catalog.{key}.endpoint"),
                    access_key=config_get(f"catalog.{key}.access_key"),
                    secret_key=config_get(f"catalog.{key}.secret_key"),
                    catalog_id=config_get(f"catalog.{key}.catalog_id"))
            elif ctype == "unity":
                from .unity import UnityCatalog
                provider = UnityCatalog(
                    nm,
                    uri=str(config_get(f"catalog.{key}.uri", "")),
                    catalog_name=str(config_get(
                        f"catalog.{key}.catalog_name", "main")),
                    token=config_get(f"catalog.{key}.token"))
            elif ctype == "onelake":
                from .onelake import OneLakeCatalog
                provider = OneLakeCatalog(
                    nm,
                    workspace=str(config_get(
                        f"catalog.{key}.workspace", "")),
                    api=str(config_get(f"catalog.{key}.api", "delta")),
                    token=config_get(f"catalog.{key}.token"),
                    endpoint=config_get(f"catalog.{key}.endpoint"))
            elif ctype == "memory":
                from .provider import MemoryCatalogProvider
                provider = MemoryCatalogProvider(nm)
            else:
                raise ValueError(f"unknown catalog type {ctype!r}")
        except Exception as e:  # noqa: BLE001 — a bad catalog entry must
            # not take down the session; surface it on first use instead
            provider = _BrokenCatalog(nm, str(e))
        manager.providers[key] = provider
    default = config_get("catalog.default")
    if default:
        manager.current_catalog = str(default).lower()


class _BrokenCatalog:
    def __init__(self, name: str, error: str):
        self.name = name
        self._error = error

    def __getattr__(self, item):
        raise RuntimeError(
            f"catalog {self.name!r} failed to configure: {self._error}")
