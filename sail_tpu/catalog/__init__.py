"""Catalog layer: databases, tables, temp views.

Reference role: sail-catalog's CatalogProvider/CatalogManager plus the
in-memory provider (SURVEY.md §2.6). v0 ships the memory catalog; Glue/HMS/
Unity/Iceberg-REST providers slot in behind the same CatalogProvider
interface in later rounds.
"""

from .manager import CatalogManager, TableEntry  # noqa: F401
