"""CatalogProvider interface + the in-memory provider.

Reference role: crates/sail-catalog/src/provider/mod.rs:26-210 — the
abstraction every external catalog (HMS, Glue, Iceberg REST, Unity,
OneLake) implements, re-designed as a small Python ABC. Providers expose
databases and tables; the CatalogManager routes multi-part identifiers to
a provider and merges session-local temp views on top.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .manager import TableEntry


class CatalogError(RuntimeError):
    pass


class CatalogProvider:
    """One catalog: a namespace of databases each holding tables."""

    name: str = ""

    # -- databases -------------------------------------------------------
    def list_databases(self) -> List[str]:
        raise NotImplementedError

    def database_info(self, name: str) -> Optional[dict]:
        """{comment, location, ...} or None when absent."""
        raise NotImplementedError

    def create_database(self, name: str, if_not_exists: bool = False,
                        comment: Optional[str] = None,
                        location: Optional[str] = None) -> None:
        raise CatalogError(f"catalog {self.name!r} is read-only")

    def drop_database(self, name: str, if_exists: bool = False,
                      cascade: bool = False) -> None:
        raise CatalogError(f"catalog {self.name!r} is read-only")

    # -- tables ----------------------------------------------------------
    def list_tables(self, database: str) -> List[str]:
        raise NotImplementedError

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        raise NotImplementedError

    def create_table(self, database: str, entry: TableEntry,
                     replace: bool = False,
                     if_not_exists: bool = False) -> None:
        raise CatalogError(f"catalog {self.name!r} is read-only")

    def drop_table(self, database: str, table: str,
                   if_exists: bool = False) -> None:
        raise CatalogError(f"catalog {self.name!r} is read-only")


class MemoryCatalogProvider(CatalogProvider):
    """Default in-memory catalog (reference: crates/sail-catalog-memory)."""

    def __init__(self, name: str = "spark_catalog"):
        self.name = name
        self.databases: Dict[str, dict] = {"default": {}}
        self.tables: Dict[Tuple[str, str], TableEntry] = {}

    def list_databases(self) -> List[str]:
        return sorted(self.databases)

    def database_info(self, name: str) -> Optional[dict]:
        return self.databases.get(name.lower())

    def create_database(self, name, if_not_exists=False, comment=None,
                        location=None):
        key = name.lower()
        if key in self.databases:
            if if_not_exists:
                return
            raise ValueError(f"database {name!r} already exists")
        self.databases[key] = {"comment": comment, "location": location}

    def drop_database(self, name, if_exists=False, cascade=False):
        key = name.lower()
        if key not in self.databases:
            if if_exists:
                return
            raise ValueError(f"database {name!r} not found")
        tables = [k for k in self.tables if k[0] == key]
        if tables and not cascade:
            raise ValueError(f"database {name!r} is not empty")
        for k in tables:
            del self.tables[k]
        del self.databases[key]

    def list_tables(self, database: str) -> List[str]:
        db = database.lower()
        return sorted(t for (d, t) in self.tables if d == db)

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        return self.tables.get((database.lower(), table.lower()))

    def create_table(self, database, entry, replace=False,
                     if_not_exists=False):
        db = database.lower()
        tbl = entry.name[-1].lower()
        if db not in self.databases:
            raise ValueError(f"database {db!r} not found")
        if (db, tbl) in self.tables and not replace:
            if if_not_exists:
                return
            raise ValueError(f"table {'.'.join(entry.name)!r} already exists")
        self.tables[(db, tbl)] = entry

    def drop_table(self, database, table, if_exists=False):
        key = (database.lower(), table.lower())
        if key not in self.tables:
            if if_exists:
                return
            raise ValueError(f"table {table!r} not found")
        del self.tables[key]
