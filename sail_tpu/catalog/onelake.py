"""Microsoft Fabric OneLake catalog provider.

Reference role: crates/sail-catalog-onelake/src/provider.rs — OneLake
exposes its table metadata through two standard protocol endpoints
(``onelake.table.fabric.microsoft.com/delta`` speaks the Unity Catalog
REST API, ``.../iceberg`` speaks the Iceberg REST catalog API), so the
provider is a thin delegate over the existing Unity / Iceberg REST
clients pointed at the Fabric endpoint, with the workspace as the
catalog/warehouse scope. The ``endpoint`` option overrides the Fabric
URL, which is how the in-repo fake-server tests drive it.
"""

from __future__ import annotations

from typing import List, Optional

from .manager import TableEntry
from .provider import CatalogError, CatalogProvider

ONELAKE_DELTA_ENDPOINT = "https://onelake.table.fabric.microsoft.com/delta"
ONELAKE_ICEBERG_ENDPOINT = \
    "https://onelake.table.fabric.microsoft.com/iceberg"


class OneLakeCatalog(CatalogProvider):
    """api="delta" (default) delegates to the Unity REST client;
    api="iceberg" delegates to the Iceberg REST client."""

    def __init__(self, name: str, workspace: str,
                 api: str = "delta", token: Optional[str] = None,
                 endpoint: Optional[str] = None, timeout: float = 30.0):
        self.name = name
        self.workspace = workspace
        self.api = api.lower()
        if self.api == "iceberg":
            from .iceberg_rest import IcebergRestCatalog
            self._inner: CatalogProvider = IcebergRestCatalog(
                name, uri=endpoint or ONELAKE_ICEBERG_ENDPOINT,
                warehouse=workspace, token=token, timeout=timeout)
        elif self.api == "delta":
            from .unity import UnityCatalog
            self._inner = UnityCatalog(
                name, uri=endpoint or ONELAKE_DELTA_ENDPOINT,
                catalog_name=workspace, token=token, timeout=timeout)
        else:
            raise CatalogError(
                f"onelake api must be delta or iceberg, got {api!r}")

    # -- delegation ------------------------------------------------------
    def list_databases(self) -> List[str]:
        return self._inner.list_databases()

    def database_info(self, name: str) -> Optional[dict]:
        return self._inner.database_info(name)

    def create_database(self, name, if_not_exists=False, comment=None,
                        location=None):
        raise CatalogError("onelake catalog is read-only in this engine")

    def drop_database(self, name, if_exists=False, cascade=False):
        raise CatalogError("onelake catalog is read-only in this engine")

    def list_tables(self, database: str) -> List[str]:
        return self._inner.list_tables(database)

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        return self._inner.get_table(database, table)

    def create_table(self, database, entry, replace=False,
                     if_not_exists=False):
        raise CatalogError("onelake catalog is read-only in this engine")

    def drop_table(self, database, table, if_exists=False):
        raise CatalogError("onelake catalog is read-only in this engine")
