"""Iceberg REST catalog provider.

Reference role: crates/sail-catalog-iceberg/src/{provider,adapter}.rs —
a client for the Apache Iceberg REST Catalog Open API (config,
namespaces, tables) adapted onto the CatalogProvider interface. Tables
resolve to their current metadata location and scan through the engine's
own Iceberg reader (sail_tpu/lakehouse/iceberg).

Uses only the standard library HTTP client so it works against any
spec-conformant server. Tested in tests/test_catalog_providers.py against
an in-repo fake REST server (the KubernetesWorkerManager fake-API
pattern); registered from config via the ``catalog.*`` keys
(catalog/manager.py::configure_catalogs).
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from .manager import TableEntry
from .provider import CatalogError, CatalogProvider


class IcebergRestCatalog(CatalogProvider):
    def __init__(self, name: str, uri: str, warehouse: Optional[str] = None,
                 token: Optional[str] = None, prefix: Optional[str] = None,
                 timeout: float = 30.0):
        self.name = name
        self.uri = uri.rstrip("/")
        self.token = token
        self.timeout = timeout
        self.warehouse = warehouse
        self._prefix = prefix  # None → fetched lazily from /v1/config

    @property
    def prefix(self) -> str:
        # lazy: construction must not touch the network (config-registered
        # catalogs are built at session start even if unused)
        if self._prefix is None:
            cfg = self._request(
                "GET", "/v1/config",
                query={"warehouse": self.warehouse}
                if self.warehouse else None, default={}, raw_path=True)
            overrides = cfg.get("overrides", {}) \
                if isinstance(cfg, dict) else {}
            self._prefix = overrides.get("prefix", "")
        return self._prefix

    # -- HTTP ------------------------------------------------------------
    def _url(self, path: str, raw_path: bool = False) -> str:
        if not raw_path and self.prefix:
            path = path.replace("/v1/", f"/v1/{self.prefix}/", 1)
        return self.uri + path

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 query: Optional[dict] = None, default=None,
                 raw_path: bool = False):
        url = self._url(path, raw_path)
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in query.items() if v is not None})
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(url, data=data, method=method)
        req.add_header("Content-Type", "application/json")
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = resp.read()
                return json.loads(payload) if payload else {}
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return default
            detail = e.read().decode(errors="replace")[:500]
            raise CatalogError(
                f"iceberg rest {method} {path}: HTTP {e.code}: {detail}")
        except urllib.error.URLError as e:
            raise CatalogError(f"iceberg rest catalog unreachable: {e}")

    def _get(self, path, query=None, default=None):
        return self._request("GET", path, query=query, default=default)

    # -- databases (namespaces) -----------------------------------------
    def list_databases(self) -> List[str]:
        out = self._get("/v1/namespaces", default={"namespaces": []}) or {}
        return sorted(".".join(ns) for ns in out.get("namespaces", []))

    def database_info(self, name: str) -> Optional[dict]:
        ns = self._get(f"/v1/namespaces/{_ns(name)}", default=None)
        if ns is None:
            return None
        props = ns.get("properties", {})
        return {"comment": props.get("comment"),
                "location": props.get("location"), "properties": props}

    def create_database(self, name, if_not_exists=False, comment=None,
                        location=None):
        props = {}
        if comment:
            props["comment"] = comment
        if location:
            props["location"] = location
        try:
            self._request("POST", "/v1/namespaces",
                          {"namespace": name.split("."),
                           "properties": props})
        except CatalogError as e:
            if "409" in str(e) and if_not_exists:
                return
            raise

    def drop_database(self, name, if_exists=False, cascade=False):
        got = self._request("DELETE", f"/v1/namespaces/{_ns(name)}",
                            default="__missing__")
        if got == "__missing__" and not if_exists:
            raise ValueError(f"database {name!r} not found")

    # -- tables ----------------------------------------------------------
    def list_tables(self, database: str) -> List[str]:
        out = self._get(f"/v1/namespaces/{_ns(database)}/tables",
                        default={"identifiers": []}) or {}
        return sorted(i["name"] for i in out.get("identifiers", []))

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        got = self._get(f"/v1/namespaces/{_ns(database)}/tables/{table}",
                        default=None)
        if got is None:
            return None
        meta = got.get("metadata", {})
        location = got.get("metadata-location") or meta.get("location")
        if location is None:
            return None
        from ..lakehouse.iceberg.table import _iceberg_type_to_spec
        schema = None
        try:
            schemas = meta.get("schemas") or []
            current = meta.get("current-schema-id")
            raw = next((s for s in schemas if s.get("schema-id") == current),
                       schemas[0] if schemas else None)
            if raw is not None:
                schema = _iceberg_type_to_spec(raw)
        except Exception:
            schema = None
        # table root (metadata-location points at …/metadata/xxx.json)
        root = meta.get("location") or location.rsplit("/metadata/", 1)[0]
        return TableEntry(
            name=(self.name, database, table), schema=schema,
            paths=(root,), format="iceberg",
            options=(("metadata_location", location),))

    def create_table(self, database, entry, replace=False,
                     if_not_exists=False):
        from ..lakehouse.iceberg.table import _spec_to_iceberg_schema
        schema, _ = _spec_to_iceberg_schema(entry.schema)
        body = {"name": entry.name[-1], "schema": schema}
        if entry.paths:
            body["location"] = entry.paths[0]
        try:
            self._request("POST",
                          f"/v1/namespaces/{_ns(database)}/tables", body)
        except CatalogError as e:
            if "409" in str(e) and if_not_exists:
                return
            raise

    def drop_table(self, database, table, if_exists=False):
        got = self._request(
            "DELETE", f"/v1/namespaces/{_ns(database)}/tables/{table}",
            default="__missing__")
        if got == "__missing__" and not if_exists:
            raise ValueError(f"table {table!r} not found")


def _ns(name: str) -> str:
    # multipart namespaces use the 0x1F unit separator per the REST spec
    return urllib.parse.quote("\x1f".join(name.split(".")), safe="")
