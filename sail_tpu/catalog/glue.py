"""AWS Glue Data Catalog provider.

Reference role: crates/sail-catalog-glue/src/provider.rs (aws-sdk-glue
there). This build speaks the Glue JSON protocol directly: POST to the
service endpoint with ``X-Amz-Target: AWSGlue.<Operation>`` and
``application/x-amz-json-1.1`` bodies, signed with SigV4 (implemented
from the public spec — no AWS SDK ships in this image). Table semantics
are Hive-shaped, so type parsing and format mapping are shared with the
HMS provider (catalog/hms.py). A custom ``endpoint`` option supports
moto-style fakes and VPC endpoints, as the reference does.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import json
import os
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from ..spec import data_type as dt
from .hms import HiveMetastoreCatalog, parse_hive_type, _hive_type_name
from .manager import TableEntry
from .provider import CatalogError, CatalogProvider


def _sign_v4(method: str, url: str, region: str, service: str,
             headers: Dict[str, str], body: bytes,
             access_key: str, secret_key: str,
             token: Optional[str] = None) -> Dict[str, str]:
    """AWS Signature Version 4 (public spec)."""
    from urllib.parse import urlparse

    parsed = urlparse(url)
    now = datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    date_stamp = now.strftime("%Y%m%d")
    out = dict(headers)
    out["Host"] = parsed.netloc
    out["X-Amz-Date"] = amz_date
    if token:
        out["X-Amz-Security-Token"] = token
    signed_names = sorted(k.lower() for k in out)
    canonical_headers = "".join(
        f"{k}:{out[next(h for h in out if h.lower() == k)].strip()}\n"
        for k in signed_names)
    payload_hash = hashlib.sha256(body).hexdigest()
    canonical = "\n".join([
        method, parsed.path or "/", parsed.query,
        canonical_headers, ";".join(signed_names), payload_hash])
    scope = f"{date_stamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), date_stamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed_names)}, Signature={signature}")
    return out


class GlueCatalog(CatalogProvider):
    def __init__(self, name: str, region: str = "us-east-1",
                 endpoint: Optional[str] = None,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None,
                 session_token: Optional[str] = None,
                 catalog_id: Optional[str] = None,
                 timeout: float = 30.0):
        self.name = name
        self.region = region
        self.endpoint = (endpoint
                         or f"https://glue.{region}.amazonaws.com")
        self.access_key = access_key or os.environ.get("AWS_ACCESS_KEY_ID",
                                                       "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        self.session_token = session_token or os.environ.get(
            "AWS_SESSION_TOKEN")
        self.catalog_id = catalog_id
        self.timeout = timeout

    # -- protocol --------------------------------------------------------
    def _call(self, operation: str, payload: dict) -> dict:
        if self.catalog_id:
            payload = {"CatalogId": self.catalog_id, **payload}
        body = json.dumps(payload).encode()
        headers = {
            "Content-Type": "application/x-amz-json-1.1",
            "X-Amz-Target": f"AWSGlue.{operation}",
        }
        headers = _sign_v4("POST", self.endpoint, self.region, "glue",
                           headers, body, self.access_key, self.secret_key,
                           self.session_token)
        req = urllib.request.Request(self.endpoint, data=body,
                                     method="POST", headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = resp.read()
                return json.loads(data) if data else {}
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")[:400]
            if "EntityNotFoundException" in detail or e.code == 404:
                raise _NotFound(detail)
            raise CatalogError(f"glue {operation}: HTTP {e.code}: {detail}")
        except urllib.error.URLError as e:
            raise CatalogError(f"glue catalog unreachable: {e}")

    # -- databases -------------------------------------------------------
    def list_databases(self) -> List[str]:
        out = self._call("GetDatabases", {})
        return sorted(d["Name"] for d in out.get("DatabaseList", []))

    def database_info(self, name: str) -> Optional[dict]:
        try:
            out = self._call("GetDatabase", {"Name": name})
        except _NotFound:
            return None
        db = out.get("Database", {})
        return {"comment": db.get("Description"),
                "location": db.get("LocationUri"),
                "properties": db.get("Parameters", {})}

    def create_database(self, name, if_not_exists=False, comment=None,
                        location=None):
        body = {"DatabaseInput": {"Name": name}}
        if comment:
            body["DatabaseInput"]["Description"] = comment
        if location:
            body["DatabaseInput"]["LocationUri"] = location
        try:
            self._call("CreateDatabase", body)
        except CatalogError as e:
            if if_not_exists and "AlreadyExists" in str(e):
                return
            raise

    def drop_database(self, name, if_exists=False, cascade=False):
        try:
            self._call("DeleteDatabase", {"Name": name})
        except (_NotFound, CatalogError):
            if not if_exists:
                raise

    # -- tables ----------------------------------------------------------
    def list_tables(self, database: str) -> List[str]:
        out = self._call("GetTables", {"DatabaseName": database})
        return sorted(t["Name"] for t in out.get("TableList", []))

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        try:
            out = self._call("GetTable", {"DatabaseName": database,
                                          "Name": table})
        except _NotFound:
            return None
        t = out.get("Table")
        if t is None:
            return None
        sd = t.get("StorageDescriptor", {}) or {}
        params = t.get("Parameters", {}) or {}
        fields = []
        for c in sd.get("Columns", []) or []:
            try:
                typ = parse_hive_type(c.get("Type", "string"))
            except CatalogError:
                typ = dt.StringType()
            fields.append(dt.StructField(c.get("Name", ""), typ, True))
        schema = dt.StructType(tuple(fields)) if fields else None
        fmt, options = HiveMetastoreCatalog._format_of(
            params, {3: sd.get("InputFormat", "")})
        part_cols = tuple(c.get("Name", "")
                          for c in (t.get("PartitionKeys") or []))
        return TableEntry(
            name=(self.name, database, table), schema=schema,
            paths=(sd.get("Location"),) if sd.get("Location") else (),
            format=fmt, options=options, partition_by=part_cols,
            comment=t.get("Description"))

    def create_table(self, database, entry: TableEntry, replace=False,
                     if_not_exists=False):
        cols = [{"Name": f.name, "Type": _hive_type_name(f.data_type)}
                for f in (entry.schema.fields if entry.schema else ())]
        params = {"EXTERNAL": "TRUE"}
        if entry.format == "iceberg":
            params["table_type"] = "ICEBERG"
        elif entry.format:
            params["spark.sql.sources.provider"] = entry.format
        body = {"DatabaseName": database, "TableInput": {
            "Name": entry.name[-1],
            "TableType": "EXTERNAL_TABLE",
            "Parameters": params,
            "StorageDescriptor": {
                "Columns": cols,
                "Location": entry.paths[0] if entry.paths else "",
            },
        }}
        try:
            self._call("CreateTable", body)
        except CatalogError as e:
            if if_not_exists and "AlreadyExists" in str(e):
                return
            raise

    def drop_table(self, database, table, if_exists=False):
        try:
            self._call("DeleteTable", {"DatabaseName": database,
                                       "Name": table})
        except (_NotFound, CatalogError):
            if not if_exists:
                raise


class _NotFound(Exception):
    pass
