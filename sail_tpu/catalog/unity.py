"""Databricks Unity Catalog provider (REST).

Reference role: crates/sail-catalog-unity (OpenAPI-generated REST client
there). This build speaks the open Unity Catalog REST API
(``/api/2.1/unity-catalog``: schemas, tables) with bearer-token auth —
the same surface the open-source unitycatalog server exposes, so it is
testable against an in-repo fake. Column types arrive as Spark
``type_text`` strings and parse with the shared hive/spark type parser.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import List, Optional

from ..spec import data_type as dt
from .hms import parse_hive_type
from .manager import TableEntry
from .provider import CatalogError, CatalogProvider


class UnityCatalog(CatalogProvider):
    def __init__(self, name: str, uri: str, catalog_name: str,
                 token: Optional[str] = None, timeout: float = 30.0):
        self.name = name
        self.uri = uri.rstrip("/")
        self.catalog_name = catalog_name
        self.token = token
        self.timeout = timeout

    def _get(self, path: str, query: Optional[dict] = None, default=None):
        url = f"{self.uri}/api/2.1/unity-catalog{path}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url)
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                data = resp.read()
                return json.loads(data) if data else {}
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return default
            detail = e.read().decode(errors="replace")[:400]
            raise CatalogError(f"unity GET {path}: HTTP {e.code}: {detail}")
        except urllib.error.URLError as e:
            raise CatalogError(f"unity catalog unreachable: {e}")

    # -- databases (schemas) ---------------------------------------------
    def list_databases(self) -> List[str]:
        out = self._get("/schemas",
                        {"catalog_name": self.catalog_name}) or {}
        return sorted(s["name"] for s in out.get("schemas", []))

    def database_info(self, name: str) -> Optional[dict]:
        out = self._get(f"/schemas/{self.catalog_name}.{name}",
                        default=None)
        if out is None:
            return None
        return {"comment": out.get("comment"),
                "location": out.get("storage_location"),
                "properties": out.get("properties", {})}

    def create_database(self, name, if_not_exists=False, comment=None,
                        location=None):
        raise CatalogError("unity catalog is read-only in this engine")

    def drop_database(self, name, if_exists=False, cascade=False):
        raise CatalogError("unity catalog is read-only in this engine")

    # -- tables ----------------------------------------------------------
    def list_tables(self, database: str) -> List[str]:
        out = self._get("/tables", {"catalog_name": self.catalog_name,
                                    "schema_name": database}) or {}
        return sorted(t["name"] for t in out.get("tables", []))

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        full = f"{self.catalog_name}.{database}.{table}"
        t = self._get(f"/tables/{full}", default=None)
        if t is None:
            return None
        fields = []
        for c in t.get("columns", []) or []:
            try:
                typ = parse_hive_type(c.get("type_text", "string"))
            except CatalogError:
                typ = dt.StringType()
            fields.append(dt.StructField(c.get("name", ""), typ,
                                         bool(c.get("nullable", True))))
        schema = dt.StructType(tuple(fields)) if fields else None
        fmt = (t.get("data_source_format") or "parquet").lower()
        if fmt == "delta":
            engine_fmt = "delta"
        elif fmt in ("parquet", "csv", "json", "avro"):
            engine_fmt = fmt
        else:
            engine_fmt = "parquet"
        location = t.get("storage_location")
        return TableEntry(
            name=(self.name, database, table), schema=schema,
            paths=(location,) if location else (), format=engine_fmt,
            comment=t.get("comment"))

    def create_table(self, database, entry, replace=False,
                     if_not_exists=False):
        raise CatalogError("unity catalog is read-only in this engine")

    def drop_table(self, database, table, if_exists=False):
        raise CatalogError("unity catalog is read-only in this engine")
