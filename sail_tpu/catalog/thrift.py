"""Minimal Apache Thrift binary-protocol codec + socket client.

Reference role: the reference talks to Hive Metastore over volo-thrift
codegen (crates/sail-common-hms/src/lib.rs, sail-catalog-hms). No thrift
library ships in this environment, so this implements the TBinaryProtocol
strict wire format from scratch — enough for the HMS call surface the
catalog provider needs. Generic decoding: structs come back as
{field_id: value} dicts, so no per-struct codegen is required; the HMS
provider maps well-known field ids (hive_metastore.thrift) onto names.
"""

from __future__ import annotations

import io
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

# TType ids
BOOL, BYTE, DOUBLE = 2, 3, 4
I16, I32, I64 = 6, 8, 10
STRING, STRUCT, MAP, SET, LST = 11, 12, 13, 14, 15
STOP = 0

VERSION_1 = 0x80010000
MSG_CALL, MSG_REPLY, MSG_EXCEPTION = 1, 2, 3


class ThriftError(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# encoding — values are (ttype, payload) pairs for explicitness
# ---------------------------------------------------------------------------

def enc_value(out: bytearray, ttype: int, v: Any) -> None:
    if ttype == BOOL:
        out.append(1 if v else 0)
    elif ttype == BYTE:
        out += struct.pack(">b", v)
    elif ttype == DOUBLE:
        out += struct.pack(">d", v)
    elif ttype == I16:
        out += struct.pack(">h", v)
    elif ttype == I32:
        out += struct.pack(">i", v)
    elif ttype == I64:
        out += struct.pack(">q", v)
    elif ttype == STRING:
        b = v.encode() if isinstance(v, str) else bytes(v)
        out += struct.pack(">i", len(b))
        out += b
    elif ttype == STRUCT:
        # v: list of (field_id, ttype, value)
        for fid, ft, fv in v:
            if fv is None:
                continue
            out.append(ft)
            out += struct.pack(">h", fid)
            enc_value(out, ft, fv)
        out.append(STOP)
    elif ttype == LST or ttype == SET:
        et, items = v  # (elem ttype, [values])
        out.append(et)
        out += struct.pack(">i", len(items))
        for it in items:
            enc_value(out, et, it)
    elif ttype == MAP:
        kt, vt, entries = v
        out.append(kt)
        out.append(vt)
        out += struct.pack(">i", len(entries))
        for k, val in entries.items():
            enc_value(out, kt, k)
            enc_value(out, vt, val)
    else:
        raise ThriftError(f"cannot encode ttype {ttype}")


def dec_value(buf: io.BytesIO, ttype: int) -> Any:
    if ttype == BOOL:
        return buf.read(1) == b"\x01"
    if ttype == BYTE:
        return struct.unpack(">b", buf.read(1))[0]
    if ttype == DOUBLE:
        return struct.unpack(">d", buf.read(8))[0]
    if ttype == I16:
        return struct.unpack(">h", buf.read(2))[0]
    if ttype == I32:
        return struct.unpack(">i", buf.read(4))[0]
    if ttype == I64:
        return struct.unpack(">q", buf.read(8))[0]
    if ttype == STRING:
        n = struct.unpack(">i", buf.read(4))[0]
        b = buf.read(n)
        try:
            return b.decode()
        except UnicodeDecodeError:
            return b
    if ttype == STRUCT:
        out: Dict[int, Any] = {}
        while True:
            ft = buf.read(1)
            if not ft or ft[0] == STOP:
                return out
            fid = struct.unpack(">h", buf.read(2))[0]
            out[fid] = dec_value(buf, ft[0])
    if ttype in (LST, SET):
        et = buf.read(1)[0]
        n = struct.unpack(">i", buf.read(4))[0]
        return [dec_value(buf, et) for _ in range(n)]
    if ttype == MAP:
        kt = buf.read(1)[0]
        vt = buf.read(1)[0]
        n = struct.unpack(">i", buf.read(4))[0]
        return {dec_value(buf, kt): dec_value(buf, vt) for _ in range(n)}
    raise ThriftError(f"cannot decode ttype {ttype}")


def encode_message(name: str, seqid: int,
                   args: List[Tuple[int, int, Any]],
                   msg_type: int = MSG_CALL) -> bytes:
    out = bytearray()
    out += struct.pack(">I", VERSION_1 | msg_type)
    enc_value(out, STRING, name)
    out += struct.pack(">i", seqid)
    enc_value(out, STRUCT, args)
    return bytes(out)


def decode_message(data: bytes):
    """→ (name, seqid, msg_type, result {field_id: value})."""
    buf = io.BytesIO(data)
    head = struct.unpack(">I", buf.read(4))[0]
    if head & 0x80000000:
        msg_type = head & 0xFF
        name = dec_value(buf, STRING)
    else:  # old unframed format: string first
        buf.seek(0)
        name = dec_value(buf, STRING)
        msg_type = struct.unpack(">b", buf.read(1))[0]
    seqid = struct.unpack(">i", buf.read(4))[0]
    result = dec_value(buf, STRUCT)
    return name, seqid, msg_type, result


class ThriftClient:
    """Blocking call client over a plain socket (TBufferedTransport).

    HMS replies are read by incremental struct decoding, so no framing is
    required (matches the metastore's default unframed transport)."""

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._sock: Optional[socket.socket] = None
        self._seq = 0

    def _connect(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout)

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def call(self, method: str, args: List[Tuple[int, int, Any]]):
        """Invoke; returns the result struct's field 0 (success) or raises
        on declared exceptions (any other field set)."""
        self._connect()
        self._seq += 1
        payload = encode_message(method, self._seq, args)
        try:
            self._sock.sendall(payload)
            data = self._read_reply()
        except (OSError, EOFError) as e:
            self.close()
            raise ThriftError(f"hms rpc {method}: {e}")
        name, _seq, msg_type, result = decode_message(data)
        if msg_type == MSG_EXCEPTION:
            raise ThriftError(
                f"hms {method}: {result.get(1, 'application exception')}")
        errs = {k: v for k, v in result.items() if k != 0}
        if errs and 0 not in result:
            detail = next(iter(errs.values()))
            if isinstance(detail, dict):
                detail = detail.get(1, detail)
            raise ThriftError(f"hms {method}: {detail}")
        return result.get(0)

    def _read_reply(self) -> bytes:
        # read until a full message parses (messages are small; HMS closes
        # or blocks between replies, so incremental parse-and-retry works)
        chunks = bytearray()
        while True:
            b = self._sock.recv(65536)
            if not b:
                if chunks:
                    return bytes(chunks)
                raise EOFError("connection closed")
            chunks += b
            try:
                decode_message(bytes(chunks))
                return bytes(chunks)
            except Exception:  # noqa: BLE001 — incomplete; keep reading
                continue
