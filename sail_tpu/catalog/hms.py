"""Hive Metastore catalog provider (Thrift).

Reference role: crates/sail-catalog-hms/src/provider.rs (HMS provider
over volo-thrift) — here on the in-repo binary-protocol client
(catalog/thrift.py). Field-id mappings follow hive_metastore.thrift:

  Database:          1 name, 2 description, 3 locationUri, 4 parameters
  Table:             1 tableName, 2 dbName, 7 sd, 8 partitionKeys,
                     9 parameters, 12 tableType
  StorageDescriptor: 1 cols, 2 location, 3 inputFormat
  FieldSchema:       1 name, 2 type, 3 comment

Hive table → engine format mapping: Iceberg tables are recognized by the
``table_type=ICEBERG`` parameter (metadata_location parameter carries the
snapshot pointer), Delta by ``spark.sql.sources.provider=delta``; other
locations scan as parquet/csv/json by input format.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..spec import data_type as dt
from .manager import TableEntry
from .provider import CatalogError, CatalogProvider
from . import thrift as tp


def parse_hive_type(s: str) -> dt.DataType:
    s = s.strip()
    low = s.lower()
    prim = {
        "boolean": dt.BooleanType(), "tinyint": dt.ByteType(),
        "smallint": dt.ShortType(), "int": dt.IntegerType(),
        "integer": dt.IntegerType(), "bigint": dt.LongType(),
        "float": dt.FloatType(), "double": dt.DoubleType(),
        "string": dt.StringType(), "varchar": dt.StringType(),
        "char": dt.StringType(), "binary": dt.BinaryType(),
        "date": dt.DateType(), "timestamp": dt.TimestampType("UTC"),
    }
    if low in prim:
        return prim[low]
    if low.startswith(("varchar(", "char(")):
        return dt.StringType()
    if low.startswith("decimal"):
        if "(" in low:
            p, s_ = low[low.index("(") + 1:low.index(")")].split(",")
            return dt.DecimalType(int(p), int(s_))
        return dt.DecimalType(10, 0)
    if low.startswith("array<") and low.endswith(">"):
        return dt.ArrayType(parse_hive_type(s[6:-1]), True)
    if low.startswith("map<") and low.endswith(">"):
        inner = s[4:-1]
        k, v = _split_top(inner)
        return dt.MapType(parse_hive_type(k), parse_hive_type(v), True)
    if low.startswith("struct<") and low.endswith(">"):
        fields = []
        for part in _split_all(s[7:-1]):
            name, _, typ = part.partition(":")
            fields.append(dt.StructField(name.strip(),
                                         parse_hive_type(typ), True))
        return dt.StructType(tuple(fields))
    raise CatalogError(f"unsupported hive type {s!r}")


def _split_top(s: str):
    depth = 0
    for i, ch in enumerate(s):
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        elif ch == "," and depth == 0:
            return s[:i], s[i + 1:]
    raise CatalogError(f"bad hive map type {s!r}")


def _split_all(s: str) -> List[str]:
    out, depth, start = [], 0, 0
    for i, ch in enumerate(s):
        if ch == "<":
            depth += 1
        elif ch == ">":
            depth -= 1
        elif ch == "," and depth == 0:
            out.append(s[start:i])
            start = i + 1
    if s[start:].strip():
        out.append(s[start:])
    return out


class HiveMetastoreCatalog(CatalogProvider):
    def __init__(self, name: str, host: str, port: int = 9083,
                 timeout: float = 30.0):
        self.name = name
        self.client = tp.ThriftClient(host, port, timeout)

    # -- databases -------------------------------------------------------
    def list_databases(self) -> List[str]:
        return sorted(self.client.call("get_all_databases", []) or [])

    def database_info(self, name: str) -> Optional[dict]:
        try:
            db = self.client.call("get_database",
                                  [(1, tp.STRING, name)])
        except tp.ThriftError:
            return None
        if not isinstance(db, dict):
            return None
        return {"comment": db.get(2), "location": db.get(3),
                "properties": db.get(4, {})}

    def create_database(self, name, if_not_exists=False, comment=None,
                        location=None):
        db = [(1, tp.STRING, name)]
        if comment:
            db.append((2, tp.STRING, comment))
        if location:
            db.append((3, tp.STRING, location))
        try:
            self.client.call("create_database", [(1, tp.STRUCT, db)])
        except tp.ThriftError as e:
            if if_not_exists and "exist" in str(e).lower():
                return
            raise CatalogError(str(e))

    def drop_database(self, name, if_exists=False, cascade=False):
        try:
            self.client.call("drop_database",
                             [(1, tp.STRING, name), (2, tp.BOOL, False),
                              (3, tp.BOOL, cascade)])
        except tp.ThriftError as e:
            if if_exists:
                return
            raise CatalogError(str(e))

    # -- tables ----------------------------------------------------------
    def list_tables(self, database: str) -> List[str]:
        out = self.client.call("get_all_tables",
                               [(1, tp.STRING, database)])
        return sorted(out or [])

    def get_table(self, database: str, table: str) -> Optional[TableEntry]:
        try:
            t = self.client.call("get_table", [(1, tp.STRING, database),
                                               (2, tp.STRING, table)])
        except tp.ThriftError:
            return None
        if not isinstance(t, dict):
            return None
        sd = t.get(7, {}) or {}
        params: Dict[str, str] = t.get(9, {}) or {}
        cols = sd.get(1, []) or []
        fields = []
        for c in cols:
            try:
                fields.append(dt.StructField(
                    c.get(1, ""), parse_hive_type(c.get(2, "string")), True))
            except CatalogError:
                fields.append(dt.StructField(c.get(1, ""), dt.StringType(),
                                             True))
        schema = dt.StructType(tuple(fields)) if fields else None
        location = sd.get(2)
        fmt, options = self._format_of(params, sd)
        part_cols = tuple(c.get(1, "") for c in (t.get(8, []) or []))
        return TableEntry(
            name=(self.name, database, table), schema=schema,
            paths=(location,) if location else (), format=fmt,
            options=options, partition_by=part_cols,
            comment=params.get("comment"))

    @staticmethod
    def _format_of(params: Dict[str, str], sd: dict):
        lowered = {str(k).lower(): str(v) for k, v in params.items()}
        if lowered.get("table_type", "").upper() == "ICEBERG":
            opts = ()
            ml = lowered.get("metadata_location")
            if ml:
                opts = (("metadata_location", ml),)
            return "iceberg", opts
        provider = lowered.get("spark.sql.sources.provider", "").lower()
        if provider == "delta":
            return "delta", ()
        if provider in ("parquet", "csv", "json", "orc", "avro"):
            return provider, ()
        input_fmt = str(sd.get(3, "")).lower()
        if "parquet" in input_fmt:
            return "parquet", ()
        if "text" in input_fmt:
            return "csv", ()
        return "parquet", ()

    def create_table(self, database, entry: TableEntry, replace=False,
                     if_not_exists=False):
        from ..columnar.arrow_interop import spec_type_to_arrow  # noqa: F401

        cols = []
        for f in (entry.schema.fields if entry.schema else ()):
            cols.append((tp.STRUCT, [
                (1, tp.STRING, f.name),
                (2, tp.STRING, _hive_type_name(f.data_type))]))
        sd = [(1, tp.LST, (tp.STRUCT, [c[1] for c in cols])),
              (2, tp.STRING, entry.paths[0] if entry.paths else "")]
        params = {"EXTERNAL": "TRUE"}
        if entry.format == "iceberg":
            params["table_type"] = "ICEBERG"
        elif entry.format:
            params["spark.sql.sources.provider"] = entry.format
        tbl = [(1, tp.STRING, entry.name[-1]),
               (2, tp.STRING, database),
               (7, tp.STRUCT, sd),
               (9, tp.MAP, (tp.STRING, tp.STRING, params)),
               (12, tp.STRING, "EXTERNAL_TABLE")]
        try:
            self.client.call("create_table", [(1, tp.STRUCT, tbl)])
        except tp.ThriftError as e:
            if if_not_exists and "exist" in str(e).lower():
                return
            raise CatalogError(str(e))

    def drop_table(self, database, table, if_exists=False):
        try:
            self.client.call("drop_table",
                             [(1, tp.STRING, database),
                              (2, tp.STRING, table), (3, tp.BOOL, False)])
        except tp.ThriftError as e:
            if if_exists:
                return
            raise CatalogError(str(e))


def _hive_type_name(t: dt.DataType) -> str:
    m = {dt.BooleanType: "boolean", dt.ByteType: "tinyint",
         dt.ShortType: "smallint", dt.IntegerType: "int",
         dt.LongType: "bigint", dt.FloatType: "float",
         dt.DoubleType: "double", dt.StringType: "string",
         dt.BinaryType: "binary", dt.DateType: "date"}
    for cls, name in m.items():
        if isinstance(t, cls):
            return name
    if isinstance(t, dt.DecimalType):
        return f"decimal({t.precision},{t.scale})"
    if isinstance(t, dt.TimestampType):
        return "timestamp"
    if isinstance(t, dt.ArrayType):
        return f"array<{_hive_type_name(t.element_type)}>"
    if isinstance(t, dt.MapType):
        return (f"map<{_hive_type_name(t.key_type)},"
                f"{_hive_type_name(t.value_type)}>")
    if isinstance(t, dt.StructType):
        inner = ",".join(f"{f.name}:{_hive_type_name(f.data_type)}"
                         for f in t.fields)
        return f"struct<{inner}>"
    return "string"
