"""sail-tpu: a TPU-native distributed compute framework with the
capabilities of Sail (lakehq/sail) — Spark SQL / DataFrame plans executed on
a columnar engine built on jax/XLA/Pallas, with distributed shuffle as ICI
collectives over a jax.sharding.Mesh.

Layering (mirrors SURVEY.md §1, re-designed TPU-first):

    session / DataFrame API / SQL          (front-ends)
      → spec IR                            (sail_tpu.spec)
      → resolver → logical plan            (sail_tpu.plan)
      → optimizer → physical plan          (sail_tpu.plan)
      → executor: jitted columnar ops      (sail_tpu.ops on sail_tpu.columnar)
      → distributed: mesh + collectives    (sail_tpu.parallel, sail_tpu.exec)
      → io / formats / catalog             (sail_tpu.io, sail_tpu.catalog)
"""

__version__ = "0.1.0"

import os as _os

import jax as _jax

# SQL semantics require 64-bit integers/floats on device (bigint, double,
# epoch-microsecond timestamps, scaled-int64 decimals).
if _os.environ.get("SAIL_TPU_DISABLE_X64") != "1":
    _jax.config.update("jax_enable_x64", True)

from .session import SparkSession  # noqa: F401

from .functions.udf import pandas_udf, udf  # noqa: F401,E402
from .session import Column, DataFrame, col, lit  # noqa: F401,E402
