"""Arrow Flight SQL front-end.

Reference role: crates/sail-flight/src/service.rs:70-207 — the minimal
Flight SQL surface: handshake, ``get_flight_info`` for a statement (plan
the SQL, return a ticket + schema), ``do_get`` (execute through the same
session/plan stack and stream record batches).

Protocol notes: Flight SQL wraps commands as ``google.protobuf.Any`` over
``arrow.flight.protocol.sql.CommandStatementQuery``. Those two messages
are tiny, so they are decoded with hand-rolled protobuf wire parsing
instead of vendored codegen; plain UTF-8 SQL bytes in the descriptor are
accepted too (handy for generic ``pyarrow.flight`` clients).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, Optional

import pyarrow as pa
import pyarrow.flight as fl

_ANY_PREFIX = b"type.googleapis.com/arrow.flight.protocol.sql."


def _read_varint(buf: bytes, pos: int):
    out = 0
    shift = 0
    while True:
        b = buf[pos]
        out |= (b & 0x7F) << shift
        pos += 1
        if not b & 0x80:
            return out, pos
        shift += 7


def _proto_fields(buf: bytes) -> Dict[int, list]:
    """Minimal protobuf wire decoder: field number → list of raw values
    (bytes for length-delimited, int for varint)."""
    fields: Dict[int, list] = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:  # varint
            v, pos = _read_varint(buf, pos)
        elif wire == 2:  # length-delimited
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wire == 5:  # fixed32
            v = buf[pos:pos + 4]
            pos += 4
        elif wire == 1:  # fixed64
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wire}")
        fields.setdefault(field, []).append(v)
    return fields


def _write_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _proto_field(field: int, value: bytes) -> bytes:
    return _write_varint((field << 3) | 2) + _write_varint(len(value)) + value


def pack_statement_query(sql: str) -> bytes:
    """Build an Any-wrapped CommandStatementQuery (what a Flight SQL
    client puts in the FlightDescriptor command)."""
    cmd = _proto_field(1, sql.encode())  # CommandStatementQuery.query = 1
    any_msg = _proto_field(1, _ANY_PREFIX + b"CommandStatementQuery") + \
        _proto_field(2, cmd)
    return any_msg


def decode_statement_command(command: bytes) -> Optional[str]:
    """FlightDescriptor.command → SQL text (Any-wrapped Flight SQL
    CommandStatementQuery / TicketStatementQuery, or raw UTF-8 SQL)."""
    if not command:
        return None
    try:
        fields = _proto_fields(command)
        type_url = fields.get(1, [b""])[0]
        if isinstance(type_url, bytes) and type_url.startswith(_ANY_PREFIX):
            inner = _proto_fields(fields[2][0])
            val = inner.get(1, [b""])[0]
            return val.decode() if isinstance(val, bytes) else None
    except (ValueError, IndexError, KeyError, UnicodeDecodeError):
        pass
    try:
        return command.decode()
    except UnicodeDecodeError:
        return None


class FlightSqlServer(fl.FlightServerBase):
    """Flight SQL server over the engine's session/plan stack."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_timeout_s: float = 3600.0):
        location = f"grpc://{host}:{port}"
        super().__init__(location)
        self._host = host
        self._lock = threading.Lock()
        # one engine session per Flight client identity is overkill for the
        # minimal surface; a single shared session mirrors the reference's
        # default-session behavior (service.rs:70)
        from .session import SparkSession
        self._spark = SparkSession()
        self._tickets: Dict[bytes, tuple] = {}  # ticket -> (sql, born_ts)
        self._ticket_ttl_s = 600.0

    @property
    def session(self):
        return self._spark

    def _plan_schema(self, sql: str) -> pa.Schema:
        from .columnar.arrow_interop import spec_type_to_arrow
        node = self._spark._resolve(self._spark.sql(sql)._plan)
        return pa.schema([(f.name, spec_type_to_arrow(f.dtype))
                          for f in node.schema])

    # -- FlightServerBase ------------------------------------------------
    def get_flight_info(self, context, descriptor):
        sql = decode_statement_command(descriptor.command)
        if sql is None:
            raise fl.FlightServerError("descriptor carries no SQL statement")
        schema = self._plan_schema(sql)
        ticket_bytes = uuid.uuid4().hex.encode()
        now = time.time()
        with self._lock:
            # prune tickets never redeemed (planning-only clients)
            expired = [t for t, (_, born) in self._tickets.items()
                       if now - born > self._ticket_ttl_s]
            for t in expired:
                del self._tickets[t]
            self._tickets[ticket_bytes] = (sql, now)
        endpoint = fl.FlightEndpoint(
            ticket_bytes, [f"grpc://{self._host}:{self.port}"])
        return fl.FlightInfo(schema, descriptor, [endpoint], -1, -1)

    def do_get(self, context, ticket):
        with self._lock:
            entry = self._tickets.pop(ticket.ticket, None)
            sql = entry[0] if entry else None
        if sql is None:
            # direct-ticket mode: ticket IS the statement (Flight SQL
            # TicketStatementQuery or raw SQL)
            sql = decode_statement_command(ticket.ticket)
        if sql is None:
            raise fl.FlightServerError("unknown ticket")
        table = self._spark.sql(sql).toArrow()
        return fl.RecordBatchStream(table)

    def get_schema(self, context, descriptor):
        sql = decode_statement_command(descriptor.command)
        if sql is None:
            raise fl.FlightServerError("descriptor carries no SQL statement")
        return fl.SchemaResult(self._plan_schema(sql))

    def do_action(self, context, action):
        if action.type == "health":
            return iter([fl.Result(b"ok")])
        raise fl.FlightServerError(f"unsupported action {action.type!r}")
