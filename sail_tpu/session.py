"""Session entry point (placeholder; filled in by the planner/executor layer).

Mirrors the role of the reference's SessionManager + SparkSession surface
(crates/sail-session, crates/sail-spark-connect/src/session.rs).
"""

from __future__ import annotations


class SparkSession:
    """Will be replaced by the full session implementation."""

    def __init__(self):
        raise NotImplementedError("session layer lands with the planner")
