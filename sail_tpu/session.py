"""SparkSession-compatible entry point and DataFrame API.

Reference role: sail-session (SessionManager/session factory) plus the
PySpark-facing DataFrame surface that Spark Connect clients drive
(SURVEY.md §2.2). In-process v0: sql()/read/createDataFrame build spec
plans; actions resolve → optimize → execute on the local executor. The
protocol servers (Spark Connect gRPC, Flight SQL) layer on top of this
same session object.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import pyarrow as pa

from .catalog import CatalogManager, TableEntry
from .spec import data_type as dt
from .spec import expression as ex
from .spec import plan as sp
from .spec.literal import Literal as LV


class SparkSession:
    _active: Optional["SparkSession"] = None
    _lock = threading.Lock()

    class Builder:
        def __init__(self):
            self._conf: Dict[str, str] = {}

        def appName(self, name: str) -> "SparkSession.Builder":
            self._conf["spark.app.name"] = name
            return self

        def master(self, _: str) -> "SparkSession.Builder":
            return self

        def config(self, key: str, value=None) -> "SparkSession.Builder":
            self._conf[key] = str(value)
            return self

        def getOrCreate(self) -> "SparkSession":
            with SparkSession._lock:
                if SparkSession._active is None:
                    SparkSession._active = SparkSession(self._conf)
                return SparkSession._active

    builder = None  # replaced below by a property-like descriptor

    def __init__(self, conf: Optional[Dict[str, str]] = None,
                 catalog_manager: Optional[CatalogManager] = None):
        import uuid
        from collections import OrderedDict
        self.conf = SessionConf(conf or {})
        # ``catalog_manager`` is shared by sibling sessions created via
        # newSession(): tables/views/UDFs are engine-wide, the conf (and
        # with it the tenant tag) is strictly per session
        self.catalog_manager = catalog_manager or CatalogManager()
        from .exec.local import LocalExecutor
        self._executor_cls = LocalExecutor
        self.catalog = Catalog(self)
        self.udf = self.catalog_manager.udfs
        self.dataSource = _DataSourceRegistry(self.catalog_manager)
        self._session_id = uuid.uuid4().hex[:8]
        # SQL text + parse wall time per root plan, consumed when the
        # plan executes so the query profile can carry both
        self._parsed: "OrderedDict[int, tuple]" = OrderedDict()
        # pull-based ops endpoint (telemetry.http.enabled; one check +
        # at most one server per process)
        from . import obs_server
        obs_server.ensure_started()
        # resolve the persistent compiled-program cache config NOW so
        # jax's compilation-cache dir is set before the first eager
        # dispatch compiles anything (exec/pcache.py), and kick off the
        # background prewarm of the manifest's top compile-time savers
        from .exec import pcache
        pcache.enabled()
        pcache.start_prewarm()

    def newSession(self) -> "SparkSession":
        """A sibling session: same catalog (tables, temp views, UDFs),
        fresh independent :class:`SessionConf` — conf keys and the
        ``spark.sail.tenant`` tag set on one session can never bleed
        into another session's queries or profiles."""
        return SparkSession({}, catalog_manager=self.catalog_manager)

    @property
    def tenant(self) -> str:
        """The admission-control tenant this session's queries bill to
        (``spark.sail.tenant``; ``admission.tenant`` config default)."""
        t = self.conf.get("spark.sail.tenant")
        if t:
            return str(t)
        from .config import get as config_get
        return str(config_get("admission.tenant", "default")
                   or "default")

    # -- plan execution ----------------------------------------------------
    def _resolve(self, plan: sp.QueryPlan):
        from . import profiler
        from .plan.optimizer import optimize
        from .plan.resolver import Resolver
        with profiler.maybe_phase("resolve"):
            node = Resolver(self.catalog_manager).resolve(plan)
        with profiler.maybe_phase("optimize"):
            return optimize(
                node,
                validate=self.conf.get("spark.sail.analysis.validatePlans"))

    def _note_parsed(self, plan: sp.QueryPlan, text: str,
                     parse_ms: float, exempt: bool = False) -> None:
        import weakref
        try:
            ref = weakref.ref(plan)
        except TypeError:
            return
        self._parsed[id(plan)] = (ref, text, parse_ms, exempt)
        while len(self._parsed) > 128:
            self._parsed.popitem(last=False)

    def _parsed_info(self, plan: sp.QueryPlan):
        entry = self._parsed.get(id(plan))
        if entry is not None and entry[0]() is plan:
            return entry[1], entry[2], entry[3]
        return "", 0.0, False

    def _execute_query(self, plan: sp.QueryPlan) -> pa.Table:
        from . import profiler
        from .exec import admission
        from .utils.tz import reset_session_timezone, set_session_timezone
        text, parse_ms, exempt = self._parsed_info(plan)
        tenant = self.tenant
        with profiler.profile_query(text, session=self._session_id,
                                    conf=self.conf, tenant=tenant,
                                    enabled=not exempt) as prof:
            if parse_ms and "parse" not in prof.phases:
                prof.add_phase("parse", parse_ms)
            # multi-tenant admission: acquire a per-tenant query slot
            # (weighted-fair wake order, bounded queue) BEFORE any
            # resolution/execution work; overflow/timeout raises a
            # typed retryable ResourceExhausted instead of hanging.
            # Nested _execute_query calls ride the outer ticket.
            # Enforcement is PROCESS-wide (admission.enabled app
            # config) — a tenant-controlled session conf must not be
            # able to opt out of the isolation layer.
            deadline = self.conf.get("spark.sail.query.deadlineMs")
            try:
                deadline_ms = float(deadline) if deadline else None
            except (TypeError, ValueError):
                deadline_ms = None
            ticket = admission.session_gate().acquire(
                tenant, query_id=prof.query_id,
                deadline_ms=deadline_ms)
            token = set_session_timezone(
                self.conf.get("spark.sql.session.timeZone") or "UTC")
            try:
                node = self._resolve(plan)
                # the baseline/anomaly plane keys repeated executions by
                # structural plan fingerprint (analysis/anomaly.py)
                from .plan.stages import plan_fingerprint_hash
                profiler.note_plan_fingerprint(
                    plan_fingerprint_hash(node))
                # result cache: a fingerprint+version-vector hit serves
                # the stored table and skips execution entirely (local,
                # mesh and cluster paths alike); a miss measures the
                # build cost for the eviction policy and stores
                from .exec import result_cache as rc
                rc_probe = None
                if rc.result_cache_enabled(self.conf):
                    rc_probe = rc.probe(
                        node, self._result_cache_session_key())
                    if rc_probe is not None:
                        cached = rc.RESULT_CACHE.lookup(rc_probe)
                        if cached is not None:
                            prof.note_result_cache(
                                "hit", fragment=cached.fragment_id,
                                nbytes=cached.nbytes)
                            prof.rows_out = cached.table.num_rows
                            return cached.table
                        prof.note_result_cache(
                            "view" if self._reads_materialized_view(node)
                            else "miss")
                build_t0 = time.perf_counter()
                # the executors record their own execute/fetch phases
                # (LocalExecutor.execute); the mesh attempt is wrapped
                # here because it returns a finished table
                with profiler.maybe_phase("execute"):
                    table = self._try_mesh_execute(node)
                if table is None:
                    table = self._executor_cls(
                        dict(self.conf.items())).execute(node)
                if rc_probe is not None:
                    rc.RESULT_CACHE.store(
                        rc_probe, table,
                        (time.perf_counter() - build_t0) * 1000.0)
                prof.rows_out = table.num_rows
                return table
            finally:
                reset_session_timezone(token)
                ticket.release()

    def _result_cache_session_key(self) -> tuple:
        """Session knobs that change a query's OUTPUT for an identical
        plan — part of the result-cache key."""
        return (self.conf.get("spark.sql.session.timeZone") or "UTC",
                str(self.conf.get("spark.sql.ansi.enabled") or ""),
                str(self.conf.get("spark.sql.shuffle.partitions") or ""))

    @staticmethod
    def _reads_materialized_view(node) -> bool:
        from .exec.result_cache import VIEWS
        from .plan import nodes as pn
        if not VIEWS.names():
            return False
        return any(isinstance(n, pn.ScanExec)
                   and VIEWS.is_view(n.table_name)
                   for n in pn.walk_plan(node))

    def _table_mutated(self, entry, kind: str = "append",
                       delta: Optional[pa.Table] = None) -> None:
        """Post-write hook for every DML path: bumps the result-cache
        table version (which also clears file listings for the written
        root) and folds the change into dependent materialized views."""
        from .exec import result_cache as rc
        rc.table_mutated(self, entry, kind=kind, delta=delta)

    def _try_mesh_execute(self, node) -> Optional[pa.Table]:
        """SPMD path: when the plan splits into co-resident stages and the
        session mesh has >1 device, the whole job graph compiles into one
        shard_map program whose exchanges are XLA collectives (see
        parallel/mesh_exec.py). mode: off | auto (default) | force."""
        from .config import get as config_get
        self._last_mesh_executor = None
        mode = (self.conf.get("spark.sail.execution.mesh")
                or str(config_get("execution.mesh", "auto")))
        if mode == "off":
            return None
        import jax
        if len(jax.devices()) < 2 and mode != "force":
            return None
        # plan-level backend routing (exec/router.py): the SPMD mesh
        # program is only worth its fixed dispatch/compile cost above a
        # row-volume floor; `execution.backend.force` pins either way
        from .exec import router
        decision = router.decide_plan(
            node, nparts=len(jax.devices()),
            force=router.forced_backend(self.conf), mode=mode,
            slo_ctx=router.slo_context(self.conf))
        router.record_decisions([decision])
        if decision.backend != "mesh":
            return None
        try:
            from .parallel.mesh_exec import MeshExecutor
            ex = MeshExecutor(config=dict(self.conf.items()))
            result = ex.execute(node)
            if result is not None:
                self._last_mesh_executor = ex
            return result
        except Exception:
            if mode == "force":
                raise
            return None

    # -- entry points -------------------------------------------------------
    def sql(self, query: str) -> "DataFrame":
        import time as _t
        from . import profiler
        from .sql import parse_one
        t0 = _t.perf_counter()
        plan = parse_one(query)
        parse_ms = (_t.perf_counter() - t0) * 1000.0
        if isinstance(plan, sp.CommandPlan):
            # commands execute eagerly: the profile covers the whole
            # statement here; lazy queries profile at action time
            with profiler.profile_query(query, session=self._session_id,
                                        conf=self.conf) as prof:
                prof.add_phase("parse", parse_ms)
                table = self._execute_command(plan)
            # the command was profiled above; fetching its materialized
            # result must not record a second, anonymous profile
            result = sp.LocalRelation(table)
            self._note_parsed(result, query, 0.0, exempt=True)
            return DataFrame(result, self)
        self._note_parsed(plan, query, parse_ms)
        return DataFrame(plan, self)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    @property
    def readStream(self):
        from .streaming import DataStreamReader
        return DataStreamReader(self)

    def createDataFrame(self, data, schema=None) -> "DataFrame":
        if isinstance(data, pa.Table):
            table = data
        elif type(data).__name__ == "DataFrame" and hasattr(data, "to_records"):
            import pandas as pd
            assert isinstance(data, pd.DataFrame)
            table = pa.Table.from_pandas(data, preserve_index=False)
        else:
            columns = list(schema) if isinstance(schema, (list, tuple)) else None
            rows = [tuple(r.values()) if isinstance(r, dict) else tuple(r)
                    for r in data]
            if columns is None:
                columns = [f"_{i + 1}" for i in range(len(rows[0]))] if rows else []
            arrays = [pa.array([r[i] for r in rows]) for i in range(len(columns))]
            table = pa.Table.from_arrays(arrays, names=columns)
        if isinstance(schema, dt.StructType):
            from .columnar.arrow_interop import spec_type_to_arrow
            target = pa.schema([(f.name, spec_type_to_arrow(f.data_type))
                                for f in schema.fields])
            table = table.rename_columns([f.name for f in schema.fields]).cast(target)
        return DataFrame(sp.LocalRelation(table), self)

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              numPartitions: Optional[int] = None) -> "DataFrame":
        if end is None:
            start, end = 0, start
        return DataFrame(sp.Range(start, end, step, numPartitions), self)

    def table(self, name: str) -> "DataFrame":
        return DataFrame(sp.ReadNamedTable(tuple(name.split("."))), self)

    def stop(self):
        with SparkSession._lock:
            if SparkSession._active is self:
                SparkSession._active = None

    @property
    def version(self) -> str:
        return "4.0.0-sail-tpu"

    # -- commands ------------------------------------------------------------
    def _execute_command(self, cmd: sp.CommandPlan) -> pa.Table:
        cm = self.catalog_manager
        empty = pa.table({})
        if isinstance(cmd, sp.CreateView):
            cm.register_temp_view(cmd.name[-1], cmd.query, replace=cmd.replace)
            return empty
        if isinstance(cmd, sp.CreateTable):
            if cmd.query is not None:  # CTAS
                table = self._execute_query(cmd.query)
                if cmd.location:
                    from .io.formats import write_table
                    write_table(table, cmd.format or "parquet", cmd.location,
                                mode="overwrite" if cmd.replace else "error",
                                partition_by=cmd.partition_by)
                    entry = self._file_table_entry(cmd)
                else:
                    entry = TableEntry(cmd.name, _schema_of(table), table,
                                       (), "memory")
                cm.register_table(entry, cmd.replace, cmd.if_not_exists)
                return empty
            if cmd.location:
                entry = self._file_table_entry(cmd)
            else:
                schema = cmd.schema or dt.StructType(())
                empty_tbl = _empty_table(schema)
                entry = TableEntry(cmd.name, schema, empty_tbl, (), "memory")
            cm.register_table(entry, cmd.replace, cmd.if_not_exists)
            return empty
        if isinstance(cmd, sp.DropTable):
            cm.drop_table(cmd.name, cmd.if_exists, cmd.is_view)
            return empty
        if isinstance(cmd, sp.CreateDatabase):
            cm.create_database(cmd.name[-1], cmd.if_not_exists, cmd.comment,
                               cmd.location)
            return empty
        if isinstance(cmd, sp.DropDatabase):
            cm.drop_database(cmd.name[-1], cmd.if_exists, cmd.cascade)
            return empty
        if isinstance(cmd, sp.UseDatabase):
            if cmd.name[-1].lower() not in cm.databases:
                raise ValueError(f"database {cmd.name[-1]!r} not found")
            cm.current_database = cmd.name[-1].lower()
            return empty
        if isinstance(cmd, sp.InsertInto):
            return self._insert_into(cmd)
        if isinstance(cmd, sp.WriteDataSource):
            if cmd.table and not cmd.path:
                if cmd.format == "delta":
                    # managed Delta table under the warehouse directory —
                    # a memory TableEntry would silently lose durability
                    from .io.formats import write_table
                    wh = self.conf.get("spark.sql.warehouse.dir") or \
                        os.path.join(os.getcwd(), "spark-warehouse")
                    location = os.path.join(wh, *cmd.table)
                    table = self._execute_query(cmd.query)
                    write_table(table, "delta", location, cmd.mode,
                                dict(cmd.options), cmd.partition_by)
                    entry = TableEntry(cmd.table, _schema_of(table), None,
                                       (location,), "delta", None,
                                       cmd.options, cmd.partition_by)
                    cm.register_table(entry, replace=True,
                                      if_not_exists=False)
                    return empty
                existing = cm.lookup_table(cmd.table)
                if existing is not None and cmd.mode == "append":
                    return self._insert_into(sp.InsertInto(cmd.table,
                                                           cmd.query))
                if existing is not None and cmd.mode == "ignore":
                    return empty
                table = self._execute_query(cmd.query)
                entry = TableEntry(cmd.table, _schema_of(table), table,
                                   (), "memory")
                cm.register_table(entry, replace=(cmd.mode == "overwrite"),
                                  if_not_exists=False)
                return empty
            if cmd.path:
                from .io.formats import write_table
                table = self._execute_query(cmd.query)
                write_table(table, cmd.format, cmd.path, cmd.mode,
                            dict(cmd.options), cmd.partition_by)
                return empty
            raise ValueError("write requires a path or table name")
        if isinstance(cmd, sp.ShowTables):
            entries = cm.list_tables(cmd.database[-1] if cmd.database else None)
            names = [e.name[-1] for e in entries]
            return pa.table({
                "namespace": pa.array([cm.current_database] * len(names)),
                "tableName": pa.array(names),
                "isTemporary": pa.array([e.view_plan is not None for e in entries]),
            })
        if isinstance(cmd, sp.ShowDatabases):
            return pa.table({"namespace": pa.array(cm.list_databases())})
        if isinstance(cmd, sp.ShowColumns):
            entry = cm.lookup_table(cmd.table)
            if entry is None:
                raise ValueError(f"table not found: {'.'.join(cmd.table)}")
            if entry.view_plan is not None:
                node = self._resolve(entry.view_plan)
                cols = [f.name for f in node.schema]
            else:
                cols = [f.name for f in entry.schema.fields]
            return pa.table({"col_name": pa.array(cols)})
        if isinstance(cmd, sp.DescribeTable):
            entry = cm.lookup_table(cmd.table)
            if entry is None:
                raise ValueError(f"table not found: {'.'.join(cmd.table)}")
            if entry.view_plan is not None:
                node = self._resolve(entry.view_plan)
                pairs = [(f.name, f.dtype.simple_string()) for f in node.schema]
            else:
                pairs = [(f.name, f.data_type.simple_string())
                         for f in entry.schema.fields]
            return pa.table({
                "col_name": pa.array([p[0] for p in pairs]),
                "data_type": pa.array([p[1] for p in pairs]),
                "comment": pa.array([None] * len(pairs), type=pa.string()),
            })
        if isinstance(cmd, sp.ShowFunctions):
            from .functions.registry import AGGREGATE_FUNCTIONS
            from .plan.compiler import _NUMERIC_BUILDERS, _STRING_TRANSFORMS
            names = sorted(set(_NUMERIC_BUILDERS) | set(_STRING_TRANSFORMS)
                           | AGGREGATE_FUNCTIONS)
            return pa.table({"function": pa.array(names)})
        if isinstance(cmd, sp.SetVariable):
            if cmd.name and cmd.value is not None:
                self.conf.set(cmd.name, cmd.value)
                if cmd.name in ("spark.sail.slo.targetMs",
                                "spark.sail.slo.objective"):
                    # register the session tenant's SLO objective with
                    # the burn-rate monitor: explicit session mirrors
                    # win over slo.tenants.* config and the global
                    # slo.{target_ms,objective} defaults
                    try:
                        from .analysis.anomaly import SLO_MONITOR
                        v = float(cmd.value)
                        if cmd.name.endswith("targetMs"):
                            SLO_MONITOR.set_objective(
                                self.tenant, target_ms=v)
                        else:
                            SLO_MONITOR.set_objective(
                                self.tenant, objective=v)
                    except (TypeError, ValueError):
                        pass
                return pa.table({"key": pa.array([cmd.name]),
                                 "value": pa.array([cmd.value])})
            if cmd.name:
                v = self.conf.get(cmd.name)
                return pa.table({"key": pa.array([cmd.name]),
                                 "value": pa.array([v])})
            items = sorted(self.conf.items())
            return pa.table({"key": pa.array([k for k, _ in items]),
                             "value": pa.array([v for _, v in items])})
        if isinstance(cmd, sp.ResetVariable):
            self.conf.reset(cmd.name)
            return empty
        if isinstance(cmd, sp.Delete):
            return self._delta_delete(cmd)
        if isinstance(cmd, sp.Update):
            return self._delta_update(cmd)
        if isinstance(cmd, sp.MergeInto):
            return self._delta_merge(cmd)
        if isinstance(cmd, sp.Explain):
            from .plan.nodes import explain
            node = self._resolve(cmd.query)
            stage_of = None
            n_stages = 0
            from .plan.stages import fusion_enabled
            fusion_on = fusion_enabled(self.conf.get(
                "spark.sail.execution.fusion.enabled"))
            backends = []
            if fusion_on:
                from .exec import router
                from .plan.stages import split_stages
                split = split_stages(node)
                stage_of = split.stage_of
                n_stages = len(split.stages)
                # the routing the executor would run under (same
                # deterministic decision function, no execution)
                backends = [d.to_dict() for d in router.decide_split(
                    split, force=router.forced_backend(self.conf),
                    slo_ctx=router.slo_context(self.conf))]
            from .exec import result_cache as rc
            rc_probe = None
            if rc.result_cache_enabled(self.conf):
                rc_probe = rc.probe(node,
                                    self._result_cache_session_key())
            if cmd.mode == "analyze":
                import time as _t
                from . import profiler
                from . import telemetry as tel
                prof = profiler.current_profile()
                # the analyzed plan is the one the baseline/anomaly
                # plane must key this profile under
                from .plan.stages import plan_fingerprint_hash
                profiler.note_plan_fingerprint(
                    plan_fingerprint_hash(node))
                t0 = _t.perf_counter()
                cached = rc.RESULT_CACHE.lookup(rc_probe) \
                    if rc_probe is not None else None
                if cached is not None:
                    # same contract as _execute_query: a hit serves the
                    # stored table — no operators ran, and the profile
                    # says so
                    result = cached.table
                    collector = []
                    if prof is not None:
                        prof.note_result_cache(
                            "hit", fragment=cached.fragment_id,
                            nbytes=cached.nbytes)
                else:
                    if prof is not None and rc_probe is not None:
                        prof.note_result_cache(
                            "view"
                            if self._reads_materialized_view(node)
                            else "miss")
                    with tel.collect_metrics() as collector:
                        # LocalExecutor.execute records execute/fetch
                        # phases
                        result = self._executor_cls(
                            dict(self.conf.items())).execute(node)
                    if rc_probe is not None:
                        rc.RESULT_CACHE.store(
                            rc_probe, result,
                            (_t.perf_counter() - t0) * 1000.0)
                total_ms = (_t.perf_counter() - t0) * 1000
                ops = [m.to_dict() for m in collector]
                if prof is not None:
                    prof.operators = ops
                    prof.rows_out = result.num_rows
                    try:
                        # classify now so the rendered payload carries
                        # the verdict the finalize pass will land (the
                        # baseline only observes at finalize, so both
                        # classify against the same state)
                        from .analysis import anomaly as _anomaly
                        _anomaly.preview(prof)
                    except Exception:  # noqa: BLE001
                        pass
                if cmd.format == "json":
                    import json as _json
                    payload = prof.to_dict() if prof is not None else \
                        {"total_ms": round(total_ms, 3), "operators": ops}
                    # the analyzed execution IS complete — the profile
                    # just hasn't closed yet (rendering happens inside it)
                    payload["status"] = "succeeded"
                    payload["plan"] = explain(node, stage_of=stage_of)
                    if stage_of is not None:
                        payload["fused_stages"] = n_stages
                    if backends:
                        payload["backends"] = backends
                    text = _json.dumps(payload, indent=2, default=str)
                else:
                    header = prof.render() if prof is not None else \
                        f"total: {total_ms:.1f}ms"
                    text = "\n".join(
                        [header] + [m.render() for m in collector])
                return pa.table({"plan": pa.array([text])})
            cache_info = None
            if rc_probe is not None:
                # non-counting peek: what WOULD happen if this ran now
                entry = rc.RESULT_CACHE.peek(rc_probe)
                if entry is not None:
                    cache_info = {"status": "hit",
                                  "fragments": [entry.fragment_id],
                                  "bytes_served": entry.nbytes}
                else:
                    cache_info = {
                        "status": "view"
                        if self._reads_materialized_view(node)
                        else "miss",
                        "fragments": [], "bytes_served": 0}
            if cmd.format == "json":
                import json as _json
                payload = {"plan": explain(node, stage_of=stage_of)}
                if stage_of is not None:
                    payload["fused_stages"] = n_stages
                if backends:
                    payload["backends"] = backends
                if cache_info is not None:
                    payload["result_cache"] = cache_info
                return pa.table({"plan": pa.array(
                    [_json.dumps(payload, indent=2)])})
            text = explain(node, stage_of=stage_of)
            if stage_of is not None:
                text += f"\nfused: {n_stages} stages"
            if backends:
                text += "\nbackend: " + " ".join(
                    f"s{b['stage']}={b['backend']}({b['reason']})"
                    for b in backends)
            if cache_info is not None:
                line = f"\ncache: {cache_info['status']}"
                if cache_info["fragments"]:
                    line += " fragments=" + ",".join(
                        cache_info["fragments"])
                if cache_info["bytes_served"]:
                    line += f" bytes={cache_info['bytes_served']}"
                text += line
            return pa.table({"plan": pa.array([text])})
        if isinstance(cmd, sp.CacheMaterialized):
            from .exec.result_cache import VIEWS
            VIEWS.create(self, cmd.name[-1], cmd.query)
            return empty
        if isinstance(cmd, sp.UncacheMaterialized):
            from .exec.result_cache import VIEWS
            VIEWS.drop(cm, cmd.name[-1], cmd.if_exists)
            return empty
        if isinstance(cmd, sp.CacheTable):
            if cmd.query is not None:
                cm.register_temp_view(cmd.name[-1], cmd.query)
            return empty
        if isinstance(cmd, sp.UncacheTable):
            return empty
        if isinstance(cmd, sp.ShowCatalogs):
            names = cm.list_catalogs() if hasattr(cm, "list_catalogs") \
                else sorted(cm.providers)
            if cmd.pattern:
                import fnmatch
                names = [n for n in names
                         if fnmatch.fnmatch(n, cmd.pattern)]
            return pa.table({"catalog": pa.array(names)})
        if isinstance(cmd, sp.TruncateTable):
            return self._truncate_table(cmd)
        if isinstance(cmd, sp.RefreshTable):
            from .io.cache import LISTING_CACHE, METADATA_CACHE
            LISTING_CACHE.clear()
            METADATA_CACHE.clear()
            entry = cm.lookup_table(cmd.name)
            if entry is not None:
                # external change declared: version the table so cached
                # results miss and dependent views recompute
                self._table_mutated(entry, "refresh")
            return empty
        if isinstance(cmd, sp.ClearCache):
            from .exec.local import clear_caches
            from .io.cache import LISTING_CACHE, METADATA_CACHE
            LISTING_CACHE.clear()
            METADATA_CACHE.clear()
            clear_caches()
            return empty
        if isinstance(cmd, sp.ShowCreateTable):
            entry = cm.lookup_table(cmd.name)
            if entry is None:
                raise ValueError(f"table not found: {'.'.join(cmd.name)}")
            cols = ",\n".join(
                f"  {f.name} {f.data_type.simple_string().upper()}"
                for f in entry.schema.fields) if entry.schema else ""
            ddl = f"CREATE TABLE {'.'.join(cmd.name)} (\n{cols})"
            if entry.format != "memory":
                ddl += f"\nUSING {entry.format}"
            if entry.paths:
                ddl += f"\nLOCATION '{entry.paths[0]}'"
            if entry.partition_by:
                ddl += f"\nPARTITIONED BY ({', '.join(entry.partition_by)})"
            return pa.table({"createtab_stmt": pa.array([ddl])})
        if isinstance(cmd, sp.AnalyzeTable):
            entry = cm.lookup_table(cmd.name)
            if entry is None:
                raise ValueError(f"table not found: {'.'.join(cmd.name)}")
            if cmd.columns:
                # parsed but column-level stats are not collected yet —
                # succeeding silently would let users believe ndv/min/max
                # stats exist when only numRows does
                raise NotImplementedError(
                    "ANALYZE TABLE ... FOR COLUMNS is not implemented; "
                    "use ANALYZE TABLE ... COMPUTE STATISTICS [NOSCAN]")
            if not cmd.noscan:
                n = self._execute_query(
                    sp.Aggregate(sp.ReadNamedTable(cmd.name), (),
                                 (ex.Alias(ex.Function(
                                     "count", (ex.Star(),)),
                                     ("cnt",)),))).column(0)[0].as_py()
                entry.options = tuple(
                    [(k, v) for k, v in entry.options if k != "numRows"]
                    + [("numRows", str(n))])
            return empty
        if isinstance(cmd, sp.AlterTable):
            return self._alter_table(cmd)
        if isinstance(cmd, sp.DescribeDatabase):
            db = cmd.name[-1]
            prov = cm.provider(cmd.name[-2]) if len(cmd.name) >= 2 \
                else cm.provider()
            info = prov.database_info(db) \
                if hasattr(prov, "database_info") else None
            if info is None:
                raise ValueError(f"database not found: {db}")
            rows = [("Namespace Name", db),
                    ("Comment", info.get("comment") or ""),
                    ("Location", info.get("location") or "")]
            return pa.table({
                "info_name": pa.array([r[0] for r in rows]),
                "info_value": pa.array([r[1] for r in rows])})
        if isinstance(cmd, sp.ShowTblProperties):
            entry = cm.lookup_table(cmd.name)
            if entry is None:
                raise ValueError(f"table not found: {'.'.join(cmd.name)}")
            props = dict(entry.options)
            if cmd.key is not None:
                props = {cmd.key: props.get(cmd.key)}
            return pa.table({
                "key": pa.array(sorted(props)),
                "value": pa.array([props[k] for k in sorted(props)])})
        if isinstance(cmd, sp.ShowPartitions):
            return self._show_partitions(cmd)
        if isinstance(cmd, sp.CommentOn):
            if cmd.kind == "database":
                prov = cm.provider(cmd.name[-2]) if len(cmd.name) >= 2 \
                    else cm.provider()
                # only the memory provider exposes a mutable database
                # dict; remote catalogs rebuild info per call, so a
                # write there would be silently lost
                dbs = getattr(prov, "databases", None)
                if not isinstance(dbs, dict) or \
                        cmd.name[-1].lower() not in dbs:
                    raise NotImplementedError(
                        "COMMENT ON DATABASE is supported for the "
                        "in-memory catalog only")
                dbs[cmd.name[-1].lower()]["comment"] = cmd.comment
            else:
                entry = cm.lookup_table(cmd.name)
                if entry is None:
                    raise ValueError(
                        f"table not found: {'.'.join(cmd.name)}")
                entry.comment = cmd.comment
            return empty
        raise NotImplementedError(f"command {type(cmd).__name__} not supported yet")

    def _truncate_table(self, cmd: sp.TruncateTable) -> pa.Table:
        cm = self.catalog_manager
        entry = cm.lookup_table(cmd.name)
        if entry is None:
            raise ValueError(f"table not found: {'.'.join(cmd.name)}")
        if entry.view_plan is not None:
            raise ValueError(
                f"cannot TRUNCATE a view: {'.'.join(cmd.name)}")
        if entry.format == "memory":
            if entry.data is not None:
                entry.data = entry.data.slice(0, 0)
            _drop_row_stats(entry)
            self._table_mutated(entry, "truncate")
            return pa.table({})
        if entry.format == "delta" and entry.paths:
            from .columnar.arrow_interop import spec_type_to_arrow
            from .lakehouse.delta import DeltaTable
            t = DeltaTable(entry.paths[0])
            # overwrite with an EMPTY table built from the schema — no
            # need to materialize the existing data
            schema = t.snapshot().schema
            t.overwrite(pa.table({
                f.name: pa.array([], type=spec_type_to_arrow(f.data_type))
                for f in schema.fields}))
            _drop_row_stats(entry)
            self._table_mutated(entry, "truncate")
            return pa.table({})
        raise NotImplementedError(
            f"TRUNCATE on format {entry.format!r} not supported")

    def _alter_table(self, cmd: sp.AlterTable) -> pa.Table:
        import pyarrow as pa_mod

        cm = self.catalog_manager
        entry = cm.lookup_table(cmd.name)
        if entry is None:
            raise ValueError(f"table not found: {'.'.join(cmd.name)}")
        if entry.view_plan is not None:
            raise ValueError(
                f"cannot ALTER a view: {'.'.join(cmd.name)}")
        empty = pa_mod.table({})
        if cmd.action == "rename":
            # an unqualified new name stays in the SOURCE database and
            # the SOURCE catalog — a fully-qualified rename of a table
            # in a non-current catalog must not migrate the entry into
            # cm.current_catalog; cross-catalog renames are rejected
            # outright (matching Spark)
            src_cat = cmd.name[-3].lower() if len(cmd.name) >= 3 else (
                str(entry.name[0]).lower() if len(entry.name) >= 3
                else cm.current_catalog)
            if len(cmd.new_name) >= 3 and \
                    cmd.new_name[-3].lower() != src_cat:
                raise ValueError(
                    f"cannot rename across catalogs: "
                    f"{'.'.join(cmd.name)} -> {'.'.join(cmd.new_name)}")
            src_db = cmd.name[-2] if len(cmd.name) >= 2 \
                else cm.current_database
            new_db = cmd.new_name[-2] if len(cmd.new_name) >= 2 \
                else src_db
            cm.drop_table(cmd.name)
            entry.name = (src_cat, new_db, cmd.new_name[-1])
            cm.register_table(entry)
            return empty
        if cmd.action in ("set_properties", "unset_properties"):
            props = dict(entry.options)
            for k, v in cmd.properties:
                if cmd.action == "set_properties":
                    props[k] = v
                else:
                    props.pop(k, None)
            entry.options = tuple(sorted(props.items()))
            return empty
        if entry.format != "memory" or entry.schema is None:
            raise NotImplementedError(
                f"ALTER TABLE {cmd.action} on format {entry.format!r} "
                "not supported")
        if cmd.action == "add_columns":
            from .columnar.arrow_interop import spec_type_to_arrow
            fields = list(entry.schema.fields)
            for cname, ctype in cmd.columns:
                fields.append(dt.StructField(cname, ctype, True))
                if entry.data is not None:
                    entry.data = entry.data.append_column(
                        cname, pa_mod.nulls(entry.data.num_rows,
                                            type=spec_type_to_arrow(ctype)))
            entry.schema = dt.StructType(tuple(fields))
            return empty
        if cmd.action == "drop_columns":
            drop = {c.lower() for c in cmd.column_names}
            if any(p.lower() in drop for p in entry.partition_by):
                raise ValueError("cannot drop a partition column")
            entry.schema = dt.StructType(tuple(
                f for f in entry.schema.fields
                if f.name.lower() not in drop))
            if entry.data is not None:
                keep = [c for c in entry.data.column_names
                        if c.lower() not in drop]
                entry.data = entry.data.select(keep)
            return empty
        if cmd.action == "rename_column":
            old, new = cmd.column_names
            entry.schema = dt.StructType(tuple(
                dt.StructField(new if f.name.lower() == old.lower()
                               else f.name, f.data_type, f.nullable)
                for f in entry.schema.fields))
            if entry.data is not None:
                entry.data = entry.data.rename_columns(
                    [new if c.lower() == old.lower() else c
                     for c in entry.data.column_names])
            entry.partition_by = tuple(
                new if p.lower() == old.lower() else p
                for p in entry.partition_by)
            return empty
        raise NotImplementedError(f"ALTER TABLE action {cmd.action!r}")

    def _show_partitions(self, cmd: sp.ShowPartitions) -> pa.Table:
        cm = self.catalog_manager
        entry = cm.lookup_table(cmd.name)
        if entry is None:
            raise ValueError(f"table not found: {'.'.join(cmd.name)}")
        if not entry.partition_by:
            raise ValueError(
                f"table {'.'.join(cmd.name)} is not partitioned")
        pcols = [c.lower() for c in entry.partition_by]
        parts = set()
        if entry.format == "delta" and entry.paths:
            from .lakehouse.delta import DeltaTable
            snap = DeltaTable(entry.paths[0]).snapshot()
            for add in snap.files.values():
                pv = dict(add.partition_values)
                parts.add("/".join(
                    f"{c}={snap.partition_raw(pv, c)}"
                    for c in entry.partition_by))
        elif entry.paths:
            # hive-style directory layout: k=v path segments
            from .io.formats import expand_paths
            for f in expand_paths(entry.paths):
                segs = [s for s in f.split(os.sep)
                        if "=" in s and s.split("=", 1)[0].lower()
                        in pcols]
                if segs:
                    parts.add("/".join(segs))
        else:
            table = self._execute_query(sp.ReadNamedTable(cmd.name))
            combos = table.select(list(entry.partition_by)) \
                .group_by(list(entry.partition_by)).aggregate([]) \
                .to_pylist()
            parts = {"/".join(f"{k}={v}" for k, v in c.items())
                     for c in combos}
        return pa.table({"partition": pa.array(sorted(parts))})

    @staticmethod
    def _generated_columns(entry) -> set:
        """Delta generated columns for an INSERT target (these must stay
        absent from the insert batch so the writer computes them)."""
        if entry.format != "delta" or not entry.paths:
            return set()
        try:
            from .lakehouse.delta import DeltaTable
            return set(DeltaTable(entry.paths[0]).snapshot()
                       .generation_expressions)
        except Exception:  # noqa: BLE001 — best-effort metadata probe
            return set()

    def _delta_entry(self, table_name):
        entry = self.catalog_manager.lookup_table(table_name)
        if entry is None:
            raise ValueError(f"table not found: {'.'.join(table_name)}")
        if entry.format != "delta" or not entry.paths:
            raise NotImplementedError(
                "DELETE/UPDATE/MERGE are supported on Delta tables "
                f"(table {'.'.join(table_name)} has format "
                f"{entry.format!r})")
        from .lakehouse.delta import DeltaTable
        return entry, DeltaTable(entry.paths[0])

    def _eval_predicate(self, table: pa.Table, cond: sp.Expr) -> pa.Table:
        """Evaluate a predicate over an arrow table → bool column."""
        import sail_tpu.spec.expression as ex
        plan = sp.Project(sp.LocalRelation(table),
                          (ex.Alias(cond, ("__pred__",)),))
        return self._execute_query(plan)

    def _delta_delete(self, cmd: sp.Delete) -> pa.Table:
        entry = self.catalog_manager.lookup_table(cmd.table)
        if entry is not None and entry.format == "iceberg" and entry.paths:
            return self._iceberg_delete(entry, cmd)
        from .lakehouse.delta.dml import DeltaDml
        out = DeltaDml(self, cmd.table).delete(cmd.condition)
        if entry is not None:
            _drop_row_stats(entry)
            self._table_mutated(entry, "mutate")
        return out

    def _iceberg_delete(self, entry, cmd: sp.Delete) -> pa.Table:
        """DELETE on an Iceberg table → merge-on-read position-delete
        files (reference: sail-iceberg row-level operations)."""
        import numpy as np

        from .lakehouse.iceberg import IcebergTable

        t = IcebergTable(entry.paths[0])

        def mask_fn(tab):
            if cmd.condition is None:
                return np.ones(tab.num_rows, dtype=bool)
            pred = self._eval_predicate(tab, cmd.condition)
            vals = pred.column(0).to_pylist()
            return np.asarray([bool(v) for v in vals], dtype=bool)

        t.delete_where(mask_fn)
        _drop_row_stats(entry)
        self._table_mutated(entry, "mutate")
        return pa.table({})

    def _delta_update(self, cmd: sp.Update) -> pa.Table:
        from .lakehouse.delta.dml import DeltaDml
        out = DeltaDml(self, cmd.table).update(cmd)
        entry = self.catalog_manager.lookup_table(cmd.table)
        if entry is not None:
            _drop_row_stats(entry)
            self._table_mutated(entry, "mutate")
        return out

    def _delta_merge(self, cmd: sp.MergeInto) -> pa.Table:
        """MERGE INTO on a Delta table — planned and executed by the
        engine DML pipeline with targeted file rewrites
        (lakehouse/delta/dml.py; reference:
        crates/sail-delta-lake/src/physical_plan/planner/op_merge.rs)."""
        from .lakehouse.delta.dml import DeltaDml
        out = DeltaDml(self, cmd.target).merge(cmd)
        entry = self.catalog_manager.lookup_table(cmd.target)
        if entry is not None:
            _drop_row_stats(entry)
            self._table_mutated(entry, "mutate")
        return out

    def _file_table_entry(self, cmd: sp.CreateTable) -> TableEntry:
        from .io.formats import infer_schema
        fmt = cmd.format or "parquet"
        schema = cmd.schema or infer_schema(fmt, (cmd.location,), dict(cmd.options))
        return TableEntry(cmd.name, schema, None, (cmd.location,), fmt,
                          None, cmd.options, cmd.partition_by)

    def _insert_into(self, cmd: sp.InsertInto) -> pa.Table:
        cm = self.catalog_manager
        entry = cm.lookup_table(cmd.table)
        if entry is None:
            raise ValueError(f"table not found: {'.'.join(cmd.table)}")
        new_data = self._execute_query(cmd.query)
        if cmd.columns and new_data.num_columns != len(cmd.columns):
            raise ValueError(
                f"INSERT column list has {len(cmd.columns)} columns but "
                f"query produced {new_data.num_columns}")
        if entry.format == "memory":
            from .columnar.arrow_interop import spec_type_to_arrow
            existing = entry.data
            if existing is not None:
                target = existing.column_names
                ttype = {n: existing.schema.field(n).type for n in target}
            elif entry.schema is not None:
                target = [f.name for f in entry.schema.fields]
                ttype = {f.name: spec_type_to_arrow(f.data_type)
                         for f in entry.schema.fields}
            else:
                target = None
                ttype = {}
            if cmd.columns:
                # explicit column list: map by NAME onto the target
                # shape, null-filling unlisted columns
                new_data = new_data.rename_columns(list(cmd.columns))
                listed = {c.lower(): c for c in new_data.column_names}
                cols = {}
                for name in (target or list(cmd.columns)):
                    src = listed.get(name.lower())
                    if src is not None:
                        cols[name] = new_data.column(src)
                    else:
                        cols[name] = pa.nulls(new_data.num_rows,
                                              type=ttype[name])
                new_data = pa.table(cols)
            elif target is not None:
                # positional semantics against the declared shape
                if new_data.num_columns != len(target):
                    raise ValueError(
                        f"INSERT query produced {new_data.num_columns} "
                        f"columns but table has {len(target)}")
                new_data = new_data.rename_columns(target)
            if cmd.overwrite or existing is None or existing.num_rows == 0:
                merged = new_data
            else:
                merged = pa.concat_tables([existing, new_data],
                                          promote_options="permissive")
            entry.data = merged
            entry.schema = _schema_of(merged)
        else:
            from .io.formats import write_table
            # positional insert semantics: a VALUES/SELECT output maps to
            # the target columns by position (or by the explicit INSERT
            # column list), not by its own generated names (col1, …)
            if cmd.columns:
                new_data = new_data.rename_columns(list(cmd.columns))
                if entry.schema is not None:
                    # null-fill unlisted target columns so every data
                    # file carries the full schema (generated Delta
                    # columns stay absent — the writer computes them)
                    from .columnar.arrow_interop import spec_type_to_arrow
                    gen = self._generated_columns(entry)
                    listed = {c.lower(): c for c in new_data.column_names}
                    cols = {}
                    for f in entry.schema.fields:
                        src = listed.get(f.name.lower())
                        if src is not None:
                            cols[f.name] = new_data.column(src)
                        elif f.name not in gen:
                            cols[f.name] = pa.nulls(
                                new_data.num_rows,
                                type=spec_type_to_arrow(f.data_type))
                    new_data = pa.table(cols)
            elif entry.schema is not None and \
                    new_data.num_columns == len(entry.schema.fields):
                new_data = new_data.rename_columns(
                    [f.name for f in entry.schema.fields])
            write_table(new_data, entry.format, entry.paths[0],
                        mode="overwrite" if cmd.overwrite else "append",
                        partition_by=entry.partition_by)
        _drop_row_stats(entry)
        self._table_mutated(entry,
                            "overwrite" if cmd.overwrite else "append",
                            delta=None if cmd.overwrite else new_data)
        return pa.table({})


def _drop_row_stats(entry) -> None:
    """ANALYZE-time row counts are stale after any data mutation
    (INSERT, TRUNCATE, overwrite); drop them so the join reorderer falls
    back to exact footer counts instead of costing the table at its
    pre-mutation size."""
    entry.options = tuple(
        (k, v) for k, v in entry.options if k != "numRows")


class _BuilderDescriptor:
    def __get__(self, obj, objtype=None):
        return SparkSession.Builder()


SparkSession.builder = _BuilderDescriptor()


class SessionConf:
    _DEFAULTS = {
        "spark.sql.session.timeZone": "UTC",
        "spark.sql.shuffle.partitions": "8",
        "sail.execution.batch_capacity": "16777216",
    }

    def __init__(self, conf: Dict[str, str]):
        # layering (low → high): class defaults, YAML session.timezone,
        # YAML/env spark.* keys, then the per-session conf dict
        from .config import app_config
        app = app_config()
        base = dict(self._DEFAULTS)
        tz = app.get("session.timezone")
        if tz:
            base["spark.sql.session.timeZone"] = str(tz)
        for key, value in app.items():
            if key.startswith("spark."):
                base[key] = str(value)
        chunk = app.get("execution.scan_chunk_rows")
        if chunk:
            base["spark.sail.scan.chunkRows"] = str(chunk)
        pf_depth = app.get("execution.scan_prefetch_depth")
        if pf_depth is not None:  # 0 is meaningful: disables pipelining
            base["spark.sail.scan.prefetchDepth"] = str(pf_depth)
        slow_ms = app.get("telemetry.slow_query_ms")
        if slow_ms is not None:  # 0 is meaningful: disables the slow log
            base["spark.sail.telemetry.slowQueryMs"] = str(slow_ms)
        # cluster fault-tolerance knobs (YAML cluster.{rpc_retry,
        # speculation, quarantine}.* → spark.sail.cluster.* camelCase)
        for yaml_key, conf_key in (
                ("cluster.rpc_retry.max_attempts",
                 "spark.sail.cluster.rpcRetry.maxAttempts"),
                ("cluster.rpc_retry.base_ms",
                 "spark.sail.cluster.rpcRetry.baseMs"),
                ("cluster.rpc_retry.cap_ms",
                 "spark.sail.cluster.rpcRetry.capMs"),
                ("cluster.speculation.enabled",
                 "spark.sail.cluster.speculation.enabled"),
                ("cluster.speculation.stage_fraction",
                 "spark.sail.cluster.speculation.stageFraction"),
                ("cluster.speculation.latency_multiplier",
                 "spark.sail.cluster.speculation.latencyMultiplier"),
                ("cluster.speculation.min_runtime_ms",
                 "spark.sail.cluster.speculation.minRuntimeMs"),
                ("cluster.quarantine.enabled",
                 "spark.sail.cluster.quarantine.enabled"),
                ("cluster.quarantine.max_failures",
                 "spark.sail.cluster.quarantine.maxFailures"),
                ("cluster.quarantine.window_secs",
                 "spark.sail.cluster.quarantine.windowSecs"),
                ("cluster.quarantine.duration_secs",
                 "spark.sail.cluster.quarantine.durationSecs"),
                ("shuffle.compression",
                 "spark.sail.shuffle.compression"),
                ("shuffle.fetch_concurrency",
                 "spark.sail.shuffle.fetchConcurrency"),
                ("cluster.memory_budget_mb",
                 "spark.sail.cluster.memoryBudgetMb"),
                ("adaptive.enabled", "spark.sail.adaptive.enabled"),
                ("adaptive.coalesce.target_mb",
                 "spark.sail.adaptive.coalesce.targetMb"),
                ("adaptive.skew.factor",
                 "spark.sail.adaptive.skew.factor"),
                ("adaptive.broadcast.threshold_mb",
                 "spark.sail.adaptive.broadcast.thresholdMb"),
                ("telemetry.events_enabled",
                 "spark.sail.telemetry.eventsEnabled"),
                ("telemetry.event_log.enabled",
                 "spark.sail.telemetry.eventLog.enabled"),
                ("telemetry.event_log.dir",
                 "spark.sail.telemetry.eventLog.dir"),
                ("telemetry.event_log.max_mb",
                 "spark.sail.telemetry.eventLog.maxMb"),
                ("faults.spec", "spark.sail.faults.spec"),
                ("faults.seed", "spark.sail.faults.seed"),
                ("analysis.validate_plans",
                 "spark.sail.analysis.validatePlans"),
                # multi-tenant admission control (exec/admission.py):
                # only the keys _execute_query actually reads per
                # session mirror here — enforcement (enabled) and all
                # caps/weights/quotas are process-wide (admission.*
                # app config / SAIL_ADMISSION env), never per-session,
                # so a tenant cannot opt itself out
                ("admission.tenant", "spark.sail.tenant"),
                ("admission.default_deadline_ms",
                 "spark.sail.query.deadlineMs")):
            value = app.get(yaml_key)
            if value is not None:
                base[conf_key] = str(value)
        self._DEFAULTS = base
        self._conf = dict(conf)

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        return self._conf.get(key, self._DEFAULTS.get(key, default))

    def set(self, key: str, value: str):
        self._conf[key] = str(value)

    def reset(self, key: Optional[str] = None):
        if key is None:
            self._conf.clear()
        else:
            self._conf.pop(key, None)

    def items(self):
        merged = dict(self._DEFAULTS)
        merged.update(self._conf)
        return merged.items()


class _DataSourceRegistry:
    """spark.dataSource — user-defined Python data sources (reference:
    sail-data-source formats/python; API mirrors pyspark.sql.datasource)."""

    def __init__(self, catalog_manager):
        self._cm = catalog_manager
        if not hasattr(catalog_manager, "data_sources"):
            catalog_manager.data_sources = {}

    def register(self, cls, name: str = None) -> None:
        self._cm.data_sources[(name or cls.name()).lower()] = cls

    def get(self, name: str):
        return self._cm.data_sources.get(name.lower())


class Catalog:
    """spark.catalog surface (subset)."""

    def __init__(self, session: SparkSession):
        self._session = session

    def listTables(self, dbName: Optional[str] = None):
        return self._session.catalog_manager.list_tables(dbName)

    def listDatabases(self):
        return self._session.catalog_manager.list_databases()

    def currentDatabase(self) -> str:
        return self._session.catalog_manager.current_database

    def setCurrentDatabase(self, name: str):
        self._session.catalog_manager.current_database = name.lower()

    def tableExists(self, name: str) -> bool:
        return self._session.catalog_manager.lookup_table(tuple(name.split("."))) is not None

    def dropTempView(self, name: str) -> bool:
        cm = self._session.catalog_manager
        if name.lower() in cm.temp_views:
            del cm.temp_views[name.lower()]
            return True
        return False


class Column:
    """Expression wrapper for the DataFrame API."""

    def __init__(self, expr: ex.Expr):
        self._expr = expr

    # arithmetic / comparison operators
    def _bin(self, other, op) -> "Column":
        return Column(ex.Function(op, (self._expr, _to_expr(other))))

    def __add__(self, o):
        return self._bin(o, "+")

    def __sub__(self, o):
        return self._bin(o, "-")

    def __mul__(self, o):
        return self._bin(o, "*")

    def __truediv__(self, o):
        return self._bin(o, "/")

    def __mod__(self, o):
        return self._bin(o, "%")

    def __radd__(self, o):
        return Column(ex.Function("+", (_to_expr(o), self._expr)))

    def __rsub__(self, o):
        return Column(ex.Function("-", (_to_expr(o), self._expr)))

    def __rmul__(self, o):
        return Column(ex.Function("*", (_to_expr(o), self._expr)))

    def __eq__(self, o):  # type: ignore[override]
        return self._bin(o, "==")

    def __ne__(self, o):  # type: ignore[override]
        return self._bin(o, "!=")

    def __lt__(self, o):
        return self._bin(o, "<")

    def __le__(self, o):
        return self._bin(o, "<=")

    def __gt__(self, o):
        return self._bin(o, ">")

    def __ge__(self, o):
        return self._bin(o, ">=")

    def __and__(self, o):
        return self._bin(o, "and")

    def __or__(self, o):
        return self._bin(o, "or")

    def __invert__(self):
        return Column(ex.Function("not", (self._expr,)))

    def __neg__(self):
        return Column(ex.Function("negative", (self._expr,)))

    def alias(self, name: str) -> "Column":
        return Column(ex.Alias(self._expr, (name,)))

    name = alias

    def cast(self, to) -> "Column":
        target = to if isinstance(to, dt.DataType) else _parse_type(to)
        return Column(ex.Cast(self._expr, target))

    def asc(self) -> "Column":
        return Column(ex.SortOrder(self._expr, True))

    def desc(self) -> "Column":
        return Column(ex.SortOrder(self._expr, False))

    def isNull(self) -> "Column":
        return Column(ex.Function("isnull", (self._expr,)))

    def isNotNull(self) -> "Column":
        return Column(ex.Function("isnotnull", (self._expr,)))

    def isin(self, *values) -> "Column":
        vals = values[0] if len(values) == 1 and isinstance(values[0], (list, tuple)) \
            else values
        return Column(ex.InList(self._expr, tuple(_to_expr(v) for v in vals)))

    def between(self, low, high) -> "Column":
        return Column(ex.Between(self._expr, _to_expr(low), _to_expr(high)))

    def like(self, pattern: str) -> "Column":
        return Column(ex.Like(self._expr, ex.lit(pattern)))

    def startswith(self, s) -> "Column":
        return Column(ex.Function("startswith", (self._expr, _to_expr(s))))

    def endswith(self, s) -> "Column":
        return Column(ex.Function("endswith", (self._expr, _to_expr(s))))

    def contains(self, s) -> "Column":
        return Column(ex.Function("contains", (self._expr, _to_expr(s))))

    def substr(self, start, length) -> "Column":
        return Column(ex.Function("substring",
                                  (self._expr, _to_expr(start), _to_expr(length))))

    def __hash__(self):
        return hash(self._expr)


def _to_expr(v) -> ex.Expr:
    if isinstance(v, Column):
        return v._expr
    if isinstance(v, ex.Expr):
        return v
    return ex.lit(v)


def _parse_type(s: str) -> dt.DataType:
    from .sql import parse_data_type
    return parse_data_type(s)


def _parse_ddl_schema(ddl: str) -> dt.StructType:
    """Parse 'a INT, b DECIMAL(10,2), c STRUCT<x: INT>' (comma split at
    depth 0 only, honoring () and <> nesting)."""
    from .sql import parse_data_type
    parts = []
    depth = 0
    start = 0
    for i, ch in enumerate(ddl):
        if ch in "(<":
            depth += 1
        elif ch in ")>":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(ddl[start:i])
            start = i + 1
    parts.append(ddl[start:])
    fields = []
    for part in parts:
        part = part.strip()
        if not part:
            continue
        name, _, typ = part.partition(" ")
        if not typ and ":" in part:
            name, _, typ = part.partition(":")
        fields.append(dt.StructField(name.strip(), _parse_type(typ.strip())))
    return dt.StructType(tuple(fields))


def col(name: str) -> Column:
    return Column(ex.Attribute(tuple(name.split("."))) if name != "*" else ex.Star())


def lit(v) -> Column:
    return Column(ex.lit(v))


class GroupedData:
    def __init__(self, df: "DataFrame", group_cols: Sequence[Column]):
        self._df = df
        self._group = tuple(_to_expr(c) for c in group_cols)

    def agg(self, *exprs) -> "DataFrame":
        items = tuple(self._group) + tuple(_to_expr(e) for e in exprs)
        plan = sp.Aggregate(self._df._plan, self._group, items)
        return DataFrame(plan, self._df._session)

    def _simple(self, fn: str, *cols) -> "DataFrame":
        targets = list(cols)
        if not targets:
            # PySpark default: aggregate every numeric non-group column
            group_names = {a.name[-1].lower() for a in self._group
                           if isinstance(a, ex.Attribute)}
            targets = [f.name for f in self._df.schema.fields
                       if f.data_type.is_numeric
                       and f.name.lower() not in group_names]
        aggs = [Column(ex.Alias(ex.Function(fn, (ex.Attribute((c,)),)),
                                (f"{fn}({c})",))) for c in targets]
        return self.agg(*aggs)

    def count(self) -> "DataFrame":
        return self.agg(Column(ex.Alias(ex.Function("count", (ex.Star(),)), ("count",))))

    def sum(self, *cols) -> "DataFrame":
        return self._simple("sum", *cols)

    def avg(self, *cols) -> "DataFrame":
        return self._simple("avg", *cols)

    def min(self, *cols) -> "DataFrame":
        return self._simple("min", *cols)

    def max(self, *cols) -> "DataFrame":
        return self._simple("max", *cols)

    def applyInPandas(self, func, schema) -> "DataFrame":
        """groupBy(...).applyInPandas — reference: sail-python-udf
        grouped-map kind (pyspark_udf.rs:19-27)."""
        from .functions.udf import UserDefinedFunction
        udf = UserDefinedFunction(func, _parse_ddl_struct(schema),
                                  "grouped_map", getattr(func, "__name__",
                                                         "applyInPandas"))
        return DataFrame(sp.GroupMap(self._df._plan, self._group, udf),
                         self._df._session)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        return CoGroupedData(self, other)


class CoGroupedData:
    def __init__(self, left: GroupedData, right: GroupedData):
        self._left = left
        self._right = right

    def applyInPandas(self, func, schema) -> "DataFrame":
        from .functions.udf import UserDefinedFunction
        udf = UserDefinedFunction(func, _parse_ddl_struct(schema),
                                  "cogrouped_map",
                                  getattr(func, "__name__", "cogroup"))
        plan = sp.CoGroupMap(self._left._df._plan, self._right._df._plan,
                             self._left._group, self._right._group, udf)
        return DataFrame(plan, self._left._df._session)


def _parse_ddl_struct(schema):
    if isinstance(schema, dt.StructType):
        return schema
    return _parse_ddl_schema(str(schema))


class DataFrame:
    def __init__(self, plan: sp.QueryPlan, session: SparkSession):
        self._plan = plan
        self._session = session

    # -- transformations -------------------------------------------------
    def select(self, *cols) -> "DataFrame":
        exprs = tuple(_to_expr(c) if not isinstance(c, str)
                      else (ex.Star() if c == "*" else ex.Attribute(tuple(c.split("."))))
                      for c in cols)
        return DataFrame(sp.Project(self._plan, exprs), self._session)

    def selectExpr(self, *exprs: str) -> "DataFrame":
        from .sql.parser import Parser
        items = []
        for s in exprs:
            p = Parser(s)
            items.append(p.parse_select_item())
        return DataFrame(sp.Project(self._plan, tuple(items)), self._session)

    def filter(self, condition) -> "DataFrame":
        if isinstance(condition, str):
            from .sql import parse_expression
            cond = parse_expression(condition)
        else:
            cond = _to_expr(condition)
        return DataFrame(sp.Filter(self._plan, cond), self._session)

    where = filter

    def withColumn(self, name: str, c: Column) -> "DataFrame":
        alias = ex.Alias(_to_expr(c), (name,))
        return DataFrame(sp.WithColumns(self._plan, (alias,)), self._session)

    def withColumnRenamed(self, old: str, new: str) -> "DataFrame":
        return DataFrame(sp.WithColumnsRenamed(self._plan, ((old, new),)),
                         self._session)

    def drop(self, *cols: str) -> "DataFrame":
        return DataFrame(sp.Drop(self._plan, tuple(cols)), self._session)

    def join(self, other: "DataFrame", on=None, how: str = "inner") -> "DataFrame":
        how = {"outer": "full", "leftouter": "left", "rightouter": "right",
               "left_outer": "left", "right_outer": "right", "fullouter": "full",
               "leftsemi": "semi", "left_semi": "semi", "leftanti": "anti",
               "left_anti": "anti"}.get(how.lower(), how.lower())
        using: Tuple[str, ...] = ()
        condition = None
        if isinstance(on, str):
            using = (on,)
        elif isinstance(on, (list, tuple)) and on and isinstance(on[0], str):
            using = tuple(on)
        elif on is not None:
            condition = _to_expr(on)
        return DataFrame(sp.Join(self._plan, other._plan, how, condition, using),
                         self._session)

    def crossJoin(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(sp.Join(self._plan, other._plan, "cross"), self._session)

    def groupBy(self, *cols) -> GroupedData:
        gcols = [col(c) if isinstance(c, str) else c for c in cols]
        return GroupedData(self, gcols)

    groupby = groupBy

    def agg(self, *exprs) -> "DataFrame":
        return GroupedData(self, []).agg(*exprs)

    def orderBy(self, *cols) -> "DataFrame":
        keys = []
        for c in cols:
            e = _to_expr(col(c) if isinstance(c, str) else c)
            if not isinstance(e, ex.SortOrder):
                e = ex.SortOrder(e, True)
            keys.append(e)
        return DataFrame(sp.Sort(self._plan, tuple(keys)), self._session)

    sort = orderBy

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(sp.Limit(self._plan, n), self._session)

    def offset(self, n: int) -> "DataFrame":
        return DataFrame(sp.Offset(self._plan, n), self._session)

    def distinct(self) -> "DataFrame":
        return DataFrame(sp.Deduplicate(self._plan), self._session)

    def dropDuplicates(self, subset: Optional[Sequence[str]] = None) -> "DataFrame":
        return DataFrame(sp.Deduplicate(self._plan, tuple(subset or ())),
                         self._session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(sp.SetOperation(self._plan, other._plan, "union", True),
                         self._session)

    unionAll = union

    def intersect(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(sp.SetOperation(self._plan, other._plan, "intersect", False),
                         self._session)

    def exceptAll(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(sp.SetOperation(self._plan, other._plan, "except", True),
                         self._session)

    def subtract(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(sp.SetOperation(self._plan, other._plan, "except", False),
                         self._session)

    def alias(self, name: str) -> "DataFrame":
        return DataFrame(sp.SubqueryAlias(self._plan, name), self._session)

    def repartition(self, n: int, *cols) -> "DataFrame":
        exprs = tuple(_to_expr(col(c) if isinstance(c, str) else c) for c in cols)
        return DataFrame(sp.Repartition(self._plan, n, exprs), self._session)

    def sample(self, withReplacement=None, fraction=None, seed=None) -> "DataFrame":
        # PySpark signature juggling: sample(fraction), sample(fraction, seed),
        # sample(withReplacement, fraction[, seed])
        if isinstance(withReplacement, float):
            withReplacement, fraction, seed = False, withReplacement, fraction
        if fraction is None:
            raise ValueError("sample() requires a fraction")
        return DataFrame(sp.Sample(self._plan, 0.0, float(fraction),
                                   bool(withReplacement), seed), self._session)

    def __getitem__(self, name: str) -> Column:
        return col(name)

    def __getattr__(self, name: str) -> Column:
        if name.startswith("_"):
            raise AttributeError(name)
        return col(name)

    def mapInPandas(self, func, schema, barrier: bool = False) -> "DataFrame":
        """mapInPandas — iterator-of-DataFrames UDF (reference:
        pyspark_map_iter_udf.rs)."""
        from .functions.udf import UserDefinedFunction
        udf = UserDefinedFunction(func, _parse_ddl_struct(schema),
                                  "map_pandas",
                                  getattr(func, "__name__", "mapInPandas"))
        return DataFrame(sp.MapPartitions(self._plan, udf, barrier),
                         self._session)

    def mapInArrow(self, func, schema, barrier: bool = False) -> "DataFrame":
        from .functions.udf import UserDefinedFunction
        udf = UserDefinedFunction(func, _parse_ddl_struct(schema),
                                  "map_arrow",
                                  getattr(func, "__name__", "mapInArrow"))
        return DataFrame(sp.MapPartitions(self._plan, udf, barrier),
                         self._session)

    # -- actions ------------------------------------------------------------
    def toArrow(self) -> pa.Table:
        return self._session._execute_query(self._plan)

    def toPandas(self):
        return self.toArrow().to_pandas()

    def collect(self) -> List[tuple]:
        table = self.toArrow()
        cols = [c.to_pylist() for c in table.columns]
        return [Row(zip(table.column_names, vals)) for vals in zip(*cols)] \
            if cols else []

    def count(self) -> int:
        plan = sp.Aggregate(self._plan, (),
                            (ex.Alias(ex.Function("count", (ex.Star(),)), ("count",)),))
        table = self._session._execute_query(plan)
        return int(table.column(0)[0].as_py())

    def first(self):
        rows = self.limit(1).collect()
        return rows[0] if rows else None

    def head(self, n: int = 1):
        rows = self.limit(n).collect()
        return rows[0] if n == 1 and rows else rows

    def take(self, n: int):
        return self.limit(n).collect()

    def show(self, n: int = 20, truncate: bool = True):
        print(self._show_string(n, truncate))

    def _show_string(self, n: int = 20, truncate: bool = True) -> str:
        table = self.limit(n).toArrow()
        names = table.column_names
        rows = [[_fmt_cell(v, truncate) for v in col.to_pylist()]
                for col in table.columns]
        widths = [max([len(nm)] + [len(r) for r in rs]) for nm, rs in zip(names, rows)]
        sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
        out = [sep, "|" + "|".join(f" {nm:<{w}} " for nm, w in zip(names, widths)) + "|", sep]
        for i in range(table.num_rows):
            out.append("|" + "|".join(
                f" {rows[j][i]:<{widths[j]}} " for j in range(len(names))) + "|")
        out.append(sep)
        return "\n".join(out)

    @property
    def schema(self) -> dt.StructType:
        node = self._session._resolve(self._plan)
        return dt.StructType(tuple(dt.StructField(f.name, f.dtype, f.nullable)
                                   for f in node.schema))

    @property
    def columns(self) -> List[str]:
        return [f.name for f in self.schema.fields]

    @property
    def dtypes(self) -> List[Tuple[str, str]]:
        return [(f.name, f.data_type.simple_string()) for f in self.schema.fields]

    def explain(self, extended: bool = False):
        from .plan.nodes import explain
        print(explain(self._session._resolve(self._plan)))

    def createOrReplaceTempView(self, name: str):
        self._session.catalog_manager.register_temp_view(name, self._plan)

    def createTempView(self, name: str):
        self._session.catalog_manager.register_temp_view(name, self._plan,
                                                         replace=False)

    def cache(self) -> "DataFrame":
        return self

    def persist(self, *_) -> "DataFrame":
        return self

    def unpersist(self) -> "DataFrame":
        return self

    def withWatermark(self, eventTime: str,
                      delayThreshold: str) -> "DataFrame":
        from .streaming import parse_delay
        return DataFrame(sp.WithWatermark(self._plan, eventTime,
                                          parse_delay(delayThreshold)),
                         self._session)

    @property
    def write(self) -> "DataFrameWriter":
        return DataFrameWriter(self)

    @property
    def writeStream(self):
        from .streaming import DataStreamWriter
        return DataStreamWriter(self)

    @property
    def isStreaming(self) -> bool:
        from .streaming import _find_stream_read
        return _find_stream_read(self._plan) is not None

    @property
    def sparkSession(self) -> SparkSession:
        return self._session


class Row(dict):
    def __getattr__(self, name):
        try:
            return self[name]
        except KeyError as e:
            raise AttributeError(name) from e

    def __getitem__(self, key):
        if isinstance(key, int):
            return list(self.values())[key]
        return super().__getitem__(key)

    def __repr__(self):
        inner = ", ".join(f"{k}={v!r}" for k, v in self.items())
        return f"Row({inner})"


def _fmt_cell(v, truncate: bool) -> str:
    if v is None:
        return "NULL"
    s = str(v)
    if truncate and len(s) > 20:
        s = s[:17] + "..."
    return s


class DataFrameReader:
    def __init__(self, session: SparkSession):
        self._session = session
        self._format = "parquet"
        self._options: Dict[str, str] = {}
        self._schema: Optional[dt.StructType] = None

    def format(self, fmt: str) -> "DataFrameReader":
        self._format = fmt.lower()
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key.lower()] = str(value)
        return self

    def options(self, **opts) -> "DataFrameReader":
        for k, v in opts.items():
            self.option(k, v)
        return self

    def schema(self, schema) -> "DataFrameReader":
        if isinstance(schema, str):
            self._schema = _parse_ddl_schema(schema)
        else:
            self._schema = schema
        return self

    def load(self, path: Optional[Union[str, List[str]]] = None) -> DataFrame:
        paths = (path,) if isinstance(path, str) else tuple(path or ())
        plan = sp.ReadDataSource(self._format, paths, self._schema,
                                 tuple(self._options.items()))
        return DataFrame(plan, self._session)

    def parquet(self, *paths: str) -> DataFrame:
        return self.format("parquet").load(list(paths))

    def csv(self, path, header=None, sep=None, inferSchema=None, **kw) -> DataFrame:
        if header is not None:
            self.option("header", str(header).lower())
        if sep is not None:
            self.option("sep", sep)
        return self.format("csv").load(path)

    def json(self, path) -> DataFrame:
        return self.format("json").load(path)

    def table(self, name: str) -> DataFrame:
        return self._session.table(name)


class DataFrameWriter:
    def __init__(self, df: DataFrame):
        self._df = df
        self._format = "parquet"
        self._mode = "error"
        self._options: Dict[str, str] = {}
        self._partition_by: Tuple[str, ...] = ()

    def format(self, fmt: str) -> "DataFrameWriter":
        self._format = fmt.lower()
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        self._mode = {"errorifexists": "error"}.get(m.lower(), m.lower())
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[key.lower()] = str(value)
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = tuple(cols)
        return self

    def save(self, path: str):
        from .io.formats import write_table
        table = self._df.toArrow()
        write_table(table, self._format, path, self._mode, self._options,
                    self._partition_by)

    def parquet(self, path: str):
        self.format("parquet").save(path)

    def csv(self, path: str, header=None):
        if header is not None:
            self.option("header", str(header).lower())
        self.format("csv").save(path)

    def json(self, path: str):
        self.format("json").save(path)

    def saveAsTable(self, name: str):
        session = self._df._session
        table = self._df.toArrow()
        from .spec.data_type import StructType
        entry = TableEntry(tuple(name.split(".")), _schema_of(table), table,
                           (), "memory")
        session.catalog_manager.register_table(
            entry, replace=(self._mode == "overwrite"),
            if_not_exists=(self._mode == "ignore"))

    def insertInto(self, name: str, overwrite: bool = False):
        session = self._df._session
        cmd = sp.InsertInto(tuple(name.split(".")), self._df._plan,
                            overwrite or self._mode == "overwrite")
        session._execute_command(cmd)


def _schema_of(table: pa.Table) -> dt.StructType:
    from .columnar.arrow_interop import arrow_type_to_spec
    return dt.StructType(tuple(
        dt.StructField(n, arrow_type_to_spec(c.type), True)
        for n, c in zip(table.column_names, table.columns)))


def _empty_table(schema: dt.StructType) -> pa.Table:
    from .columnar.arrow_interop import spec_type_to_arrow
    arrays = [pa.array([], type=spec_type_to_arrow(f.data_type))
              for f in schema.fields]
    return pa.Table.from_arrays(arrays, names=[f.name for f in schema.fields])
