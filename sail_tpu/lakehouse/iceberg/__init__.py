from .table import IcebergTable  # noqa: F401
