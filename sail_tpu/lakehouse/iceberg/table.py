"""Apache Iceberg tables — metadata, manifests, snapshots, from scratch.

Reference role: crates/sail-iceberg (src/spec table metadata/manifests/
snapshots, src/operations append/overwrite, src/table_format.rs), built
against the public Iceberg table spec v2 with the Hadoop-style file
layout: `metadata/vN.metadata.json` + `version-hint.text`, Avro manifest
lists and manifests (see avro_io), parquet data files. Commits use atomic
create-if-absent of the next metadata version (optimistic concurrency,
like the Delta implementation).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from . import avro_io

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "partition", "type": {
                    "type": "map", "values": ["null", "string"]}},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

_MANIFEST_FILE_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "sequence_number", "type": "long"},
        {"name": "added_snapshot_id", "type": "long"},
        {"name": "added_files_count", "type": "int"},
        {"name": "existing_files_count", "type": "int"},
        {"name": "deleted_files_count", "type": "int"},
        {"name": "added_rows_count", "type": "long"},
    ]}


class IcebergConflict(Exception):
    pass


def _spec_to_iceberg_schema(st) -> Tuple[dict, int]:
    """Convert a spec StructType to an Iceberg schema dict. Returns the
    schema plus the final field-id counter value: nested list/map/struct
    types consume ids beyond the top-level field count, and the Iceberg
    invariant requires last-column-id >= the max assigned field id.
    Top-level field ids are recoverable from the returned schema's
    ``fields`` list (partition-spec source-ids must use THOSE ids, not
    positional indexes)."""
    from ...spec import data_type as dt

    next_id = [0]

    def fid():
        next_id[0] += 1
        return next_id[0]

    def conv(t):
        if isinstance(t, dt.StructType):
            return {"type": "struct", "fields": [
                {"id": fid(), "name": f.name, "required": not f.nullable,
                 "type": conv(f.data_type)} for f in t.fields]}
        if isinstance(t, dt.ArrayType):
            return {"type": "list", "element-id": fid(),
                    "element": conv(t.element_type),
                    "element-required": not t.contains_null}
        if isinstance(t, dt.MapType):
            return {"type": "map", "key-id": fid(), "key": conv(t.key_type),
                    "value-id": fid(), "value": conv(t.value_type),
                    "value-required": not t.value_contains_null}
        m = {dt.BooleanType: "boolean", dt.IntegerType: "int",
             dt.ByteType: "int", dt.ShortType: "int", dt.LongType: "long",
             dt.FloatType: "float", dt.DoubleType: "double",
             dt.StringType: "string", dt.BinaryType: "binary",
             dt.DateType: "date"}
        for cls, name in m.items():
            if isinstance(t, cls):
                return name
        if isinstance(t, dt.DecimalType):
            return f"decimal({t.precision}, {t.scale})"
        if isinstance(t, dt.TimestampType):
            return "timestamptz" if t.timezone is not None else "timestamp"
        raise ValueError(f"cannot map type {t!r} to iceberg")

    out = conv(st)
    out["schema-id"] = 0
    return out, next_id[0]


def _iceberg_type_to_spec(t):
    from ...spec import data_type as dt

    if isinstance(t, dict):
        if t["type"] == "struct":
            return dt.StructType(tuple(
                dt.StructField(f["name"], _iceberg_type_to_spec(f["type"]),
                               not f.get("required", False))
                for f in t["fields"]))
        if t["type"] == "list":
            return dt.ArrayType(_iceberg_type_to_spec(t["element"]),
                                not t.get("element-required", False))
        if t["type"] == "map":
            return dt.MapType(_iceberg_type_to_spec(t["key"]),
                              _iceberg_type_to_spec(t["value"]),
                              not t.get("value-required", False))
        raise ValueError(f"unknown iceberg type {t}")
    m = {"boolean": dt.BooleanType(), "int": dt.IntegerType(),
         "long": dt.LongType(), "float": dt.FloatType(),
         "double": dt.DoubleType(), "string": dt.StringType(),
         "binary": dt.BinaryType(), "date": dt.DateType(),
         "timestamp": dt.TimestampType(None),
         "timestamptz": dt.TimestampType("UTC"), "uuid": dt.StringType()}
    if t in m:
        return m[t]
    if t.startswith("decimal"):
        p, s = t[t.index("(") + 1:t.index(")")].split(",")
        return dt.DecimalType(int(p), int(s))
    raise ValueError(f"unknown iceberg type {t!r}")


class IcebergTable:
    def __init__(self, path: str):
        self.path = path
        self.metadata_dir = os.path.join(path, "metadata")

    # -- metadata --------------------------------------------------------
    @staticmethod
    def exists(path: str) -> bool:
        return os.path.exists(os.path.join(path, "metadata",
                                           "version-hint.text"))

    def _current_version(self) -> Optional[int]:
        hint = os.path.join(self.metadata_dir, "version-hint.text")
        if not os.path.exists(hint):
            return None
        with open(hint) as f:
            return int(f.read().strip())

    def _metadata_path(self, version: int) -> str:
        return os.path.join(self.metadata_dir, f"v{version}.metadata.json")

    def metadata(self, version: Optional[int] = None) -> dict:
        v = version if version is not None else self._current_version()
        if v is None:
            raise FileNotFoundError(f"not an Iceberg table: {self.path}")
        with open(self._metadata_path(v)) as f:
            return json.load(f)

    def schema(self, version: Optional[int] = None):
        md = self.metadata(version)
        sid = md.get("current-schema-id", 0)
        for s in md.get("schemas", []):
            if s.get("schema-id") == sid:
                return _iceberg_type_to_spec(s)
        return _iceberg_type_to_spec(md["schemas"][0])

    # -- snapshots -------------------------------------------------------
    def snapshot(self, snapshot_id: Optional[int] = None,
                 timestamp_ms: Optional[int] = None) -> Optional[dict]:
        md = self.metadata()
        snaps = md.get("snapshots", [])
        if not snaps:
            return None
        if snapshot_id is None and timestamp_ms is not None:
            eligible = [s for s in snaps
                        if s["timestamp-ms"] <= timestamp_ms]
            if not eligible:
                raise ValueError("no snapshot at or before timestamp")
            return max(eligible, key=lambda s: s["timestamp-ms"])
        if snapshot_id is None:
            snapshot_id = md.get("current-snapshot-id")
            if snapshot_id in (None, -1):
                return None
        for s in snaps:
            if s["snapshot-id"] == snapshot_id:
                return s
        raise ValueError(f"snapshot {snapshot_id} not found")

    def data_files(self, snapshot: Optional[dict]) -> List[dict]:
        if snapshot is None:
            return []
        mlist_path = snapshot["manifest-list"]
        manifests, _ = avro_io.read_container(
            os.path.join(self.path, mlist_path)
            if not os.path.isabs(mlist_path) else mlist_path)
        out = []
        for m in manifests:
            entries, _ = avro_io.read_container(
                os.path.join(self.path, m["manifest_path"])
                if not os.path.isabs(m["manifest_path"])
                else m["manifest_path"])
            for e in entries:
                if e["status"] in (0, 1):  # existing | added
                    out.append(e["data_file"])
        return out

    def to_arrow(self, snapshot_id: Optional[int] = None,
                 timestamp_ms: Optional[int] = None,
                 columns: Optional[Sequence[str]] = None):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ...columnar.arrow_interop import spec_type_to_arrow

        snap = self.snapshot(snapshot_id, timestamp_ms)
        files = self.data_files(snap)
        tables = []
        for df in files:
            fp = df["file_path"]
            if not os.path.isabs(fp):
                fp = os.path.join(self.path, fp)
            tables.append(pq.read_table(
                fp, columns=list(columns) if columns else None))
        if not tables:
            st = self.schema()
            fields = [(f.name, spec_type_to_arrow(f.data_type))
                      for f in st.fields
                      if columns is None or f.name in columns]
            return pa.table({n: pa.array([], type=t) for n, t in fields})
        return pa.concat_tables(tables, promote_options="permissive")

    def history(self) -> List[dict]:
        md = self.metadata()
        return sorted(md.get("snapshots", []),
                      key=lambda s: s["timestamp-ms"], reverse=True)

    # -- writes ----------------------------------------------------------
    def create(self, table, partition_by: Sequence[str] = ()) -> int:
        from ...columnar.arrow_interop import arrow_type_to_spec
        from ...spec import data_type as dt

        os.makedirs(self.metadata_dir, exist_ok=True)
        st = dt.StructType(tuple(
            dt.StructField(n, arrow_type_to_spec(c.type), True)
            for n, c in zip(table.column_names, table.columns)))
        schema_json, last_column_id = _spec_to_iceberg_schema(st)
        md = {
            "format-version": 2,
            "table-uuid": str(uuid.uuid4()),
            "location": self.path,
            "last-sequence-number": 0,
            "last-updated-ms": int(time.time() * 1000),
            "last-column-id": last_column_id,
            "current-schema-id": 0,
            "schemas": [schema_json],
            "default-spec-id": 0,
            "partition-specs": [{"spec-id": 0, "fields": [
                {"name": c, "transform": "identity",
                 "source-id": next(f["id"] for f in schema_json["fields"]
                                   if f["name"] == c),
                 "field-id": 1000 + i}
                for i, c in enumerate(partition_by)]}],
            "last-partition-id": 1000 + len(partition_by) - 1,
            "default-sort-order-id": 0,
            "sort-orders": [{"order-id": 0, "fields": []}],
            "properties": {},
            "current-snapshot-id": -1,
            "snapshots": [],
            "snapshot-log": [],
            "metadata-log": [],
        }
        self._write_metadata_version(1, md)
        if table.num_rows:
            return self.append(table)
        return 1

    def _write_metadata_version(self, version: int, md: dict):
        path = self._metadata_path(version)
        tmp = path + f".{uuid.uuid4().hex}.tmp"
        with open(tmp, "w") as f:
            json.dump(md, f)
        try:
            os.link(tmp, path)  # atomic create-if-absent
        except FileExistsError:
            raise IcebergConflict(
                f"concurrent commit of metadata v{version}")
        finally:
            os.unlink(tmp)
        hint_tmp = os.path.join(self.metadata_dir,
                                f".hint.{uuid.uuid4().hex}.tmp")
        with open(hint_tmp, "w") as f:
            f.write(str(version))
        os.replace(hint_tmp, os.path.join(self.metadata_dir,
                                          "version-hint.text"))

    def _partition_columns(self) -> List[str]:
        """Identity-transform column names of the default partition spec."""
        md = self.metadata()
        spec_id = md.get("default-spec-id", 0)
        for spec in md.get("partition-specs", []):
            if spec.get("spec-id") == spec_id:
                return [f["name"] for f in spec.get("fields", [])
                        if f.get("transform") == "identity"]
        return []

    def _write_data_files(self, table) -> List[dict]:
        import pyarrow.parquet as pq

        data_dir = os.path.join(self.path, "data")
        os.makedirs(data_dir, exist_ok=True)
        part_cols = [c for c in self._partition_columns()
                     if c in table.column_names]
        if part_cols and table.num_rows:
            groups: Dict[tuple, List[int]] = {}
            rows = table.select(part_cols).to_pylist()
            for i, row in enumerate(rows):
                groups.setdefault(
                    tuple(row[c] for c in part_cols), []).append(i)
            splits = [({c: (None if v is None else str(v))
                        for c, v in zip(part_cols, key)}, table.take(idxs))
                      for key, idxs in groups.items()]
        else:
            splits = [({}, table)]
        out = []
        for partition, chunk in splits:
            name = f"data/{uuid.uuid4().hex}.parquet"
            fp = os.path.join(self.path, name)
            pq.write_table(chunk, fp)
            out.append({"content": 0, "file_path": name,
                        "file_format": "PARQUET", "partition": partition,
                        "record_count": chunk.num_rows,
                        "file_size_in_bytes": os.path.getsize(fp)})
        return out

    def _commit_snapshot(self, new_entries: List[dict],
                         carry_forward: bool, operation: str,
                         max_retries: int = 10) -> int:
        for _ in range(max_retries):
            version = self._current_version()
            md = self.metadata(version)
            seq = md["last-sequence-number"] + 1
            snap_id = int(uuid.uuid4().int % (1 << 62))
            manifest_name = f"metadata/{uuid.uuid4().hex}-m0.avro"
            entries = [{"status": 1, "snapshot_id": snap_id,
                        "data_file": df} for df in new_entries]
            if carry_forward:
                prev = self.snapshot()
                for df in self.data_files(prev):
                    entries.append({"status": 0, "snapshot_id": snap_id,
                                    "data_file": df})
            avro_io.write_container(
                os.path.join(self.path, manifest_name),
                _MANIFEST_ENTRY_SCHEMA, entries)
            mlist_name = f"metadata/snap-{snap_id}.avro"
            avro_io.write_container(
                os.path.join(self.path, mlist_name), _MANIFEST_FILE_SCHEMA,
                [{"manifest_path": manifest_name,
                  "manifest_length": os.path.getsize(
                      os.path.join(self.path, manifest_name)),
                  "partition_spec_id": 0, "content": 0,
                  "sequence_number": seq, "added_snapshot_id": snap_id,
                  "added_files_count": len(new_entries),
                  "existing_files_count": len(entries) - len(new_entries),
                  "deleted_files_count": 0,
                  "added_rows_count": sum(df["record_count"]
                                          for df in new_entries)}])
            snapshot = {
                "snapshot-id": snap_id,
                "sequence-number": seq,
                "timestamp-ms": int(time.time() * 1000),
                "manifest-list": mlist_name,
                "summary": {"operation": operation},
                "schema-id": md.get("current-schema-id", 0),
            }
            md["snapshots"] = md.get("snapshots", []) + [snapshot]
            md["current-snapshot-id"] = snap_id
            md["last-sequence-number"] = seq
            md["last-updated-ms"] = snapshot["timestamp-ms"]
            md.setdefault("snapshot-log", []).append(
                {"snapshot-id": snap_id,
                 "timestamp-ms": snapshot["timestamp-ms"]})
            try:
                self._write_metadata_version(version + 1, md)
                return snap_id
            except IcebergConflict:
                continue  # re-read the new base metadata and retry
        raise IcebergConflict("gave up after repeated commit races")

    def append(self, table) -> int:
        return self._commit_snapshot(self._write_data_files(table),
                                     carry_forward=True, operation="append")

    def overwrite(self, table) -> int:
        return self._commit_snapshot(self._write_data_files(table),
                                     carry_forward=False,
                                     operation="overwrite")
