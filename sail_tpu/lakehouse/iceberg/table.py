"""Apache Iceberg tables — metadata, manifests, snapshots, from scratch.

Reference role: crates/sail-iceberg (src/spec table metadata/manifests/
snapshots, src/operations append/overwrite, src/table_format.rs), built
against the public Iceberg table spec v2 with the Hadoop-style file
layout: `metadata/vN.metadata.json` + `version-hint.text`, Avro manifest
lists and manifests (see avro_io), parquet data files. Commits use atomic
create-if-absent of the next metadata version (optimistic concurrency,
like the Delta implementation).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from . import avro_io

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        # explicit data sequence number; null = inherit the manifest's
        # (spec v2 inheritance for ADDED entries)
        {"name": "sequence_number", "type": ["null", "long"],
         "default": None},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                # 0 = data, 1 = position deletes, 2 = equality deletes
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "partition", "type": {
                    "type": "map", "values": ["null", "string"]}},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
                # schema field ids of the equality-delete key columns
                {"name": "equality_ids",
                 "type": ["null", {"type": "array", "items": "int"}],
                 "default": None},
            ]}},
    ]}

_MANIFEST_FILE_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "sequence_number", "type": "long"},
        {"name": "added_snapshot_id", "type": "long"},
        {"name": "added_files_count", "type": "int"},
        {"name": "existing_files_count", "type": "int"},
        {"name": "deleted_files_count", "type": "int"},
        {"name": "added_rows_count", "type": "long"},
    ]}


class IcebergConflict(Exception):
    pass


def _spec_to_iceberg_schema(st) -> Tuple[dict, int]:
    """Convert a spec StructType to an Iceberg schema dict. Returns the
    schema plus the final field-id counter value: nested list/map/struct
    types consume ids beyond the top-level field count, and the Iceberg
    invariant requires last-column-id >= the max assigned field id.
    Top-level field ids are recoverable from the returned schema's
    ``fields`` list (partition-spec source-ids must use THOSE ids, not
    positional indexes)."""
    from ...spec import data_type as dt

    next_id = [0]

    def fid():
        next_id[0] += 1
        return next_id[0]

    def conv(t):
        if isinstance(t, dt.StructType):
            return {"type": "struct", "fields": [
                {"id": fid(), "name": f.name, "required": not f.nullable,
                 "type": conv(f.data_type)} for f in t.fields]}
        if isinstance(t, dt.ArrayType):
            return {"type": "list", "element-id": fid(),
                    "element": conv(t.element_type),
                    "element-required": not t.contains_null}
        if isinstance(t, dt.MapType):
            return {"type": "map", "key-id": fid(), "key": conv(t.key_type),
                    "value-id": fid(), "value": conv(t.value_type),
                    "value-required": not t.value_contains_null}
        m = {dt.BooleanType: "boolean", dt.IntegerType: "int",
             dt.ByteType: "int", dt.ShortType: "int", dt.LongType: "long",
             dt.FloatType: "float", dt.DoubleType: "double",
             dt.StringType: "string", dt.BinaryType: "binary",
             dt.DateType: "date"}
        for cls, name in m.items():
            if isinstance(t, cls):
                return name
        if isinstance(t, dt.DecimalType):
            return f"decimal({t.precision}, {t.scale})"
        if isinstance(t, dt.TimestampType):
            return "timestamptz" if t.timezone is not None else "timestamp"
        raise ValueError(f"cannot map type {t!r} to iceberg")

    out = conv(st)
    out["schema-id"] = 0
    return out, next_id[0]


def _iceberg_type_to_spec(t):
    from ...spec import data_type as dt

    if isinstance(t, dict):
        if t["type"] == "struct":
            return dt.StructType(tuple(
                dt.StructField(f["name"], _iceberg_type_to_spec(f["type"]),
                               not f.get("required", False))
                for f in t["fields"]))
        if t["type"] == "list":
            return dt.ArrayType(_iceberg_type_to_spec(t["element"]),
                                not t.get("element-required", False))
        if t["type"] == "map":
            return dt.MapType(_iceberg_type_to_spec(t["key"]),
                              _iceberg_type_to_spec(t["value"]),
                              not t.get("value-required", False))
        raise ValueError(f"unknown iceberg type {t}")
    m = {"boolean": dt.BooleanType(), "int": dt.IntegerType(),
         "long": dt.LongType(), "float": dt.FloatType(),
         "double": dt.DoubleType(), "string": dt.StringType(),
         "binary": dt.BinaryType(), "date": dt.DateType(),
         "timestamp": dt.TimestampType(None),
         "timestamptz": dt.TimestampType("UTC"), "uuid": dt.StringType()}
    if t in m:
        return m[t]
    if t.startswith("decimal"):
        p, s = t[t.index("(") + 1:t.index(")")].split(",")
        return dt.DecimalType(int(p), int(s))
    raise ValueError(f"unknown iceberg type {t!r}")


class IcebergTable:
    def __init__(self, path: str, metadata_location: Optional[str] = None):
        """``metadata_location`` pins the table to a specific metadata file
        (catalog-vended pointer, e.g. HMS/REST ``metadata_location``)
        instead of the directory's version hint."""
        self.path = path
        self.metadata_dir = os.path.join(path, "metadata")
        self.metadata_location = metadata_location

    # -- metadata --------------------------------------------------------
    @staticmethod
    def exists(path: str) -> bool:
        return os.path.exists(os.path.join(path, "metadata",
                                           "version-hint.text"))

    def _current_version(self) -> Optional[int]:
        hint = os.path.join(self.metadata_dir, "version-hint.text")
        if not os.path.exists(hint):
            return None
        with open(hint) as f:
            return int(f.read().strip())

    def _metadata_path(self, version: int) -> str:
        return os.path.join(self.metadata_dir, f"v{version}.metadata.json")

    def metadata(self, version: Optional[int] = None) -> dict:
        if version is None and self.metadata_location:
            with open(self.metadata_location) as f:
                return json.load(f)
        v = version if version is not None else self._current_version()
        if v is None:
            raise FileNotFoundError(f"not an Iceberg table: {self.path}")
        with open(self._metadata_path(v)) as f:
            return json.load(f)

    def schema(self, version: Optional[int] = None):
        md = self.metadata(version)
        sid = md.get("current-schema-id", 0)
        for s in md.get("schemas", []):
            if s.get("schema-id") == sid:
                return _iceberg_type_to_spec(s)
        return _iceberg_type_to_spec(md["schemas"][0])

    # -- snapshots -------------------------------------------------------
    def snapshot(self, snapshot_id=None,
                 timestamp_ms: Optional[int] = None) -> Optional[dict]:
        md = self.metadata()
        snaps = md.get("snapshots", [])
        if isinstance(snapshot_id, str):
            # named ref: branch or tag (spec v2 `refs` map). `main` is
            # implicitly the current state on tables whose writers never
            # materialized a refs entry.
            ref = (md.get("refs") or {}).get(snapshot_id)
            if ref is not None:
                snapshot_id = int(ref["snapshot-id"])
            elif snapshot_id == "main":
                snapshot_id = None
            else:
                raise ValueError(f"unknown ref {snapshot_id!r}")
        if not snaps:
            return None
        if snapshot_id is None and timestamp_ms is not None:
            eligible = [s for s in snaps
                        if s["timestamp-ms"] <= timestamp_ms]
            if not eligible:
                raise ValueError("no snapshot at or before timestamp")
            return max(eligible, key=lambda s: s["timestamp-ms"])
        if snapshot_id is None:
            snapshot_id = md.get("current-snapshot-id")
            if snapshot_id in (None, -1):
                return None
        for s in snaps:
            if s["snapshot-id"] == snapshot_id:
                return s
        raise ValueError(f"snapshot {snapshot_id} not found")

    def _entries(self, snapshot: Optional[dict]) -> List[Tuple[dict, int]]:
        """All live (data_file, data_sequence_number) pairs of a snapshot,
        delete files included (distinguished by data_file['content'])."""
        if snapshot is None:
            return []
        mlist_path = snapshot["manifest-list"]
        manifests, _ = avro_io.read_container(
            os.path.join(self.path, mlist_path)
            if not os.path.isabs(mlist_path) else mlist_path)
        out = []
        for m in manifests:
            entries, _ = avro_io.read_container(
                os.path.join(self.path, m["manifest_path"])
                if not os.path.isabs(m["manifest_path"])
                else m["manifest_path"])
            mseq = m.get("sequence_number", 0)
            for e in entries:
                if e["status"] in (0, 1):  # existing | added
                    seq = e.get("sequence_number")
                    out.append((e["data_file"],
                                mseq if seq is None else seq))
        return out

    def data_files(self, snapshot: Optional[dict]) -> List[dict]:
        return [df for df, _ in self._entries(snapshot)
                if df.get("content", 0) == 0]

    def delete_files(self, snapshot: Optional[dict]) -> List[Tuple[dict, int]]:
        """(delete_file, data_sequence_number) pairs: content 1 = position
        deletes, 2 = equality deletes (reference:
        crates/sail-iceberg/src/spec/delete_index.rs)."""
        return [(df, seq) for df, seq in self._entries(snapshot)
                if df.get("content", 0) in (1, 2)]

    def _field_names_by_id(self) -> Dict[int, str]:
        md = self.metadata()
        sid = md.get("current-schema-id", 0)
        schemas = md.get("schemas", [])
        schema = next((s for s in schemas if s.get("schema-id") == sid),
                      schemas[0] if schemas else {"fields": []})
        return {f["id"]: f["name"] for f in schema.get("fields", [])}

    def _resolve_path(self, fp: str) -> str:
        return fp if os.path.isabs(fp) else os.path.join(self.path, fp)

    def _load_delete_index(self, entries):
        """Position deletes as {data file_path: [(delete_seq, positions)]}
        and equality deletes as [(delete_seq, key column names, key table)].
        ``entries`` is the (data_file, seq) list from one _entries() walk —
        manifests are read once per scan, not once per purpose."""
        import pyarrow.parquet as pq

        pos: Dict[str, List[Tuple[int, List[int]]]] = {}
        eq: List[Tuple[int, List[str], object]] = []
        by_id = None
        for df, seq in entries:
            if df.get("content", 0) not in (1, 2):
                continue
            t = pq.read_table(self._resolve_path(df["file_path"]))
            if df.get("content") == 1:  # position deletes
                paths = t.column("file_path").to_pylist()
                positions = t.column("pos").to_pylist()
                grouped: Dict[str, List[int]] = {}
                for p, i in zip(paths, positions):
                    grouped.setdefault(p, []).append(i)
                for p, idxs in grouped.items():
                    pos.setdefault(p, []).append((seq, idxs))
            else:  # equality deletes
                ids = df.get("equality_ids") or []
                if by_id is None:
                    by_id = self._field_names_by_id()
                cols = [by_id[i] for i in ids if i in by_id]
                if not cols:  # fall back to the delete file's own columns
                    cols = t.column_names
                eq.append((seq, cols, t.select(cols)))
        return pos, eq

    def _apply_deletes(self, table, file_path: str, data_seq: int,
                       pos_index, eq_deletes):
        """Row-level delete application during scan (reference:
        IcebergDeleteApplyExec). Position deletes apply when
        delete_seq >= data_seq; equality deletes when delete_seq >
        data_seq."""
        import numpy as np
        import pyarrow as pa

        if table.num_rows == 0:
            return table
        mask = None
        # delete files written by other engines usually record the fully
        # resolved data-file path; ours record the stored (relative) one
        pos_lists = (pos_index.get(file_path, [])
                     + pos_index.get(self._resolve_path(file_path), []))
        for seq, idxs in pos_lists:
            if seq >= data_seq:
                if mask is None:
                    mask = np.ones(table.num_rows, dtype=bool)
                idx = np.asarray(idxs, dtype=np.int64)
                mask[idx[(idx >= 0) & (idx < table.num_rows)]] = False
        for seq, cols, keys in eq_deletes:
            if seq <= data_seq or keys.num_rows == 0:
                continue
            avail = [c for c in cols if c in table.column_names]
            if len(avail) != len(cols):
                continue
            import pandas as pd
            left = table.select(cols).to_pandas()
            right = keys.to_pandas().drop_duplicates()
            hit = left.merge(right.assign(__del=True), on=cols, how="left")
            dead = hit["__del"].fillna(False).to_numpy(dtype=bool)
            if mask is None:
                mask = np.ones(table.num_rows, dtype=bool)
            mask &= ~dead
        if mask is None or mask.all():
            return table
        return table.filter(pa.array(mask))

    def to_arrow(self, snapshot_id: Optional[int] = None,
                 timestamp_ms: Optional[int] = None,
                 columns: Optional[Sequence[str]] = None):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ...columnar.arrow_interop import spec_type_to_arrow

        snap = self.snapshot(snapshot_id, timestamp_ms)
        all_entries = self._entries(snap)
        entries = [(df, seq) for df, seq in all_entries
                   if df.get("content", 0) == 0]
        pos_index, eq_deletes = self._load_delete_index(all_entries)
        # equality filtering needs the key columns even when projected out
        read_cols = None
        if columns is not None:
            need = set(columns)
            for _, cols, _ in eq_deletes:
                need.update(cols)
            read_cols = [c for c in need]
        evolved = len(self.metadata().get("schemas", [])) > 1
        evo_plan = self._evolution_plan(read_cols) if evolved else None
        tables = []
        for df, seq in entries:
            fp = df["file_path"]
            if evolved:
                t = self._read_evolved(fp, *evo_plan)
            else:
                t = pq.read_table(self._resolve_path(fp),
                                  columns=read_cols if read_cols else None)
            t = self._apply_deletes(t, fp, seq, pos_index, eq_deletes)
            if columns is not None:
                t = t.select(list(columns))
            tables.append(t)
        if not tables:
            st = self.schema()
            fields = [(f.name, spec_type_to_arrow(f.data_type))
                      for f in st.fields
                      if columns is None or f.name in columns]
            return pa.table({n: pa.array([], type=t) for n, t in fields})
        return pa.concat_tables(tables, promote_options="permissive")

    def _evolution_plan(self, read_cols):
        """(wanted fields, historical-name map) computed ONCE per scan:
        [(name, field_id, arrow_type)] for the current schema projection."""
        from ...columnar.arrow_interop import spec_type_to_arrow

        md = self.metadata()
        sid = md.get("current-schema-id", 0)
        schemas = md.get("schemas", [])
        current = next((s for s in schemas if s.get("schema-id") == sid),
                       schemas[0])
        historical = self._historical_names(md)
        wanted = []
        for f in current.get("fields", []):
            if read_cols is not None and f["name"] not in read_cols:
                continue
            wanted.append((f["name"], f["id"],
                           spec_type_to_arrow(
                               _iceberg_type_to_spec(f["type"]))))
        return wanted, historical

    def _read_evolved(self, fp: str, wanted, historical) -> "object":
        """Read a data file written under ANY historical schema, projected
        onto the CURRENT schema by field id: renamed columns resolve
        through the id's unambiguous older names, added columns null-fill,
        dropped columns vanish. Row order/count preserved (position
        deletes stay valid)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        path = self._resolve_path(fp)
        file_cols = set(pq.ParquetFile(path).schema_arrow.names)
        want_src = {}
        for name, fid, at in wanted:
            src = next((c for c in historical.get(fid, [name])
                        if c in file_cols), None)
            want_src[name] = src
        present = sorted({s for s in want_src.values() if s is not None})
        raw = pq.read_table(path, columns=present or None)
        arrays, names = [], []
        for name, fid, at in wanted:
            src = want_src[name]
            if src is None:
                arr = pa.nulls(raw.num_rows, type=at)
            else:
                arr = raw.column(src)
                if arr.type != at:
                    arr = arr.cast(at, safe=False)
            arrays.append(arr)
            names.append(name)
        return pa.Table.from_arrays(arrays, names=names)

    def history(self) -> List[dict]:
        md = self.metadata()
        return sorted(md.get("snapshots", []),
                      key=lambda s: s["timestamp-ms"], reverse=True)

    # -- schema evolution -------------------------------------------------
    # Reference: crates/sail-iceberg/src/schema_evolution.rs — columns are
    # tracked by FIELD ID; files written under older schemas resolve
    # through the id's historical names (add → null-fill, rename → old
    # name lookup, drop → projected away).

    def _evolve_schema(self, mutate) -> None:
        for _ in range(10):
            version = self._current_version()
            md = self.metadata(version)
            sid = md.get("current-schema-id", 0)
            schemas = md.get("schemas", [])
            current = next(s for s in schemas if s.get("schema-id") == sid)
            new_schema = json.loads(json.dumps(current))  # deep copy
            mutate(new_schema, md)
            new_sid = max(s.get("schema-id", 0) for s in schemas) + 1
            new_schema["schema-id"] = new_sid
            md["schemas"] = schemas + [new_schema]
            md["current-schema-id"] = new_sid
            md["last-updated-ms"] = int(time.time() * 1000)
            try:
                self._write_metadata_version(version + 1, md)
                return
            except IcebergConflict:
                continue
        raise IcebergConflict("schema evolution lost repeated races")

    def add_column(self, name: str, dtype) -> None:
        from ...spec import data_type as dt  # noqa: F401

        def mutate(schema, md):
            if any(f["name"] == name for f in schema["fields"]):
                raise ValueError(f"column {name!r} already exists")
            sub, last = _spec_to_iceberg_schema(
                dt.StructType((dt.StructField(name, dtype, True),)))
            field = sub["fields"][0]
            base = md.get("last-column-id", 0)

            def shift(obj):
                if isinstance(obj, dict):
                    out = {}
                    for k, v in obj.items():
                        if k in ("id", "element-id", "key-id", "value-id"):
                            out[k] = v + base
                        else:
                            out[k] = shift(v)
                    return out
                if isinstance(obj, list):
                    return [shift(x) for x in obj]
                return obj

            schema["fields"].append(shift(field))
            md["last-column-id"] = base + last

        self._evolve_schema(mutate)

    def rename_column(self, old: str, new: str) -> None:
        def mutate(schema, md):
            for f in schema["fields"]:
                if f["name"] == old:
                    f["name"] = new
                    return
            raise ValueError(f"column {old!r} not found")

        self._evolve_schema(mutate)

    def drop_column(self, name: str) -> None:
        def mutate(schema, md):
            before = len(schema["fields"])
            schema["fields"] = [f for f in schema["fields"]
                                if f["name"] != name]
            if len(schema["fields"]) == before:
                raise ValueError(f"column {name!r} not found")

        self._evolve_schema(mutate)

    def _historical_names(self, md: Optional[dict] = None
                          ) -> Dict[int, List[str]]:
        """field id → candidate source column names, newest schema first.

        A name that EVER belonged to more than one field id is excluded:
        without parquet field-id metadata it is ambiguous which id a
        file's column of that name carries (drop-then-reuse / rename-onto
        -dropped-name scenarios), and the sound answer is null-fill, not
        a guess."""
        md = md if md is not None else self.metadata()
        out: Dict[int, List[str]] = {}
        claimed: Dict[str, set] = {}
        for s in sorted(md.get("schemas", []),
                        key=lambda s: -s.get("schema-id", 0)):
            for f in s.get("fields", []):
                names = out.setdefault(f["id"], [])
                if f["name"] not in names:
                    names.append(f["name"])
                claimed.setdefault(f["name"], set()).add(f["id"])
        return {fid: [n for n in names if len(claimed[n]) == 1]
                for fid, names in out.items()}

    # -- writes ----------------------------------------------------------
    def create(self, table, partition_by: Sequence[str] = ()) -> int:
        from ...columnar.arrow_interop import arrow_type_to_spec
        from ...spec import data_type as dt

        os.makedirs(self.metadata_dir, exist_ok=True)
        st = dt.StructType(tuple(
            dt.StructField(n, arrow_type_to_spec(c.type), True)
            for n, c in zip(table.column_names, table.columns)))
        schema_json, last_column_id = _spec_to_iceberg_schema(st)
        md = {
            "format-version": 2,
            "table-uuid": str(uuid.uuid4()),
            "location": self.path,
            "last-sequence-number": 0,
            "last-updated-ms": int(time.time() * 1000),
            "last-column-id": last_column_id,
            "current-schema-id": 0,
            "schemas": [schema_json],
            "default-spec-id": 0,
            "partition-specs": [{"spec-id": 0, "fields": [
                {"name": c, "transform": "identity",
                 "source-id": next(f["id"] for f in schema_json["fields"]
                                   if f["name"] == c),
                 "field-id": 1000 + i}
                for i, c in enumerate(partition_by)]}],
            "last-partition-id": 1000 + len(partition_by) - 1,
            "default-sort-order-id": 0,
            "sort-orders": [{"order-id": 0, "fields": []}],
            "properties": {},
            "current-snapshot-id": -1,
            "snapshots": [],
            "snapshot-log": [],
            "metadata-log": [],
        }
        self._write_metadata_version(1, md)
        if table.num_rows:
            return self.append(table)
        return 1

    def _write_metadata_version(self, version: int, md: dict):
        # commits add files under data/ and metadata/ without touching
        # the table root's mtime — drop this root's listings explicitly
        # and version the table for the result cache (which also clears
        # root-scoped listings; unrelated tables keep warm entries)
        from ...exec.result_cache import bump_table_version
        bump_table_version(self.path, root=self.path)
        path = self._metadata_path(version)
        tmp = path + f".{uuid.uuid4().hex}.tmp"
        with open(tmp, "w") as f:
            json.dump(md, f)
        try:
            os.link(tmp, path)  # atomic create-if-absent
        except FileExistsError:
            raise IcebergConflict(
                f"concurrent commit of metadata v{version}")
        finally:
            os.unlink(tmp)
        hint_tmp = os.path.join(self.metadata_dir,
                                f".hint.{uuid.uuid4().hex}.tmp")
        with open(hint_tmp, "w") as f:
            f.write(str(version))
        os.replace(hint_tmp, os.path.join(self.metadata_dir,
                                          "version-hint.text"))

    def _partition_columns(self) -> List[str]:
        """Identity-transform column names of the default partition spec."""
        md = self.metadata()
        spec_id = md.get("default-spec-id", 0)
        for spec in md.get("partition-specs", []):
            if spec.get("spec-id") == spec_id:
                return [f["name"] for f in spec.get("fields", [])
                        if f.get("transform") == "identity"]
        return []

    def _write_data_files(self, table) -> List[dict]:
        import pyarrow.parquet as pq

        data_dir = os.path.join(self.path, "data")
        os.makedirs(data_dir, exist_ok=True)
        part_cols = [c for c in self._partition_columns()
                     if c in table.column_names]
        if part_cols and table.num_rows:
            groups: Dict[tuple, List[int]] = {}
            rows = table.select(part_cols).to_pylist()
            for i, row in enumerate(rows):
                groups.setdefault(
                    tuple(row[c] for c in part_cols), []).append(i)
            splits = [({c: (None if v is None else str(v))
                        for c, v in zip(part_cols, key)}, table.take(idxs))
                      for key, idxs in groups.items()]
        else:
            splits = [({}, table)]
        out = []
        for partition, chunk in splits:
            name = f"data/{uuid.uuid4().hex}.parquet"
            fp = os.path.join(self.path, name)
            pq.write_table(chunk, fp)
            out.append({"content": 0, "file_path": name,
                        "file_format": "PARQUET", "partition": partition,
                        "record_count": chunk.num_rows,
                        "file_size_in_bytes": os.path.getsize(fp)})
        return out

    def _commit_snapshot(self, new_entries: List[dict],
                         carry_forward: bool, operation: str,
                         new_content: int = 0,
                         max_retries: int = 10) -> int:
        for _ in range(max_retries):
            version = self._current_version()
            md = self.metadata(version)
            seq = md["last-sequence-number"] + 1
            snap_id = int(uuid.uuid4().int % (1 << 62))
            # added entries inherit the new sequence number; carried
            # entries keep their original one explicitly (spec v2)
            groups: List[Tuple[int, List[dict]]] = []
            added = [{"status": 1, "snapshot_id": snap_id,
                      "sequence_number": None, "data_file": df}
                     for df in new_entries]
            if added:
                groups.append((new_content, added))
            if carry_forward:
                prev = self.snapshot()
                carried_data, carried_del = [], []
                for df, dseq in self._entries(prev):
                    e = {"status": 0, "snapshot_id": snap_id,
                         "sequence_number": dseq, "data_file": df}
                    (carried_del if df.get("content", 0) in (1, 2)
                     else carried_data).append(e)
                if carried_data:
                    groups.append((0, carried_data))
                if carried_del:
                    groups.append((1, carried_del))
            mfiles = []
            for gi, (content, entries) in enumerate(groups):
                manifest_name = f"metadata/{uuid.uuid4().hex}-m{gi}.avro"
                avro_io.write_container(
                    os.path.join(self.path, manifest_name),
                    _MANIFEST_ENTRY_SCHEMA, entries)
                n_added = sum(1 for e in entries if e["status"] == 1)
                mfiles.append({
                    "manifest_path": manifest_name,
                    "manifest_length": os.path.getsize(
                        os.path.join(self.path, manifest_name)),
                    "partition_spec_id": 0, "content": content,
                    "sequence_number": seq, "added_snapshot_id": snap_id,
                    "added_files_count": n_added,
                    "existing_files_count": len(entries) - n_added,
                    "deleted_files_count": 0,
                    "added_rows_count": sum(
                        e["data_file"]["record_count"] for e in entries
                        if e["status"] == 1)})
            mlist_name = f"metadata/snap-{snap_id}.avro"
            avro_io.write_container(
                os.path.join(self.path, mlist_name), _MANIFEST_FILE_SCHEMA,
                mfiles)
            snapshot = {
                "snapshot-id": snap_id,
                "sequence-number": seq,
                "timestamp-ms": int(time.time() * 1000),
                "manifest-list": mlist_name,
                "summary": {"operation": operation},
                "schema-id": md.get("current-schema-id", 0),
            }
            md["snapshots"] = md.get("snapshots", []) + [snapshot]
            md["current-snapshot-id"] = snap_id
            # the main branch tracks the current snapshot (spec v2 refs;
            # "refs": null is a legal on-disk shape from other writers)
            md["refs"] = dict(md.get("refs") or {})
            md["refs"]["main"] = {"snapshot-id": snap_id,
                                  "type": "branch"}
            md["last-sequence-number"] = seq
            md["last-updated-ms"] = snapshot["timestamp-ms"]
            md.setdefault("snapshot-log", []).append(
                {"snapshot-id": snap_id,
                 "timestamp-ms": snapshot["timestamp-ms"]})
            try:
                self._write_metadata_version(version + 1, md)
                return snap_id
            except IcebergConflict:
                continue  # re-read the new base metadata and retry
        raise IcebergConflict("gave up after repeated commit races")

    def _mutate_refs(self, mutate) -> int:
        """Commit a ref-map change with the same re-read-and-retry loop
        as every other metadata writer (and, like them, against the
        LIVE version — never a metadata_location-pinned snapshot)."""
        for _ in range(10):
            version = self._current_version()
            md = self.metadata(version)
            md["refs"] = dict(md.get("refs") or {})
            mutate(md)
            try:
                self._write_metadata_version(version + 1, md)
                return version + 1
            except IcebergConflict:
                continue
        raise IcebergConflict("ref update lost repeated races")

    def set_ref(self, name: str, snapshot_id: Optional[int] = None,
                ref_type: str = "tag") -> int:
        """Create or move a named ref (branch or tag). Defaults to the
        current snapshot. Returns the new metadata version."""
        if ref_type not in ("tag", "branch"):
            raise ValueError("ref type must be 'tag' or 'branch'")

        def mutate(md):
            sid = snapshot_id if snapshot_id is not None else \
                md.get("current-snapshot-id")
            if sid in (None, -1):
                raise ValueError("table has no snapshot to reference")
            if not any(s["snapshot-id"] == sid
                       for s in md.get("snapshots", [])):
                raise ValueError(f"snapshot {sid} not found")
            md["refs"][name] = {"snapshot-id": sid, "type": ref_type}

        return self._mutate_refs(mutate)

    def drop_ref(self, name: str) -> int:
        if name == "main":
            raise ValueError("cannot drop the main branch")

        def mutate(md):
            if name not in md["refs"]:
                raise ValueError(f"unknown ref {name!r}")
            del md["refs"][name]

        return self._mutate_refs(mutate)

    def append(self, table) -> int:
        return self._commit_snapshot(self._write_data_files(table),
                                     carry_forward=True, operation="append")

    def overwrite(self, table) -> int:
        return self._commit_snapshot(self._write_data_files(table),
                                     carry_forward=False,
                                     operation="overwrite")

    # -- row-level deletes (merge-on-read) --------------------------------
    def add_position_deletes(self, deletes: Dict[str, Sequence[int]]) -> int:
        """Commit a position-delete file: {data file_path as stored in the
        metadata: row positions}. Merge-on-read — data files are untouched
        (reference: sail-iceberg deletion content files, spec v2)."""
        import pyarrow as pa
        import pyarrow.parquet as pq

        paths, positions = [], []
        for fp, idxs in deletes.items():
            for i in sorted(idxs):
                paths.append(fp)
                positions.append(int(i))
        name = f"data/{uuid.uuid4().hex}-deletes.parquet"
        full = os.path.join(self.path, name)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        pq.write_table(pa.table({
            "file_path": pa.array(paths, type=pa.string()),
            "pos": pa.array(positions, type=pa.int64())}), full)
        entry = {"content": 1, "file_path": name, "file_format": "PARQUET",
                 "partition": {}, "record_count": len(paths),
                 "file_size_in_bytes": os.path.getsize(full)}
        return self._commit_snapshot([entry], carry_forward=True,
                                     operation="delete", new_content=1)

    def add_equality_deletes(self, keys, columns: Sequence[str]) -> int:
        """Commit an equality-delete file: rows of ``keys`` (a pyarrow
        Table) matching on ``columns`` are deleted from all EARLIER data
        files (delete_seq > data_seq semantics)."""
        import pyarrow.parquet as pq

        name = f"data/{uuid.uuid4().hex}-eq-deletes.parquet"
        full = os.path.join(self.path, name)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        keys = keys.select(list(columns))
        pq.write_table(keys, full)
        by_name = {v: k for k, v in self._field_names_by_id().items()}
        unknown = [c for c in columns if c not in by_name]
        if unknown:
            # narrowing the key would delete rows the caller never targeted
            raise ValueError(
                f"equality-delete key columns not in table schema: {unknown}")
        entry = {"content": 2, "file_path": name, "file_format": "PARQUET",
                 "partition": {}, "record_count": keys.num_rows,
                 "file_size_in_bytes": os.path.getsize(full),
                 "equality_ids": [by_name[c] for c in columns]}
        return self._commit_snapshot([entry], carry_forward=True,
                                     operation="delete", new_content=1)

    def delete_where(self, mask_fn) -> int:
        """Row-level DELETE via position-delete files: ``mask_fn`` maps a
        per-file pyarrow Table to a boolean numpy array (True = delete).
        Re-recording an already-deleted position is a harmless no-op, so
        the raw file rows are passed to ``mask_fn`` unfiltered."""
        import numpy as np
        import pyarrow.parquet as pq

        snap = self.snapshot()
        evolved = len(self.metadata().get("schemas", [])) > 1
        evo_plan = self._evolution_plan(None) if evolved else None
        out: Dict[str, List[int]] = {}
        for df, _dseq in self._entries(snap):
            if df.get("content", 0) != 0:
                continue
            fp = df["file_path"]
            if evolved:
                # current-schema projection: predicates reference the
                # CURRENT column names; row order/count preserved so the
                # recorded positions stay file positions
                t = self._read_evolved(fp, *evo_plan)
            else:
                t = pq.read_table(self._resolve_path(fp))
            dead = np.asarray(mask_fn(t), dtype=bool)
            hits = np.flatnonzero(dead)
            if len(hits):
                out[fp] = [int(i) for i in hits]
        if not out:
            return snap["snapshot-id"] if snap else -1
        return self.add_position_deletes(out)
