"""Minimal Avro object-container-file codec.

Reference role: crates/sail-iceberg/src/io/ (Avro manifest IO, written
from scratch there too — no avro library ships in this environment). This
implements the Avro 1.x binary encoding subset Iceberg manifests use:
records, nullable unions ["null", T], string/bytes/int/long/boolean/
double, arrays, and maps; null codec (no compression).
"""

from __future__ import annotations

import io
import json
import os
import struct
from typing import Any, Dict, List, Optional

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# primitive codecs
# ---------------------------------------------------------------------------

def _zigzag_encode(n: int) -> bytes:
    n = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not (v & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_bytes(out: bytearray, b: bytes):
    out += _zigzag_encode(len(b))
    out += b


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _zigzag_decode(buf)
    return buf.read(n)


# ---------------------------------------------------------------------------
# schema-driven encode/decode
# ---------------------------------------------------------------------------

def _branch_index(schema_union: List, value) -> int:
    for i, br in enumerate(schema_union):
        t = br["type"] if isinstance(br, dict) and "type" in br and \
            not isinstance(br.get("type"), dict) else br
        if value is None and t == "null":
            return i
        if value is not None and t != "null":
            return i
    return 0


def encode_value(out: bytearray, schema, value):
    if isinstance(schema, list):  # union
        idx = _branch_index(schema, value)
        out += _zigzag_encode(idx)
        encode_value(out, schema[idx], value)
        return
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            for f in schema["fields"]:
                encode_value(out, f["type"], value.get(f["name"])
                             if value else None)
            return
        if t == "array":
            items = value or []
            if items:
                out += _zigzag_encode(len(items))
                for it in items:
                    encode_value(out, schema["items"], it)
            out += _zigzag_encode(0)
            return
        if t == "map":
            entries = value or {}
            if entries:
                out += _zigzag_encode(len(entries))
                for k, v in entries.items():
                    _write_bytes(out, str(k).encode())
                    encode_value(out, schema["values"], v)
            out += _zigzag_encode(0)
            return
        if t == "fixed":
            out += value
            return
        encode_value(out, t, value)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.append(1 if value else 0)
        return
    if schema in ("int", "long"):
        out += _zigzag_encode(int(value))
        return
    if schema == "float":
        out += struct.pack("<f", float(value))
        return
    if schema == "double":
        out += struct.pack("<d", float(value))
        return
    if schema == "string":
        _write_bytes(out, str(value).encode())
        return
    if schema == "bytes":
        _write_bytes(out, bytes(value))
        return
    raise ValueError(f"unsupported avro type {schema!r}")


def decode_value(buf: io.BytesIO, schema):
    if isinstance(schema, list):
        idx = _zigzag_decode(buf)
        return decode_value(buf, schema[idx])
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return {f["name"]: decode_value(buf, f["type"])
                    for f in schema["fields"]}
        if t == "array":
            out = []
            while True:
                n = _zigzag_decode(buf)
                if n == 0:
                    break
                if n < 0:
                    _zigzag_decode(buf)  # block byte size
                    n = -n
                for _ in range(n):
                    out.append(decode_value(buf, schema["items"]))
            return out
        if t == "map":
            out = {}
            while True:
                n = _zigzag_decode(buf)
                if n == 0:
                    break
                if n < 0:
                    _zigzag_decode(buf)
                    n = -n
                for _ in range(n):
                    k = _read_bytes(buf).decode()
                    out[k] = decode_value(buf, schema["values"])
            return out
        if t == "fixed":
            return buf.read(schema["size"])
        return decode_value(buf, t)
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) == b"\x01"
    if schema in ("int", "long"):
        return _zigzag_decode(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "string":
        return _read_bytes(buf).decode()
    if schema == "bytes":
        return _read_bytes(buf)
    raise ValueError(f"unsupported avro type {schema!r}")


# ---------------------------------------------------------------------------
# object container files
# ---------------------------------------------------------------------------

def write_container(path: str, schema: dict, records: List[dict],
                    metadata: Optional[Dict[str, bytes]] = None):
    sync = os.urandom(16)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null"}
    for k, v in (metadata or {}).items():
        meta[k] = v if isinstance(v, bytes) else str(v).encode()
    out = bytearray()
    out += MAGIC
    out += _zigzag_encode(len(meta))
    for k, v in meta.items():
        _write_bytes(out, k.encode())
        _write_bytes(out, v)
    out += _zigzag_encode(0)
    out += sync
    block = bytearray()
    for r in records:
        encode_value(block, schema, r)
    out += _zigzag_encode(len(records))
    out += _zigzag_encode(len(block))
    out += block
    out += sync
    with open(path, "wb") as f:
        f.write(out)


def read_container(path: str):
    """Returns (records, metadata)."""
    with open(path, "rb") as f:
        buf = io.BytesIO(f.read())
    if buf.read(4) != MAGIC:
        raise ValueError(f"not an avro container file: {path}")
    meta: Dict[str, bytes] = {}
    while True:
        n = _zigzag_decode(buf)
        if n == 0:
            break
        if n < 0:
            _zigzag_decode(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"])
    codec = meta.get("avro.codec", b"null")
    sync = buf.read(16)
    records = []
    while True:
        head = buf.read(1)
        if not head:
            break
        buf.seek(-1, 1)
        count = _zigzag_decode(buf)
        size = _zigzag_decode(buf)
        blob = buf.read(size)
        if codec == b"deflate":
            import zlib
            blob = zlib.decompress(blob, -15)
        elif codec not in (b"null", b""):
            raise ValueError(f"unsupported avro codec {codec!r}")
        bbuf = io.BytesIO(blob)
        for _ in range(count):
            records.append(decode_value(bbuf, schema))
        if buf.read(16) != sync:
            raise ValueError("avro sync marker mismatch")
    return records, meta
