"""Delta transaction log: commit files, checkpoints, snapshot replay.

Reference role: crates/sail-delta-lake/src/delta_log/ (log listing,
segment replay, checkpoints) and src/spec/ (actions). From scratch against
the public Delta protocol: a table is a directory with `_delta_log/`
containing ordered JSON commits `%020d.json`, optional parquet checkpoints
`%020d.checkpoint.parquet`, and a `_last_checkpoint` pointer.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
import uuid
from typing import Dict, List, Optional, Tuple

LOG_DIR = "_delta_log"
CHECKPOINT_INTERVAL = 10


@dataclasses.dataclass(frozen=True)
class AddFile:
    path: str
    size: int = 0
    partition_values: Tuple[Tuple[str, str], ...] = ()
    modification_time: int = 0
    data_change: bool = True
    stats: Optional[str] = None
    # inline deletion-vector descriptor tuple (sorted key/value pairs), or
    # None — kept hashable for the frozen dataclass
    deletion_vector: Optional[Tuple[Tuple[str, object], ...]] = None

    def dv(self):
        from .deletion_vector import DeletionVector
        return DeletionVector.from_json(
            dict(self.deletion_vector) if self.deletion_vector else None)

    def to_json(self) -> dict:
        return {"add": {
            "path": self.path, "size": self.size,
            "partitionValues": dict(self.partition_values),
            "modificationTime": self.modification_time,
            "dataChange": self.data_change,
            **({"stats": self.stats} if self.stats else {}),
            **({"deletionVector": dict(self.deletion_vector)}
               if self.deletion_vector else {}),
        }}


@dataclasses.dataclass(frozen=True)
class RemoveFile:
    path: str
    deletion_timestamp: int = 0
    data_change: bool = True

    def to_json(self) -> dict:
        return {"remove": {
            "path": self.path, "deletionTimestamp": self.deletion_timestamp,
            "dataChange": self.data_change,
        }}


@dataclasses.dataclass(frozen=True)
class Metadata:
    schema_string: str
    partition_columns: Tuple[str, ...] = ()
    table_id: str = ""
    name: Optional[str] = None
    configuration: Tuple[Tuple[str, str], ...] = ()
    created_time: int = 0

    def to_json(self) -> dict:
        return {"metaData": {
            "id": self.table_id or str(uuid.uuid4()),
            "name": self.name,
            "format": {"provider": "parquet", "options": {}},
            "schemaString": self.schema_string,
            "partitionColumns": list(self.partition_columns),
            "configuration": dict(self.configuration),
            "createdTime": self.created_time or int(time.time() * 1000),
        }}


@dataclasses.dataclass(frozen=True)
class Protocol:
    min_reader_version: int = 1
    min_writer_version: int = 2

    def to_json(self) -> dict:
        return {"protocol": {
            "minReaderVersion": self.min_reader_version,
            "minWriterVersion": self.min_writer_version,
        }}


@dataclasses.dataclass
class Snapshot:
    version: int
    metadata: Optional[Metadata]
    protocol: Optional[Protocol]
    files: Dict[str, AddFile]
    timestamp_ms: int = 0
    # Unexpired remove tombstones (path -> RemoveFile). External readers
    # (VACUUM, retention) need these preserved across checkpoints.
    tombstones: Dict[str, "RemoveFile"] = dataclasses.field(
        default_factory=dict)

    @property
    def schema(self):
        from ...spec.schema_json import schema_from_json
        return schema_from_json(json.loads(self.metadata.schema_string))

    def _raw_fields(self) -> List[dict]:
        """Parsed top-level schema fields, cached — DML loops call the
        mapping properties once per data file."""
        cached = self.__dict__.get("_raw_fields_cache")
        if cached is None:
            cached = [] if self.metadata is None else \
                json.loads(self.metadata.schema_string).get("fields", [])
            self.__dict__["_raw_fields_cache"] = cached
        return cached

    @property
    def column_mapping_mode(self) -> str:
        """delta.columnMapping.mode: none | name | id. Both non-none modes
        store data under per-field physical names; "id" additionally pins
        parquet field ids (we resolve by physical name, which the protocol
        guarantees is also present in id mode)."""
        conf = dict(self.metadata.configuration) if self.metadata else {}
        return conf.get("delta.columnMapping.mode", "none")

    @property
    def physical_names(self) -> Dict[str, str]:
        """Top-level logical field name -> physical parquet column name
        (identity map when column mapping is off)."""
        cached = self.__dict__.get("_physical_names_cache")
        if cached is None:
            mapped = self.column_mapping_mode != "none"
            cached = {}
            for f in self._raw_fields():
                meta = f.get("metadata") or {}
                phys = meta.get("delta.columnMapping.physicalName") \
                    if mapped else None
                cached[f["name"]] = phys or f["name"]
            self.__dict__["_physical_names_cache"] = cached
        return cached

    def rename_to_logical(self, table):
        """Physical parquet column names -> logical schema names, top
        level AND nested struct fields (list elements included)."""
        inv = {p: l for l, p in self.physical_names.items()}
        table = table.rename_columns(
            [inv.get(n, n) for n in table.column_names])
        if self.column_mapping_mode == "none":
            return table
        by_logical = {f["name"]: f for f in self._raw_fields()}
        cols, changed = [], False
        for name, col in zip(table.column_names, table.columns):
            fj = by_logical.get(name)
            new = col
            if fj is not None and isinstance(fj.get("type"), dict):
                new = _map_nested(col, fj["type"], to_logical=True)
            changed = changed or new is not col
            cols.append(new)
        if not changed:
            return table
        import pyarrow as pa
        return pa.table(dict(zip(table.column_names, cols)))

    def rename_to_physical(self, table):
        """Logical -> physical, the write-side mirror of
        ``rename_to_logical`` (nested struct fields included)."""
        if self.column_mapping_mode == "none":
            return table
        by_logical = {f["name"]: f for f in self._raw_fields()}
        cols, names = [], []
        for name, col in zip(table.column_names, table.columns):
            fj = by_logical.get(name)
            if fj is None:
                names.append(name)
                cols.append(col)
                continue
            names.append(self.physical_names.get(name, name))
            cols.append(_map_nested(col, fj["type"], to_logical=False)
                        if isinstance(fj.get("type"), dict) else col)
        import pyarrow as pa
        return pa.table(dict(zip(names, cols)))

    def partition_raw(self, pv: Dict[str, str], col: str):
        """partitionValues lookup: keys are physical under column
        mapping, logical otherwise (foreign writers vary)."""
        return pv.get(self.physical_names.get(col, col), pv.get(col))

    @property
    def generation_expressions(self) -> Dict[str, str]:
        """Logical column -> SQL generation expression
        (delta.generationExpression field metadata; the writer computes
        missing generated columns from it — ref
        crates/sail-delta-lake/src/table/features.rs GeneratedColumns)."""
        cached = self.__dict__.get("_generation_cache")
        if cached is None:
            cached = {}
            for f in self._raw_fields():
                meta = f.get("metadata") or {}
                expr = meta.get("delta.generationExpression")
                if expr:
                    cached[f["name"]] = expr
            self.__dict__["_generation_cache"] = cached
        return cached


def _field_phys(fj: dict) -> str:
    meta = fj.get("metadata") or {}
    return meta.get("delta.columnMapping.physicalName") or fj["name"]


def _map_nested(col, type_json, to_logical: bool):
    """Rebuild a (possibly chunked) arrow array so nested struct field
    names follow the schema JSON: physical -> logical on read,
    logical -> physical on write. Structs and lists recurse; map values
    and other nesting pass through unchanged (returned as-is)."""
    import pyarrow as pa

    if not isinstance(type_json, dict):
        return col
    kind = type_json.get("type")
    if isinstance(col, pa.ChunkedArray):
        col = col.combine_chunks()
    if kind == "struct":
        st = col.type
        children, names = [], []
        for fj in type_json.get("fields", []):
            src = _field_phys(fj) if to_logical else fj["name"]
            dst = fj["name"] if to_logical else _field_phys(fj)
            idx = st.get_field_index(src)
            if idx < 0:
                continue
            children.append(_map_nested(col.field(idx), fj.get("type"),
                                        to_logical))
            names.append(dst)
        if not children:
            return col
        return pa.StructArray.from_arrays(
            children, names=names,
            mask=col.is_null() if col.null_count else None)
    if kind == "array":
        if col.offset != 0 and col.null_count:
            # ListArray.from_arrays rejects a null bitmap on a sliced
            # array; take() compacts to offset 0
            col = col.take(pa.array(range(len(col)), type=pa.int64()))
        inner = _map_nested(col.values, type_json.get("elementType"),
                            to_logical)
        if inner is col.values:
            return col
        return pa.ListArray.from_arrays(
            col.offsets, inner,
            mask=col.is_null() if col.null_count else None)
    return col


_MAP_FIELDS = ("partitionValues", "configuration", "options")


def _maps_to_dicts(v):
    """pyarrow map columns come back as lists of (k, v) pairs; convert the
    known Delta map fields back to dicts."""
    if isinstance(v, dict):
        out = {}
        for k, x in v.items():
            if k in _MAP_FIELDS and isinstance(x, list):
                out[k] = dict(x)
            else:
                out[k] = _maps_to_dicts(x)
        return out
    return v


_DEFAULT_RETENTION_MS = 7 * 24 * 3600 * 1000  # delta default: 1 week

_INTERVAL_UNITS_MS = {
    "millisecond": 1, "second": 1000, "minute": 60_000, "hour": 3_600_000,
    "day": 86_400_000, "week": 7 * 86_400_000,
}


def _retention_ms(snapshot: "Snapshot") -> int:
    """deletedFileRetentionDuration from table config ("interval N unit")."""
    if snapshot.metadata is None:
        return _DEFAULT_RETENTION_MS
    conf = dict(snapshot.metadata.configuration)
    raw = conf.get("delta.deletedFileRetentionDuration", "")
    parts = raw.lower().split()
    if len(parts) == 3 and parts[0] == "interval":
        try:
            n = int(parts[1])
            unit = parts[2].rstrip("s")
            if unit in _INTERVAL_UNITS_MS:
                return n * _INTERVAL_UNITS_MS[unit]
        except ValueError:
            pass
    return _DEFAULT_RETENTION_MS


def _commit_path(log_dir: str, version: int) -> str:
    return os.path.join(log_dir, f"{version:020d}.json")


def _checkpoint_path(log_dir: str, version: int) -> str:
    return os.path.join(log_dir, f"{version:020d}.checkpoint.parquet")


class DeltaLog:
    def __init__(self, table_path: str):
        self.table_path = table_path
        self.log_dir = os.path.join(table_path, LOG_DIR)

    def exists(self) -> bool:
        return os.path.isdir(self.log_dir) and bool(self.versions())

    def versions(self) -> List[int]:
        if not os.path.isdir(self.log_dir):
            return []
        out = []
        for name in os.listdir(self.log_dir):
            if name.endswith(".json") and len(name) == 25:
                try:
                    out.append(int(name[:20]))
                except ValueError:
                    pass
        return sorted(out)

    def latest_version(self) -> Optional[int]:
        vs = self.versions()
        return vs[-1] if vs else None

    # -- action IO -------------------------------------------------------
    def read_commit(self, version: int) -> List[dict]:
        path = _commit_path(self.log_dir, version)
        with open(path, "r", encoding="utf-8") as f:
            return [json.loads(line) for line in f if line.strip()]

    def write_commit_atomic(self, version: int, actions: List[dict]):
        """Atomically create the commit file for ``version``; raises
        FileExistsError when another writer got there first (the optimistic
        concurrency primitive). The content is written to a temp file first
        and linked into place, so a concurrent reader/loser can never
        observe a partially-written commit."""
        os.makedirs(self.log_dir, exist_ok=True)
        path = _commit_path(self.log_dir, version)
        data = "\n".join(json.dumps(a, separators=(",", ":"))
                         for a in actions) + "\n"
        tmp = os.path.join(self.log_dir,
                           f".{version:020d}.{uuid.uuid4().hex}.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        try:
            os.link(tmp, path)  # atomic create-if-absent with full content
        finally:
            os.unlink(tmp)

    # -- checkpoints -----------------------------------------------------
    def last_checkpoint(self) -> Optional[int]:
        p = os.path.join(self.log_dir, "_last_checkpoint")
        if not os.path.exists(p):
            return None
        with open(p, "r", encoding="utf-8") as f:
            return int(json.load(f)["version"])

    # Classic Delta checkpoint layout: one row per action, one nullable
    # struct column per action type (protocol / metaData / add), so
    # standard Delta readers can load the checkpoint.
    _CP_SCHEMA = None

    @staticmethod
    def _checkpoint_schema():
        import pyarrow as pa

        if DeltaLog._CP_SCHEMA is None:
            str_map = pa.map_(pa.string(), pa.string())
            DeltaLog._CP_SCHEMA = pa.schema([
                ("protocol", pa.struct([
                    ("minReaderVersion", pa.int32()),
                    ("minWriterVersion", pa.int32())])),
                ("metaData", pa.struct([
                    ("id", pa.string()), ("name", pa.string()),
                    ("description", pa.string()),
                    ("format", pa.struct([("provider", pa.string()),
                                          ("options", str_map)])),
                    ("schemaString", pa.string()),
                    ("partitionColumns", pa.list_(pa.string())),
                    ("configuration", str_map),
                    ("createdTime", pa.int64())])),
                ("add", pa.struct([
                    ("path", pa.string()),
                    ("partitionValues", str_map),
                    ("size", pa.int64()),
                    ("modificationTime", pa.int64()),
                    ("dataChange", pa.bool_()),
                    ("stats", pa.string()),
                    ("deletionVector", pa.struct([
                        ("storageType", pa.string()),
                        ("pathOrInlineDv", pa.string()),
                        ("offset", pa.int32()),
                        ("sizeInBytes", pa.int32()),
                        ("cardinality", pa.int64())]))])),
                ("remove", pa.struct([
                    ("path", pa.string()),
                    ("deletionTimestamp", pa.int64()),
                    ("dataChange", pa.bool_())])),
            ])
        return DeltaLog._CP_SCHEMA

    def write_checkpoint(self, snapshot: Snapshot):
        import pyarrow as pa
        import pyarrow.parquet as pq

        rows = []
        if snapshot.protocol is not None:
            rows.append({"protocol": snapshot.protocol.to_json()["protocol"]})
        if snapshot.metadata is not None:
            m = snapshot.metadata.to_json()["metaData"]
            m["format"]["options"] = list(m["format"]["options"].items())
            m["configuration"] = list(m["configuration"].items())
            rows.append({"metaData": m})
        for add in snapshot.files.values():
            a = add.to_json()["add"]
            a["partitionValues"] = list(a["partitionValues"].items())
            a.setdefault("stats", None)
            a.setdefault("deletionVector", None)
            rows.append({"add": a})
        cutoff = int(time.time() * 1000) - _retention_ms(snapshot)
        for rm in snapshot.tombstones.values():
            # expire tombstones past the retention window (Delta protocol:
            # checkpoints only carry unexpired removes)
            if rm.deletion_timestamp >= cutoff:
                rows.append({"remove": rm.to_json()["remove"]})
        schema = self._checkpoint_schema()
        cols = {name: [r.get(name) for r in rows] for name in schema.names}
        table = pa.table({n: pa.array(cols[n], type=schema.field(n).type)
                          for n in schema.names})
        pq.write_table(table, _checkpoint_path(self.log_dir,
                                               snapshot.version))
        tmp = os.path.join(self.log_dir, "_last_checkpoint.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": snapshot.version, "size": len(rows)}, f)
        os.replace(tmp, os.path.join(self.log_dir, "_last_checkpoint"))

    # -- V2 checkpoints ---------------------------------------------------
    # Reference: crates/sail-delta-lake/src/checkpoint/ — a manifest file
    # `<version>.checkpoint.<uuid>.parquet` holding protocol/metaData +
    # `sidecar` actions pointing at `_sidecars/<uuid>.parquet` files that
    # carry the add/remove actions.

    def write_checkpoint_v2(self, snapshot: Snapshot,
                            actions_per_sidecar: int = 100_000):
        import uuid as _uuid

        import pyarrow as pa
        import pyarrow.parquet as pq

        schema = self._checkpoint_schema()
        side_dir = os.path.join(self.log_dir, "_sidecars")
        os.makedirs(side_dir, exist_ok=True)
        file_rows = []
        for add in snapshot.files.values():
            a = add.to_json()["add"]
            a["partitionValues"] = list(a["partitionValues"].items())
            a.setdefault("stats", None)
            a.setdefault("deletionVector", None)
            file_rows.append({"add": a})
        cutoff = int(time.time() * 1000) - _retention_ms(snapshot)
        for rm in snapshot.tombstones.values():
            if rm.deletion_timestamp >= cutoff:
                file_rows.append({"remove": rm.to_json()["remove"]})
        sidecars = []
        for i in range(0, max(len(file_rows), 1), actions_per_sidecar):
            chunk = file_rows[i:i + actions_per_sidecar]
            name = f"{_uuid.uuid4().hex}.parquet"
            path = os.path.join(side_dir, name)
            cols = {n: [r.get(n) for r in chunk] for n in ("add", "remove")}
            pq.write_table(pa.table(
                {n: pa.array(cols[n], type=schema.field(n).type)
                 for n in ("add", "remove")}), path)
            sidecars.append({"path": name,
                             "sizeInBytes": os.path.getsize(path),
                             "modificationTime": int(time.time() * 1000)})
        manifest_rows = []
        if snapshot.protocol is not None:
            manifest_rows.append(
                {"protocol": snapshot.protocol.to_json()["protocol"]})
        if snapshot.metadata is not None:
            m = snapshot.metadata.to_json()["metaData"]
            m["format"]["options"] = list(m["format"]["options"].items())
            m["configuration"] = list(m["configuration"].items())
            manifest_rows.append({"metaData": m})
        for sc in sidecars:
            manifest_rows.append({"sidecar": sc})
        manifest_rows.append({"checkpointMetadata":
                              {"version": snapshot.version}})
        str_map = pa.map_(pa.string(), pa.string())
        mschema = pa.schema([
            schema.field("protocol"), schema.field("metaData"),
            ("sidecar", pa.struct([("path", pa.string()),
                                   ("sizeInBytes", pa.int64()),
                                   ("modificationTime", pa.int64())])),
            ("checkpointMetadata", pa.struct([("version", pa.int64()),
                                              ("tags", str_map)])),
        ])
        cols = {n: [r.get(n) for r in manifest_rows] for n in mschema.names}
        name = f"{snapshot.version:020d}.checkpoint.{_uuid.uuid4().hex}" \
               f".parquet"
        pq.write_table(pa.table(
            {n: pa.array(cols[n], type=mschema.field(n).type)
             for n in mschema.names}), os.path.join(self.log_dir, name))
        tmp = os.path.join(self.log_dir, "_last_checkpoint.tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": snapshot.version,
                       "size": len(file_rows) + len(manifest_rows),
                       "v2Checkpoint": {"path": name}}, f)
        os.replace(tmp, os.path.join(self.log_dir, "_last_checkpoint"))

    def _v2_manifest(self, version: int) -> Optional[str]:
        p = os.path.join(self.log_dir, "_last_checkpoint")
        if os.path.exists(p):
            with open(p, "r", encoding="utf-8") as f:
                lc = json.load(f)
            v2 = lc.get("v2Checkpoint")
            if v2 and lc.get("version") == version:
                return os.path.join(self.log_dir, v2["path"])
        import glob as _glob
        hits = sorted(_glob.glob(os.path.join(
            self.log_dir, f"{version:020d}.checkpoint.*.parquet")))
        return hits[-1] if hits else None

    def read_checkpoint(self, version: int) -> List[dict]:
        import pyarrow.parquet as pq

        classic = _checkpoint_path(self.log_dir, version)
        if os.path.exists(classic):
            table = pq.read_table(classic)
        else:
            manifest = self._v2_manifest(version)
            if manifest is None:
                raise FileNotFoundError(
                    f"no checkpoint for version {version}")
            return self._read_checkpoint_v2(manifest)
        out: List[dict] = []
        for row in table.to_pylist():
            for kind in ("protocol", "metaData", "add", "remove", "txn"):
                v = row.get(kind)
                if v is None:
                    continue
                v = _maps_to_dicts(v)
                out.append({kind: v})
        return out

    def _read_checkpoint_v2(self, manifest_path: str) -> List[dict]:
        import pyarrow.parquet as pq

        out: List[dict] = []
        sidecar_paths = []
        for row in pq.read_table(manifest_path).to_pylist():
            for kind in ("protocol", "metaData", "add", "remove"):
                v = row.get(kind)
                if v is not None:
                    out.append({kind: _maps_to_dicts(v)})
            sc = row.get("sidecar")
            if sc is not None and sc.get("path"):
                sidecar_paths.append(sc["path"])
        for name in sidecar_paths:
            path = name if os.path.isabs(name) else \
                os.path.join(self.log_dir, "_sidecars", name)
            for row in pq.read_table(path).to_pylist():
                for kind in ("add", "remove"):
                    v = row.get(kind)
                    if v is not None:
                        out.append({kind: _maps_to_dicts(v)})
        return out

    # -- replay ----------------------------------------------------------
    def snapshot(self, version: Optional[int] = None,
                 timestamp_ms: Optional[int] = None) -> Snapshot:
        versions = self.versions()
        if not versions:
            raise FileNotFoundError(
                f"not a Delta table (no {LOG_DIR}): {self.table_path}")
        if timestamp_ms is not None and version is None:
            version = self._version_at_timestamp(versions, timestamp_ms)
        if version is None:
            version = versions[-1]
        if version not in versions:
            raise ValueError(f"version {version} not in Delta log "
                             f"(have {versions[0]}..{versions[-1]})")
        start = 0
        snap = Snapshot(version, None, None, {})
        cp = self.last_checkpoint()
        if cp is not None and cp <= version:
            for action in self.read_checkpoint(cp):
                self._apply(snap, action)
            start = cp + 1
        for v in versions:
            if start <= v <= version:
                for action in self.read_commit(v):
                    self._apply(snap, action)
        snap.version = version
        snap.timestamp_ms = int(os.path.getmtime(
            _commit_path(self.log_dir, version)) * 1000)
        return snap

    def _version_at_timestamp(self, versions: List[int], ts_ms: int) -> int:
        best = None
        for v in versions:
            mtime = os.path.getmtime(_commit_path(self.log_dir, v)) * 1000
            if mtime <= ts_ms:
                best = v
        if best is None:
            raise ValueError(f"no Delta version at or before timestamp "
                             f"{ts_ms}")
        return best

    @staticmethod
    def _apply(snap: Snapshot, action: dict):
        if "metaData" in action:
            m = action["metaData"]
            snap.metadata = Metadata(
                m["schemaString"], tuple(m.get("partitionColumns", ())),
                m.get("id", ""), m.get("name"),
                tuple(sorted((m.get("configuration") or {}).items())),
                m.get("createdTime", 0))
        elif "protocol" in action:
            p = action["protocol"]
            snap.protocol = Protocol(p.get("minReaderVersion", 1),
                                     p.get("minWriterVersion", 2))
        elif "add" in action:
            a = action["add"]
            snap.tombstones.pop(a["path"], None)
            dv = a.get("deletionVector")
            snap.files[a["path"]] = AddFile(
                a["path"], a.get("size", 0),
                tuple(sorted((a.get("partitionValues") or {}).items())),
                a.get("modificationTime", 0), a.get("dataChange", True),
                a.get("stats"),
                tuple(sorted(dv.items())) if dv else None)
        elif "remove" in action:
            r = action["remove"]
            snap.files.pop(r["path"], None)
            snap.tombstones[r["path"]] = RemoveFile(
                r["path"], r.get("deletionTimestamp", 0),
                r.get("dataChange", True))
        # commitInfo / txn are informational for replay
