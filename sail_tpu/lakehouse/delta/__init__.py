from .table import DeltaTable  # noqa: F401
from .log import DeltaLog, Snapshot  # noqa: F401
from .transaction import CommitConflict, Transaction  # noqa: F401
