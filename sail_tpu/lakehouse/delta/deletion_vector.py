"""Delta deletion vectors (merge-on-read row-level deletes).

Reference role: crates/sail-delta-lake/src/deletion_vector/ — the DV
bitmap format, z85 inline encoding, and AddFile descriptor plumbing.
Implemented from the PUBLIC formats:

- bitmap bytes = ``[magic 1681511377 u32 LE][portable RoaringTreemap]``
  where the treemap (RoaringFormatSpec "portable" 64-bit layout) is
  ``u64 LE bitmap-count`` then per entry ``u32 LE high-key`` + a standard
  32-bit roaring bitmap serialization (cookie 12346, array containers for
  cardinality <= 4096, bitset containers above — run containers never
  emitted).
- inline descriptors carry the bytes z85-encoded in ``pathOrInlineDv``
  with ``storageType "i"``.

Self-describing and self-consistent for this engine's reader/writer;
checksummed on-disk DV files (storageType "u"/"p") are not emitted.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

DV_MAGIC = 1681511377
_SERIAL_COOKIE_NO_RUN = 12346
_ARRAY_MAX = 4096

_Z85_CHARS = ("0123456789abcdefghijklmnopqrstuvwxyz"
              "ABCDEFGHIJKLMNOPQRSTUVWXYZ.-:+=^!/*?&<>()[]{}@%$#")
_Z85_INDEX = {c: i for i, c in enumerate(_Z85_CHARS)}


def z85_encode(data: bytes) -> str:
    """ZeroMQ base85. Delta pads to a 4-byte multiple with zero bytes and
    records the true size in ``sizeInBytes``."""
    pad = (-len(data)) % 4
    data = data + b"\0" * pad
    out = []
    for i in range(0, len(data), 4):
        v = int.from_bytes(data[i:i + 4], "big")
        chunk = []
        for _ in range(5):
            chunk.append(_Z85_CHARS[v % 85])
            v //= 85
        out.extend(reversed(chunk))
    return "".join(out)


def z85_decode(text: str, size: Optional[int] = None) -> bytes:
    out = bytearray()
    for i in range(0, len(text), 5):
        v = 0
        for c in text[i:i + 5]:
            v = v * 85 + _Z85_INDEX[c]
        out.extend(v.to_bytes(4, "big"))
    return bytes(out[:size]) if size is not None else bytes(out)


# ---------------------------------------------------------------------------
# roaring serialization
# ---------------------------------------------------------------------------

def _serialize_bitmap32(values: np.ndarray) -> bytes:
    """Standard 32-bit roaring serialization (no run containers)."""
    highs = (values >> 16).astype(np.uint32)
    lows = (values & 0xFFFF).astype(np.uint16)
    keys, starts = np.unique(highs, return_index=True)
    bounds = list(starts) + [len(values)]
    out = bytearray()
    out += struct.pack("<II", _SERIAL_COOKIE_NO_RUN, len(keys))
    containers = []
    for i, key in enumerate(keys):
        vals = lows[bounds[i]:bounds[i + 1]]
        card = len(vals)
        out += struct.pack("<HH", int(key), card - 1)
        if card <= _ARRAY_MAX:
            containers.append(vals.astype("<u2").tobytes())
        else:
            bits = np.zeros(1024, dtype="<u8")
            idx = vals.astype(np.uint32)
            np.bitwise_or.at(bits, idx >> 6,
                             np.left_shift(np.uint64(1),
                                           (idx & 63).astype(np.uint64)))
            containers.append(bits.tobytes())
    # offsets section (present in the no-run format)
    offset = len(out) + 4 * len(keys)
    for c in containers:
        out += struct.pack("<I", offset)
        offset += len(c)
    for c in containers:
        out += c
    return bytes(out)


def _deserialize_bitmap32(buf: bytes, pos: int):
    cookie, = struct.unpack_from("<I", buf, pos)
    base = pos
    if cookie == _SERIAL_COOKIE_NO_RUN:
        n, = struct.unpack_from("<I", buf, pos + 4)
        pos += 8
        headers = []
        for _ in range(n):
            key, card_m1 = struct.unpack_from("<HH", buf, pos)
            headers.append((key, card_m1 + 1))
            pos += 4
        pos += 4 * n  # offsets
        values: List[np.ndarray] = []
        for key, card in headers:
            if card <= _ARRAY_MAX:
                vals = np.frombuffer(buf, dtype="<u2", count=card,
                                     offset=pos).astype(np.uint32)
                pos += 2 * card
            else:
                bits = np.frombuffer(buf, dtype="<u8", count=1024,
                                     offset=pos)
                pos += 8192
                vals = np.nonzero(
                    np.unpackbits(bits.view(np.uint8), bitorder="little")
                )[0].astype(np.uint32)
            values.append((np.uint32(key) << np.uint32(16)) | vals)
        out = np.concatenate(values) if values else \
            np.empty(0, dtype=np.uint32)
        return out, pos
    if (cookie & 0xFFFF) == 12347:  # run-container format (read-only)
        n = (cookie >> 16) + 1
        run_bitmap_len = (n + 7) // 8
        run_flags = np.unpackbits(
            np.frombuffer(buf, dtype=np.uint8, count=run_bitmap_len,
                          offset=pos + 4), bitorder="little")[:n]
        pos += 4 + run_bitmap_len
        headers = []
        for _ in range(n):
            key, card_m1 = struct.unpack_from("<HH", buf, pos)
            headers.append((key, card_m1 + 1))
            pos += 4
        if n >= 4:
            # RoaringFormatSpec: with the run cookie the offset section is
            # present whenever there are >= NO_OFFSET_THRESHOLD (4)
            # containers, regardless of which are run-encoded
            pos += 4 * n
        values = []
        for i, (key, card) in enumerate(headers):
            if run_flags[i]:
                n_runs, = struct.unpack_from("<H", buf, pos)
                pos += 2
                vals_list = []
                for _ in range(n_runs):
                    start, length = struct.unpack_from("<HH", buf, pos)
                    pos += 4
                    vals_list.append(np.arange(start, start + length + 1,
                                               dtype=np.uint32))
                vals = np.concatenate(vals_list) if vals_list else \
                    np.empty(0, dtype=np.uint32)
            elif card <= _ARRAY_MAX:
                vals = np.frombuffer(buf, dtype="<u2", count=card,
                                     offset=pos).astype(np.uint32)
                pos += 2 * card
            else:
                bits = np.frombuffer(buf, dtype="<u8", count=1024,
                                     offset=pos)
                pos += 8192
                vals = np.nonzero(
                    np.unpackbits(bits.view(np.uint8), bitorder="little")
                )[0].astype(np.uint32)
            values.append((np.uint32(key) << np.uint32(16)) | vals)
        out = np.concatenate(values) if values else \
            np.empty(0, dtype=np.uint32)
        return out, pos
    raise ValueError(f"unsupported roaring cookie {cookie} at {base}")


def serialize_dv(row_indices: Sequence[int]) -> bytes:
    """Sorted distinct row indices → Delta DV bitmap bytes."""
    values = np.unique(np.asarray(row_indices, dtype=np.uint64))
    highs = (values >> np.uint64(32)).astype(np.uint32)
    lows = (values & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    keys, starts = np.unique(highs, return_index=True)
    bounds = list(starts) + [len(values)]
    out = bytearray(struct.pack("<I", DV_MAGIC))
    out += struct.pack("<Q", len(keys))
    for i, key in enumerate(keys):
        out += struct.pack("<I", int(key))
        out += _serialize_bitmap32(lows[bounds[i]:bounds[i + 1]])
    return bytes(out)


def deserialize_dv(data: bytes) -> np.ndarray:
    magic, = struct.unpack_from("<I", data, 0)
    if magic != DV_MAGIC:
        raise ValueError(f"bad deletion-vector magic {magic}")
    n_maps, = struct.unpack_from("<Q", data, 4)
    pos = 12
    parts = []
    for _ in range(n_maps):
        high, = struct.unpack_from("<I", data, pos)
        pos += 4
        lows, pos = _deserialize_bitmap32(data, pos)
        parts.append((np.uint64(high) << np.uint64(32)) |
                     lows.astype(np.uint64))
    return np.concatenate(parts) if parts else np.empty(0, dtype=np.uint64)


# ---------------------------------------------------------------------------
# descriptor
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class DeletionVector:
    """The AddFile ``deletionVector`` descriptor (inline storage)."""

    storage_type: str      # "i" inline
    path_or_inline: str    # z85 of the bitmap bytes
    size_in_bytes: int
    cardinality: int
    offset: Optional[int] = None

    @classmethod
    def from_row_indices(cls, row_indices: Sequence[int]) -> "DeletionVector":
        data = serialize_dv(row_indices)
        return cls("i", z85_encode(data), len(data),
                   len(np.unique(np.asarray(row_indices))))

    def row_indices(self) -> np.ndarray:
        if self.storage_type != "i":
            raise ValueError(
                f"unsupported DV storage type {self.storage_type!r}")
        return deserialize_dv(z85_decode(self.path_or_inline,
                                         self.size_in_bytes))

    def to_json(self) -> dict:
        out = {"storageType": self.storage_type,
               "pathOrInlineDv": self.path_or_inline,
               "sizeInBytes": self.size_in_bytes,
               "cardinality": self.cardinality}
        if self.offset is not None:
            out["offset"] = self.offset
        return out

    @classmethod
    def from_json(cls, d: Optional[dict]) -> Optional["DeletionVector"]:
        if not d:
            return None
        return cls(d.get("storageType", "i"), d.get("pathOrInlineDv", ""),
                   int(d.get("sizeInBytes", 0)), int(d.get("cardinality", 0)),
                   d.get("offset"))
