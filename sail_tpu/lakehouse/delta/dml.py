"""Delta row-level DML as an engine pipeline.

Reference role: crates/sail-delta-lake/src/physical_plan/planner/
op_{delete,update,merge}.rs:105-330 — DML planned as discovery → scan with
file metadata columns → join/per-clause projection (ENGINE-executed, so
the compute runs on device) → TARGETED file rewrite (only touched files)
→ conflict-checked commit. The copy-on-write variant rewrites touched
files; DELETE additionally supports the merge-on-read deletion-vector
variant (build_merge_plan_mor) when the table sets
``delta.enableDeletionVectors``.

Metadata-column design (datasource.rs:23-42 in the reference): the target
scan carries ``__fid__`` (file ordinal) and ``__rid__`` (global row id);
match sets come back as row-id arrays, are claimed first-clause-wins, and
group by file so unmatched files are never rewritten.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

from ...spec import expression as ex
from ...spec import plan as sp
from .log import RemoveFile
from .table import DeltaTable, _parse_partition_value
from .transaction import Transaction


def _read_file_with_partitions(dt_table: DeltaTable, snap, add) -> pa.Table:
    import pyarrow.parquet as pq
    from ...columnar.arrow_interop import spec_type_to_arrow

    t = snap.rename_to_logical(
        pq.read_table(os.path.join(dt_table.path, add.path)))
    dv = add.dv()
    if dv is not None and dv.cardinality:
        deleted = dv.row_indices()
        keep = np.ones(t.num_rows, dtype=bool)
        keep[deleted[deleted < t.num_rows].astype(np.int64)] = False
        t = t.filter(pa.array(keep))
    pv = dict(add.partition_values)
    for c in snap.metadata.partition_columns:
        f = snap.schema.field(c)
        at = spec_type_to_arrow(f.data_type)
        raw = snap.partition_raw(pv, c)
        val = None if raw is None else _parse_partition_value(raw, at)
        t = t.append_column(c, pa.array([val] * t.num_rows, type=at))
    # column order per declared schema
    return t.select([f.name for f in snap.schema.fields])


class DeltaDml:
    """DELETE / UPDATE / MERGE against one Delta table."""

    def __init__(self, session, table_name: Tuple[str, ...]):
        self.session = session
        entry, dt_table = session._delta_entry(table_name)
        self.entry = entry
        self.table = dt_table
        self.snap = dt_table.snapshot()
        self.schema = self.snap.schema

    # -- shared plumbing -------------------------------------------------
    def _run(self, plan):
        return self.session._execute_query(plan)

    def _dv_enabled(self) -> bool:
        conf = dict(self.snap.metadata.configuration)
        return conf.get("delta.enableDeletionVectors", "").lower() == "true"

    def _regen(self, table: pa.Table) -> pa.Table:
        """Recompute every generated column from its expression — rows an
        UPDATE/MERGE changed must keep the generation invariant, and
        recomputation is idempotent for untouched rows."""
        gen = [c for c in self.snap.generation_expressions
               if c in table.column_names]
        if not gen or not table.num_rows:
            return table
        order = table.column_names
        out = self.table._compute_generated(
            table.drop_columns(gen), self.snap, session=self.session)
        return out.select(order)

    def _target_with_meta(self):
        """(per-file tables, concatenated table + __fid__/__rid__ meta
        columns, fid row offsets)."""
        files = list(self.snap.files.values())
        per_file: List[pa.Table] = []
        offsets = [0]
        for add in files:
            t = _read_file_with_partitions(self.table, self.snap, add)
            per_file.append(t)
            offsets.append(offsets[-1] + t.num_rows)
        if per_file:
            whole = pa.concat_tables(per_file, promote_options="permissive")
        else:
            from ...columnar.arrow_interop import spec_type_to_arrow
            whole = pa.table({f.name: pa.array(
                [], type=spec_type_to_arrow(f.data_type))
                for f in self.schema.fields})
        n = whole.num_rows
        fid = np.repeat(np.arange(len(per_file), dtype=np.int64),
                        [t.num_rows for t in per_file]) if per_file else \
            np.empty(0, dtype=np.int64)
        whole = whole.append_column("__fid__", pa.array(fid, pa.int64()))
        whole = whole.append_column(
            "__rid__", pa.array(np.arange(n), pa.int64()))
        return files, per_file, whole, np.asarray(offsets)

    def _arrow_target_schema(self) -> pa.Schema:
        from ...columnar.arrow_interop import spec_type_to_arrow
        return pa.schema([(f.name, spec_type_to_arrow(f.data_type))
                          for f in self.schema.fields])

    def _rewrite_touched(self, tx: Transaction, files, per_file,
                        deletes: np.ndarray, updates: Optional[pa.Table],
                        offsets: np.ndarray):
        """Targeted copy-on-write: rewrite ONLY files containing a deleted
        or updated row; untouched files keep their AddFile untouched."""
        n_total = offsets[-1] if len(offsets) else 0
        touched_rows = np.zeros(int(n_total), dtype=bool)
        if deletes.size:
            touched_rows[deletes] = True
        upd_rids = np.empty(0, dtype=np.int64)
        if updates is not None and updates.num_rows:
            upd_rids = np.asarray(updates.column("__rid__"))
            touched_rows[upd_rids] = True
        touched_fids = np.unique(
            np.searchsorted(offsets, np.nonzero(touched_rows)[0],
                            side="right") - 1)
        now = int(time.time() * 1000)
        target_schema = self._arrow_target_schema()
        part_cols = list(self.snap.metadata.partition_columns)
        for fid in touched_fids:
            add = files[fid]
            t = per_file[fid]
            lo, hi = int(offsets[fid]), int(offsets[fid + 1])
            survive = ~touched_rows[lo:hi]
            kept = t.filter(pa.array(survive))
            parts = [kept.cast(target_schema, safe=False)]
            if upd_rids.size:
                in_file = (upd_rids >= lo) & (upd_rids < hi)
                if in_file.any():
                    upd_here = updates.filter(pa.array(in_file)) \
                        .drop_columns(["__rid__"])
                    parts.append(upd_here.cast(target_schema, safe=False))
            new_table = self._regen(pa.concat_tables(parts))
            tx.read_files.add(add.path)
            tx.remove_file(RemoveFile(add.path, now))
            if new_table.num_rows:
                for new_add in self.table._write_data_files(
                        new_table, part_cols,
                        self.table._mapping(self.snap)):
                    tx.add_file(new_add)

    # -- DELETE ----------------------------------------------------------
    def delete(self, condition: Optional[ex.Expr]) -> pa.Table:
        mode = "dv" if self._dv_enabled() else "cow"
        if condition is None:
            version, deleted = self.table.delete_where(
                lambda tb: pa.array([False] * tb.num_rows), mode=mode)
        else:
            def keep_mask(tb):
                pred = self.session._eval_predicate(
                    tb, condition).column(0)
                hit = np.asarray(pred.fill_null(False).to_pylist(),
                                 dtype=bool) if tb.num_rows else \
                    np.zeros(0, dtype=bool)
                return pa.array(~hit)
            version, deleted = self.table.delete_where(keep_mask, mode=mode)
        return pa.table({"num_affected_rows":
                         pa.array([deleted], type=pa.int64())})

    # -- UPDATE ----------------------------------------------------------
    def update(self, cmd) -> pa.Table:
        """Targeted copy-on-write UPDATE: each file is read DV-aware;
        files with no hits keep their AddFile; touched files are rewritten
        with CASE WHEN cond THEN expr ELSE col END projections run by the
        engine."""
        session = self.session
        schema = self.schema
        assigns = {path[-1].lower(): expr
                   for path, expr in cmd.assignments}
        cond = cmd.condition
        tx = Transaction(self.table.log, self.snap.version, "UPDATE")
        now = int(time.time() * 1000)
        updated = 0
        part_cols = list(self.snap.metadata.partition_columns)
        for add in list(self.snap.files.values()):
            t = _read_file_with_partitions(self.table, self.snap, add)
            if cond is not None:
                pred = session._eval_predicate(t, cond).column(0)
                nhit = int(np.asarray(
                    pred.fill_null(False)).sum()) if t.num_rows else 0
                if not nhit:
                    continue
            else:
                nhit = t.num_rows
            exprs = []
            for f in schema.fields:
                col = ex.Attribute((f.name,))
                if f.name.lower() in assigns:
                    new = assigns[f.name.lower()]
                    val = new if cond is None else \
                        ex.CaseWhen(((cond, new),), col)
                    exprs.append(ex.Alias(ex.Cast(val, f.data_type),
                                          (f.name,)))
                else:
                    exprs.append(ex.Alias(col, (f.name,)))
            rewritten = self._regen(self._run(
                sp.Project(sp.LocalRelation(t), tuple(exprs))))
            tx.read_files.add(add.path)
            tx.remove_file(RemoveFile(add.path, now))
            for new_add in self.table._write_data_files(
                    rewritten, part_cols,
                    self.table._mapping(self.snap)):
                tx.add_file(new_add)
            updated += nhit
        if updated:
            tx.commit()
        return pa.table({"num_affected_rows":
                         pa.array([updated], type=pa.int64())})

    # -- MERGE -----------------------------------------------------------
    def merge(self, cmd: sp.MergeInto) -> pa.Table:
        session = self.session
        schema = self.schema
        col_names = [f.name for f in schema.fields]
        files, per_file, t_arrow, offsets = self._target_with_meta()
        t_alias = (cmd.target_alias or cmd.target[-1])
        target_plan = sp.SubqueryAlias(sp.LocalRelation(t_arrow), t_alias)

        if isinstance(cmd.source, sp.SubqueryAlias):
            s_alias = cmd.source.alias
        elif isinstance(cmd.source, sp.ReadNamedTable):
            s_alias = cmd.source.name[-1]
        else:
            s_alias = "__src__"
        s_arrow = self._run(cmd.source)
        s_cols = list(s_arrow.column_names)
        s_arrow = s_arrow.append_column(
            "__srid__", pa.array(np.arange(s_arrow.num_rows), pa.int64()))
        source_plan = sp.SubqueryAlias(sp.LocalRelation(s_arrow), s_alias)
        join = sp.Join(target_plan, source_plan, "inner", cmd.condition)

        if cmd.matched_actions:
            # cardinality check: a target row may be modified by at most
            # one source row; duplicates that satisfy no matched clause
            # are allowed (Delta semantics)
            card_base: sp.QueryPlan = join
            conds = [a.condition for a in cmd.matched_actions]
            if all(c is not None for c in conds):
                disj = conds[0]
                for c in conds[1:]:
                    disj = ex.Function("or", (disj, c))
                card_base = sp.Filter(join, disj)
            dup = self._run(sp.Filter(
                sp.Aggregate(card_base, (ex.col("__rid__"),),
                             (ex.col("__rid__"),
                              ex.Alias(ex.Function("count", ()), ("c",)))),
                ex.Function(">", (ex.col("c"), ex.lit(1)))))
            if dup.num_rows:
                raise ValueError(
                    "MERGE cardinality violation: a target row matched "
                    "multiple source rows")

        n_rows = t_arrow.num_rows
        claimed = np.zeros(n_rows, dtype=bool)
        delete_rids: List[np.ndarray] = []
        update_tables: List[pa.Table] = []
        n_updates = 0

        def claim(rids: np.ndarray) -> np.ndarray:
            fresh = ~claimed[rids]
            claimed[rids[fresh]] = True
            return fresh

        for action in cmd.matched_actions:
            base: sp.QueryPlan = join
            if action.condition is not None:
                base = sp.Filter(join, action.condition)
            if action.action == "delete":
                rids = np.asarray(self._run(sp.Project(
                    base, (ex.col("__rid__"),))).column(0),
                    dtype=np.int64)
                delete_rids.append(rids[claim(rids)])
            elif action.action in ("update", "update_star"):
                if action.action == "update_star":
                    assigns = {c.lower(): ex.Attribute((s_alias, c))
                               for c in s_cols}
                else:
                    assigns = {path[-1].lower(): e
                               for path, e in action.assignments}
                exprs = [ex.Alias(ex.col("__rid__"), ("__rid__",))]
                for c, f in zip(col_names, schema.fields):
                    e = assigns.get(c.lower())
                    e = ex.Attribute((t_alias, c)) if e is None else \
                        ex.Cast(e, f.data_type)
                    exprs.append(ex.Alias(e, (c,)))
                rows = self._run(sp.Project(base, tuple(exprs)))
                rids = np.asarray(rows.column("__rid__"), dtype=np.int64)
                fresh = claim(rids)
                kept = rows.filter(pa.array(fresh))
                update_tables.append(kept)
                n_updates += kept.num_rows
            else:
                raise ValueError(
                    f"unsupported matched action {action.action!r}")

        # not-matched source rows → inserts (first satisfied clause wins)
        insert_tables: List[pa.Table] = []
        claimed_src = np.zeros(s_arrow.num_rows, dtype=bool)
        anti = sp.Join(source_plan, target_plan, "anti", cmd.condition)
        target_schema = self._arrow_target_schema()
        for action in cmd.not_matched_actions:
            base = anti
            if action.condition is not None:
                base = sp.Filter(anti, action.condition)
            if action.action == "insert_star":
                src_low = {c.lower(): c for c in s_cols}
                assigns = {c.lower(): ex.Attribute(
                    (s_alias, src_low[c.lower()]))
                    for c in col_names if c.lower() in src_low}
            elif action.action == "insert":
                assigns = {path[-1].lower(): e
                           for path, e in action.assignments}
            else:
                raise ValueError(
                    f"unsupported not-matched action {action.action!r}")
            exprs = [ex.Alias(ex.Attribute((s_alias, "__srid__")),
                              ("__srid__",))]
            for c, f in zip(col_names, schema.fields):
                e = assigns.get(c.lower())
                e = ex.lit(None) if e is None else ex.Cast(e, f.data_type)
                exprs.append(ex.Alias(e, (c,)))
            rows = self._run(sp.Project(base, tuple(exprs)))
            srids = np.asarray(rows.column("__srid__"), dtype=np.int64)
            fresh = ~claimed_src[srids]
            claimed_src[srids[fresh]] = True
            ins = rows.filter(pa.array(fresh)).drop_columns(["__srid__"])
            # generated columns the clause did not assign must be
            # computed, not inserted as NULL (same path as append)
            gen = self.snap.generation_expressions
            unassigned = [c for c in col_names
                          if c in gen and c.lower() not in assigns]
            if unassigned and ins.num_rows:
                ins = self.table._compute_generated(
                    ins.drop_columns(unassigned), self.snap,
                    session=self.session)
                ins = ins.select(list(col_names))
            insert_tables.append(ins.cast(target_schema, safe=False))

        # not matched by source → update/delete target rows with no match
        if cmd.not_matched_by_source_actions:
            t_anti = sp.Join(target_plan, source_plan, "anti",
                             cmd.condition)
            for action in cmd.not_matched_by_source_actions:
                base = t_anti
                if action.condition is not None:
                    base = sp.Filter(t_anti, action.condition)
                if action.action == "delete":
                    rids = np.asarray(self._run(sp.Project(
                        base, (ex.col("__rid__"),))).column(0),
                        dtype=np.int64)
                    delete_rids.append(rids[claim(rids)])
                elif action.action == "update":
                    assigns = {path[-1].lower(): e
                               for path, e in action.assignments}
                    exprs = [ex.Alias(ex.col("__rid__"), ("__rid__",))]
                    for c, f in zip(col_names, schema.fields):
                        e = assigns.get(c.lower())
                        e = ex.Attribute((c,)) if e is None \
                            else ex.Cast(e, f.data_type)
                        exprs.append(ex.Alias(e, (c,)))
                    rows = self._run(sp.Project(base, tuple(exprs)))
                    rids = np.asarray(rows.column("__rid__"),
                                      dtype=np.int64)
                    fresh = claim(rids)
                    kept = rows.filter(pa.array(fresh))
                    update_tables.append(kept)
                    n_updates += kept.num_rows
                else:
                    raise ValueError(
                        f"unsupported not-matched-by-source action "
                        f"{action.action!r}")

        deletes = np.concatenate(delete_rids) if delete_rids else \
            np.empty(0, dtype=np.int64)
        updates = None
        if update_tables:
            norm = []
            meta = pa.schema([("__rid__", pa.int64())])
            want = pa.schema(list(meta) + list(target_schema))
            for t in update_tables:
                norm.append(t.select([f.name for f in want]).cast(
                    want, safe=False))
            updates = pa.concat_tables(norm)
        inserts = pa.concat_tables(insert_tables) if insert_tables else None
        n_inserts = inserts.num_rows if inserts is not None else 0

        if deletes.size == 0 and n_updates == 0 and n_inserts == 0:
            return _merge_metrics(0, 0, 0)

        tx = Transaction(self.table.log, self.snap.version, "MERGE")
        # matching reads the whole table: concurrent writers adding
        # matching rows must conflict
        tx.read_whole_table = True
        self._rewrite_touched(tx, files, per_file, deletes, updates,
                              offsets)
        if n_inserts:
            for add in self.table._write_data_files(
                    inserts, list(self.snap.metadata.partition_columns),
                    self.table._mapping(self.snap)):
                tx.add_file(add)
        tx.commit()
        return _merge_metrics(n_updates, int(deletes.size), n_inserts)


def _merge_metrics(updated: int, deleted: int, inserted: int) -> pa.Table:
    return pa.table({
        "num_affected_rows": pa.array([updated + deleted + inserted],
                                      type=pa.int64()),
        "num_updated_rows": pa.array([updated], type=pa.int64()),
        "num_deleted_rows": pa.array([deleted], type=pa.int64()),
        "num_inserted_rows": pa.array([inserted], type=pa.int64()),
    })
