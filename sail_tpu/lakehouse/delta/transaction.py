"""Optimistic-concurrency transactions with a conflict checker.

Reference role: crates/sail-delta-lake/src/transaction/ (commit protocol)
and src/transaction/conflict_checker.rs:321-480 (the winner-vs-loser
commit compatibility rules). The commit primitive is atomic
create-if-absent of the next `%020d.json`; on a lost race, the
transaction replays the winners' actions and decides whether its own
operation still commutes.
"""

from __future__ import annotations

import time
from typing import List, Optional

from .log import AddFile, DeltaLog, Metadata, Protocol, RemoveFile


class CommitConflict(Exception):
    """The transaction cannot be re-applied on top of the winning commits."""


class Transaction:
    def __init__(self, log: DeltaLog, read_version: Optional[int],
                 operation: str = "WRITE"):
        self.log = log
        self.read_version = read_version
        self.operation = operation
        self.actions: List[dict] = []
        self._adds: List[AddFile] = []
        self._removes: List[RemoveFile] = []
        self._metadata: Optional[Metadata] = None
        self._protocol: Optional[Protocol] = None
        # what this transaction read, for conflict detection
        self.read_whole_table = False
        self.read_files: set = set()

    # -- staging ---------------------------------------------------------
    def set_protocol(self, protocol: Protocol):
        self._protocol = protocol

    def set_metadata(self, metadata: Metadata):
        self._metadata = metadata

    def add_file(self, add: AddFile):
        self._adds.append(add)

    def remove_file(self, remove: RemoveFile):
        self._removes.append(remove)

    # -- commit ----------------------------------------------------------
    def _assemble(self) -> List[dict]:
        actions: List[dict] = [{"commitInfo": {
            "timestamp": int(time.time() * 1000),
            "operation": self.operation,
            "engineInfo": "sail-tpu",
        }}]
        if self._protocol is not None:
            actions.append(self._protocol.to_json())
        if self._metadata is not None:
            actions.append(self._metadata.to_json())
        actions.extend(r.to_json() for r in self._removes)
        actions.extend(a.to_json() for a in self._adds)
        return actions

    def commit(self, max_retries: int = 15) -> int:
        """Returns the committed version."""
        attempt_version = (self.read_version + 1
                           if self.read_version is not None else 0)
        for _ in range(max_retries):
            try:
                self.log.write_commit_atomic(attempt_version,
                                             self._assemble())
            except FileExistsError:
                self._check_conflicts(attempt_version)
                attempt_version += 1
                continue
            self._maybe_checkpoint(attempt_version)
            # commits add data files under nested partition directories
            # without moving the table root's mtime — drop the root's
            # file listings and version the table for the result cache
            import os as _os
            from ...exec.result_cache import bump_table_version
            root = _os.path.dirname(self.log.log_dir)
            bump_table_version(root, root=root)
            return attempt_version
        raise CommitConflict(
            f"gave up after {max_retries} commit attempts")

    def _check_conflicts(self, winner_version: int):
        """Replay the winning commit and decide whether this transaction's
        operation still applies (reference: conflict_checker.rs rules)."""
        winner_actions = self.log.read_commit(winner_version)
        winner_removed = set()
        winner_added = set()
        winner_metadata = False
        winner_protocol = False
        for a in winner_actions:
            if "remove" in a:
                winner_removed.add(a["remove"]["path"])
            elif "add" in a:
                winner_added.add(a["add"]["path"])
            elif "metaData" in a:
                winner_metadata = True
            elif "protocol" in a:
                winner_protocol = True
        if winner_protocol or (self._protocol is not None):
            raise CommitConflict("concurrent protocol change")
        if winner_metadata or (self._metadata is not None
                               and self.read_version is not None):
            raise CommitConflict("concurrent metadata change")
        # files we intend to remove must still exist
        my_removes = {r.path for r in self._removes}
        if my_removes & winner_removed:
            raise CommitConflict(
                "concurrent delete of the same files "
                f"({sorted(my_removes & winner_removed)[:3]})")
        # if we read the whole table (overwrite/delete/merge), any winner
        # data change invalidates the read
        if self.read_whole_table and (winner_added or winner_removed):
            raise CommitConflict(
                "concurrent update while rewriting the table")
        # files we read must not have been removed under us
        if self.read_files & winner_removed:
            raise CommitConflict("concurrent delete of files read by this "
                                 "transaction")
        # blind appends commute — retry at the next version

    def _maybe_checkpoint(self, version: int):
        from .log import CHECKPOINT_INTERVAL
        if version > 0 and version % CHECKPOINT_INTERVAL == 0:
            try:
                self.log.write_checkpoint(self.log.snapshot(version))
            except Exception:  # noqa: BLE001 — checkpoint is best-effort
                pass
