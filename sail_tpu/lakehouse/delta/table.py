"""DeltaTable: open/read/time-travel/append/overwrite/delete.

Reference role: crates/sail-delta-lake/src/table/mod.rs:80-272 (open/
load/time travel) and the write pipelines
(src/physical_plan/planner/op_{write,delete}.rs) collapsed to arrow-level
operations: data files are parquet written via pyarrow, partitioned
Hive-style by the metadata's partitionColumns.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

from .log import AddFile, DeltaLog, Metadata, Protocol, RemoveFile, Snapshot
from .transaction import Transaction


def _stats_for(table) -> str:
    import pyarrow.compute as pc

    stats: Dict[str, object] = {"numRecords": table.num_rows}
    min_v: Dict[str, object] = {}
    max_v: Dict[str, object] = {}
    null_c: Dict[str, object] = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            null_c[name] = col.null_count
            if table.num_rows and col.null_count < table.num_rows and \
                    not str(col.type).startswith(("struct", "list", "map",
                                                  "binary")):
                mn = pc.min(col).as_py()
                mx = pc.max(col).as_py()
                for d, v in ((min_v, mn), (max_v, mx)):
                    if hasattr(v, "isoformat"):
                        v = v.isoformat()
                    elif type(v).__name__ == "Decimal":
                        v = float(v)
                    d[name] = v
        except Exception:  # noqa: BLE001 — stats are best-effort
            continue
    stats["minValues"] = min_v
    stats["maxValues"] = max_v
    stats["nullCount"] = null_c
    return json.dumps(stats)


class DeltaTable:
    def __init__(self, path: str):
        self.path = path
        self.log = DeltaLog(path)

    # -- open / read -----------------------------------------------------
    @staticmethod
    def exists(path: str) -> bool:
        return DeltaLog(path).exists()

    def snapshot(self, version: Optional[int] = None,
                 timestamp_ms: Optional[int] = None) -> Snapshot:
        return self.log.snapshot(version, timestamp_ms)

    def to_arrow(self, version: Optional[int] = None,
                 timestamp_ms: Optional[int] = None,
                 columns: Optional[Sequence[str]] = None):
        import pyarrow as pa
        import pyarrow.parquet as pq
        from ...columnar.arrow_interop import spec_type_to_arrow

        snap = self.snapshot(version, timestamp_ms)
        schema = snap.schema
        # column-mapping mode: data files carry physical names, and the
        # partitionValues keys of add actions are physical; the metadata's
        # partitionColumns list stays logical (Delta PROTOCOL.md)
        pmap = snap.physical_names          # logical -> physical
        part_cols = list(snap.metadata.partition_columns)
        tables = []
        for add in snap.files.values():
            fpath = os.path.join(self.path, add.path)
            want = None
            if columns is not None:
                want = [pmap.get(c, c) for c in columns
                        if c not in part_cols]
            t = snap.rename_to_logical(pq.read_table(fpath, columns=want))
            dv = add.dv()
            if dv is not None and dv.cardinality:
                import numpy as np
                deleted = dv.row_indices()
                keep = np.ones(t.num_rows, dtype=bool)
                keep[deleted[deleted < t.num_rows].astype(np.int64)] = False
                t = t.filter(pa.array(keep))
            pv = dict(add.partition_values)
            for c in part_cols:
                if columns is not None and c not in columns:
                    continue
                f = schema.field(c)
                at = spec_type_to_arrow(f.data_type)
                raw = snap.partition_raw(pv, c)
                val = None if raw is None else _parse_partition_value(raw, at)
                t = t.append_column(
                    c, pa.array([val] * t.num_rows, type=at))
            tables.append(t)
        if not tables:
            fields = [(f.name, spec_type_to_arrow(f.data_type))
                      for f in schema.fields
                      if columns is None or f.name in columns]
            return pa.table({n: pa.array([], type=t) for n, t in fields})
        out = pa.concat_tables(tables, promote_options="permissive")
        if columns is not None:
            out = out.select([c for c in columns if c in out.column_names])
        return out

    def history(self) -> List[dict]:
        out = []
        for v in reversed(self.log.versions()):
            info = {"version": v}
            for a in self.log.read_commit(v):
                if "commitInfo" in a:
                    info.update(a["commitInfo"])
            out.append(info)
        return out

    # -- writes ----------------------------------------------------------
    def _write_data_files(self, table, partition_by: Sequence[str],
                          mapping: Optional["Snapshot"] = None
                          ) -> List[AddFile]:
        import pyarrow.parquet as pq

        if mapping is not None:
            # column mapping: data files (incl. nested struct fields),
            # stats keys, partition dirs and partitionValues keys all
            # use physical names
            table = mapping.rename_to_physical(table)
            pmap = mapping.physical_names
            partition_by = [pmap.get(c, c) for c in partition_by]
        adds: List[AddFile] = []
        now = int(time.time() * 1000)
        if not partition_by:
            name = f"part-{uuid.uuid4().hex}.snappy.parquet"
            fpath = os.path.join(self.path, name)
            os.makedirs(self.path, exist_ok=True)
            pq.write_table(table, fpath)
            adds.append(AddFile(name, os.path.getsize(fpath), (), now, True,
                                _stats_for(table)))
            return adds
        import pyarrow.compute as pc

        keys = table.select(list(partition_by))
        combos = keys.group_by(list(partition_by)).aggregate([]).to_pylist()
        for combo in combos:
            mask = None
            for c, v in combo.items():
                m = pc.is_null(table.column(c)) if v is None else \
                    pc.equal(table.column(c), v)
                mask = m if mask is None else pc.and_(mask, m)
            part = table.filter(mask).drop_columns(list(partition_by))
            reldir = "/".join(
                f"{c}={_format_partition_value(combo[c])}"
                for c in partition_by)
            os.makedirs(os.path.join(self.path, reldir), exist_ok=True)
            name = f"{reldir}/part-{uuid.uuid4().hex}.snappy.parquet"
            fpath = os.path.join(self.path, name)
            pq.write_table(part, fpath)
            adds.append(AddFile(
                name, os.path.getsize(fpath),
                tuple(sorted((c, _format_partition_value(combo[c]))
                             for c in partition_by)),
                now, True, _stats_for(part)))
        return adds

    def _metadata_for(self, table, partition_by: Sequence[str]) -> Metadata:
        from ...spec.schema_json import type_to_json
        from ...columnar.arrow_interop import arrow_type_to_spec
        from ...spec import data_type as dt

        st = dt.StructType(tuple(
            dt.StructField(n, arrow_type_to_spec(c.type), True)
            for n, c in zip(table.column_names, table.columns)))
        return Metadata(json.dumps(type_to_json(st)), tuple(partition_by))

    def create(self, table, partition_by: Sequence[str] = ()) -> int:
        tx = Transaction(self.log, None, "CREATE TABLE AS SELECT")
        tx.set_protocol(Protocol())
        tx.set_metadata(self._metadata_for(table, partition_by))
        for add in self._write_data_files(table, partition_by):
            tx.add_file(add)
        return tx.commit()

    def _compute_generated(self, table, snap, session=None):
        """Fill in generated columns the writer did not supply by
        evaluating each delta.generationExpression over the input batch
        with the engine (ref: sail-delta-lake table features
        GeneratedColumns). Caller-supplied values are passed through
        unvalidated."""
        missing = {c: e for c, e in snap.generation_expressions.items()
                   if c not in table.column_names}
        if not missing:
            return table
        s = session if session is not None else _gen_session()
        view = f"__delta_gen_{uuid.uuid4().hex[:8]}"
        s.createDataFrame(table).createOrReplaceTempView(view)
        try:
            sel = ", ".join(f"({e}) AS {c}" for c, e in missing.items())
            return s.sql(f"SELECT *, {sel} FROM {view}").toArrow()
        finally:
            s.catalog.dropTempView(view)

    def _mapping(self, snap) -> Optional["Snapshot"]:
        """The snapshot itself when column mapping is active (it carries
        the nested-aware physical<->logical transforms), else None."""
        return snap if snap.column_mapping_mode != "none" else None

    def append(self, table) -> int:
        snap = self.snapshot()
        table = self._compute_generated(table, snap)
        tx = Transaction(self.log, snap.version, "WRITE")
        for add in self._write_data_files(
                table, snap.metadata.partition_columns,
                self._mapping(snap)):
            tx.add_file(add)
        return tx.commit()

    def overwrite(self, table) -> int:
        snap = self.snapshot()
        table = self._compute_generated(table, snap)
        tx = Transaction(self.log, snap.version, "WRITE")
        tx.read_whole_table = True
        now = int(time.time() * 1000)
        for path in snap.files:
            tx.remove_file(RemoveFile(path, now))
        for add in self._write_data_files(
                table, snap.metadata.partition_columns,
                self._mapping(snap)):
            tx.add_file(add)
        return tx.commit()

    def delete_where(self, mask_fn, mode: str = "cow") -> Tuple[int, int]:
        """Row-level DELETE: ``mask_fn(table) -> bool mask of rows to
        KEEP``. Returns (version, deleted_rows).

        mode="cow" rewrites touched files (copy-on-write); mode="dv"
        writes a deletion vector on each touched file instead — the
        merge-on-read plan of the reference's build_merge_plan_mor
        (crates/sail-delta-lake/src/physical_plan/planner/op_merge.rs)."""
        import numpy as np
        import pyarrow.parquet as pq

        snap = self.snapshot()
        tx = Transaction(self.log, snap.version, "DELETE")
        now = int(time.time() * 1000)
        deleted = 0
        part_cols = list(snap.metadata.partition_columns)
        for add in list(snap.files.values()):
            t = snap.rename_to_logical(
                pq.read_table(os.path.join(self.path, add.path)))
            full = t
            if part_cols:
                import pyarrow as pa
                from ...columnar.arrow_interop import spec_type_to_arrow
                pv = dict(add.partition_values)
                for c in part_cols:
                    f = snap.schema.field(c)
                    at = spec_type_to_arrow(f.data_type)
                    val = _parse_partition_value(
                        snap.partition_raw(pv, c), at)
                    full = full.append_column(
                        c, pa.array([val] * full.num_rows, type=at))
            existing_dv = add.dv()
            prior = existing_dv.row_indices() if existing_dv is not None \
                else np.empty(0, dtype=np.uint64)
            keep = np.asarray(mask_fn(full))
            # rows already deleted by a DV stay deleted regardless of mask
            if prior.size:
                keep = keep.copy()
                keep[prior[prior < len(keep)].astype(np.int64)] = True
                live_mask = np.ones(full.num_rows, dtype=bool)
                live_mask[prior[prior < full.num_rows].astype(np.int64)] = \
                    False
            else:
                live_mask = np.ones(full.num_rows, dtype=bool)
            newly = (~keep) & live_mask
            n_new = int(newly.sum())
            if n_new == 0:
                continue  # file untouched
            tx.read_files.add(add.path)
            deleted += n_new
            if mode == "dv":
                from .deletion_vector import DeletionVector
                all_deleted = np.union1d(prior,
                                         np.nonzero(newly)[0]
                                         .astype(np.uint64))
                dv = DeletionVector.from_row_indices(all_deleted)
                tx.add_file(AddFile(
                    add.path, add.size, add.partition_values, now, True,
                    add.stats, tuple(sorted(dv.to_json().items()))))
                continue
            tx.remove_file(RemoveFile(add.path, now))
            kept = full.filter(pa_array_bool(keep & live_mask))
            if kept.num_rows:
                for new_add in self._write_data_files(
                        kept, snap.metadata.partition_columns,
                        self._mapping(snap)):
                    tx.add_file(new_add)
        if deleted == 0:
            return snap.version, 0
        return tx.commit(), deleted


_GEN_SESSION = None


def _gen_session():
    """One lazily-built session for generated-column evaluation when the
    write comes from the table API directly (engine writes pass their
    own live session instead)."""
    global _GEN_SESSION
    if _GEN_SESSION is None:
        from ...session import SparkSession
        _GEN_SESSION = SparkSession({"spark.sail.execution.mesh": "off"})
    return _GEN_SESSION


def pa_array_bool(mask):
    import pyarrow as pa
    return pa.array(mask.tolist() if hasattr(mask, "tolist") else mask,
                    type=pa.bool_())


def _format_partition_value(v) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    if isinstance(v, bool):
        return "true" if v else "false"
    if hasattr(v, "isoformat"):
        return v.isoformat(sep=" ") if hasattr(v, "hour") else v.isoformat()
    return str(v)


def _parse_partition_value(raw: Optional[str], at):
    import pyarrow as pa

    if raw is None or raw == "__HIVE_DEFAULT_PARTITION__":
        return None
    if pa.types.is_boolean(at):
        return raw == "true"
    if pa.types.is_integer(at):
        return int(raw)
    if pa.types.is_floating(at):
        return float(raw)
    if pa.types.is_date(at):
        import datetime
        return datetime.date.fromisoformat(raw)
    if pa.types.is_timestamp(at):
        import datetime
        return datetime.datetime.fromisoformat(raw)
    if pa.types.is_decimal(at):
        import decimal
        return decimal.Decimal(raw)
    return raw
