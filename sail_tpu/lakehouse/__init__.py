"""Lakehouse table formats (Delta Lake, Iceberg) — from scratch.

Reference role: crates/sail-delta-lake, crates/sail-iceberg (both built
from scratch in the reference too; SURVEY.md §2.6).
"""
