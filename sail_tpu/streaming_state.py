"""Incremental keyed state for streaming aggregation.

Reference role: the keyed state stores behind Sail's (and Spark's)
stateful streaming operators — per-key partial aggregates updated from
each micro-batch's delta instead of re-aggregating the whole retained
input every trigger, with a changelog that rides the Arrow state
checkpoint so recovery replays only what changed since the last
snapshot.

Shape:

- :func:`analyze_plan` decides whether a streaming plan is eligible for
  incremental state: exactly one ``Aggregate``, every aggregate function
  mergeable (``sum``/``count``/``min``/``max`` — a partial over the
  delta batch folds losslessly into the running partial), no
  ``HAVING``/grouping sets/DISTINCT, and no ``session_window`` grouping
  (sessions merge across batches, so they stay on the whole-buffer
  path). The per-epoch delta runs the SAME ``Aggregate`` node through
  the normal (jitted) engine over just the new slice; only the fold is
  host-side, and it is O(delta keys), not O(state).
- :class:`KeyedStateStore` holds ``key tuple → folded values`` plus a
  per-key event-time high-water mark (``__wm_ts``) for watermark
  eviction, tracks the keys changed/evicted since the last checkpoint,
  and serializes either a full snapshot or a changelog delta as an
  Arrow IPC table. Changelog entries carry the FULLY FOLDED values, so
  recovery replay is last-write-wins — no re-folding, no ordering
  hazards beyond epoch order.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import pyarrow as pa

from .spec import expression as ex
from .spec import plan as sp

#: aggregate functions whose partials fold losslessly across epochs;
#: the value is the fold rule applied per output column
MERGEABLE = {
    "sum": "sum",
    "count": "sum",
    "min": "min",
    "max": "max",
}

#: hidden per-key event-time high-water mark column (watermark eviction)
WM_COLUMN = "__wm_ts"
#: changelog-only tombstone flag column
DELETED_COLUMN = "__deleted"


@dataclasses.dataclass
class AggSpec:
    """Analysis of a streaming plan's single Aggregate node."""

    agg: sp.Aggregate
    #: per output column of the aggregate's result: None = group key
    #: (carried, not folded), else a MERGEABLE fold rule
    merge_kinds: Tuple[Optional[str], ...]

    @property
    def key_positions(self) -> Tuple[int, ...]:
        return tuple(i for i, k in enumerate(self.merge_kinds)
                     if k is None)


def _expr_contains_function(expr, names) -> bool:
    if isinstance(expr, ex.Function) and expr.name.lower() in names:
        return True
    if dataclasses.is_dataclass(expr):
        for f in dataclasses.fields(expr):
            v = getattr(expr, f.name)
            vs = v if isinstance(v, tuple) else (v,)
            for item in vs:
                if isinstance(item, ex.Expr) and \
                        _expr_contains_function(item, names):
                    return True
    return False


#: node types above the Aggregate that map rows independently — safe to
#: run over a changed-keys-only slice of the state. Anything else
#: (Sort+Limit, Deduplicate, joins, set ops, …) computes over the WHOLE
#: result, so feeding it partial state would emit wrong rows.
PER_ROW_ABOVE = (sp.Project, sp.Filter, sp.SubqueryAlias, sp.WithColumns,
                 sp.WithColumnsRenamed, sp.Drop, sp.ToSchema)


def _ancestors(plan, target) -> Optional[List[object]]:
    """Nodes strictly above ``target`` on its root path (by identity),
    or None when ``target`` is not in the tree."""
    if plan is target:
        return []
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            vs = v if isinstance(v, tuple) else (v,)
            for item in vs:
                if isinstance(item, sp.QueryPlan):
                    below = _ancestors(item, target)
                    if below is not None:
                        return [plan] + below
    return None


def _find_aggregates(plan) -> List[object]:
    out: List[object] = []
    if isinstance(plan, (sp.Aggregate, sp.Deduplicate)):
        out.append(plan)
    if dataclasses.is_dataclass(plan):
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            vs = v if isinstance(v, tuple) else (v,)
            for item in vs:
                if isinstance(item, sp.QueryPlan):
                    out.extend(_find_aggregates(item))
    return out


def session_window_gap_seconds(plan) -> Optional[float]:
    """Static ``session_window`` gap of the plan's aggregate grouping,
    or None when the plan has no session window / the gap is dynamic.
    The whole-buffer path widens its row-eviction horizon by this much:
    a row may still extend a session until the watermark is a full gap
    past it."""
    from .streaming import parse_delay
    for node in _find_aggregates(plan):
        if not isinstance(node, sp.Aggregate):
            continue
        for g in node.group:
            expr = g.child if isinstance(g, ex.Alias) else g
            if isinstance(expr, ex.Function) and \
                    expr.name.lower() == "session_window" and \
                    len(expr.args) == 2:
                gap = expr.args[1]
                # parser literals nest (expression Literal wrapping the
                # spec Literal): unwrap until a scalar surfaces
                value = getattr(gap, "value", None)
                while value is not None and \
                        not isinstance(value, (str, int, float)):
                    value = getattr(value, "value", None)
                if isinstance(value, str):
                    try:
                        return parse_delay(value)
                    except (ValueError, IndexError):
                        return None
                if isinstance(value, (int, float)):
                    # numeric literal gaps are rejected at resolve time
                    # (Spark semantics); treat as unknown here
                    return None
                return None  # dynamic (per-row) gap: no safe horizon
    return None


def analyze_plan(plan, changed_keys_only: bool = False) -> Optional[AggSpec]:
    """Return an :class:`AggSpec` when ``plan`` can run on the
    incremental keyed state store, else None (whole-buffer fallback).

    ``changed_keys_only`` marks update/append output modes, where the
    residual plan above the aggregate executes over only the keys this
    epoch touched: eligibility then additionally requires every operator
    above the Aggregate to be per-row (:data:`PER_ROW_ABOVE`) — an
    ``ORDER BY … LIMIT`` over partial state would otherwise pick its
    "top" rows from whatever happened to change this trigger."""
    aggs = _find_aggregates(plan)
    if len(aggs) != 1 or not isinstance(aggs[0], sp.Aggregate):
        return None
    agg = aggs[0]
    if agg.having is not None or agg.grouping_sets is not None \
            or agg.rollup or agg.cube:
        return None
    if changed_keys_only:
        for node in _ancestors(plan, agg) or ():
            if not isinstance(node, PER_ROW_ABOVE):
                return None
    for g in agg.group:
        if _expr_contains_function(g, ("session_window",)):
            return None  # sessions merge across batches: buffer path

    def matches_group(expr) -> bool:
        if expr in agg.group:
            return True
        if isinstance(expr, ex.Attribute):
            for g in agg.group:
                target = g.child if isinstance(g, ex.Alias) else g
                if isinstance(target, ex.Attribute) and \
                        target.name[-1] == expr.name[-1]:
                    return True
        return False

    kinds: List[Optional[str]] = []
    for entry in agg.aggregate:
        expr = entry.child if isinstance(entry, ex.Alias) else entry
        if matches_group(expr) or matches_group(entry):
            kinds.append(None)
            continue
        if isinstance(expr, ex.Function) and not expr.is_distinct \
                and expr.filter is None \
                and expr.name.lower() in MERGEABLE:
            kinds.append(MERGEABLE[expr.name.lower()])
            continue
        return None
    if not any(k is not None for k in kinds):
        return None
    return AggSpec(agg=agg, merge_kinds=tuple(kinds))


def _hashable(value):
    if isinstance(value, dict):
        return tuple((k, _hashable(v)) for k, v in value.items())
    if isinstance(value, (list, tuple)):
        return tuple(_hashable(v) for v in value)
    return value


def _fold(kind: str, old, new):
    """SQL-null-aware fold: an absent side contributes nothing."""
    if old is None:
        return new
    if new is None:
        return old
    if kind == "sum":
        return old + new
    if kind == "min":
        return new if new < old else old
    return new if new > old else old  # max


class KeyedStateStore:
    """Hash-keyed partial aggregates with changelog tracking.

    ``rows`` maps the hashable form of a key tuple to the full list of
    output-column values (keys carried verbatim, aggregates folded).
    Insertion order is preserved, so repeated emissions of unchanged
    state are stable."""

    def __init__(self, merge_kinds: Tuple[Optional[str], ...]):
        self.merge_kinds = merge_kinds
        self.schema: Optional[pa.Schema] = None   # incl. WM_COLUMN if any
        self.rows: "Dict[tuple, List[object]]" = {}
        self.wm_index: Optional[int] = None
        self._changed: set = set()
        self._deleted: Dict[tuple, List[object]] = {}

    # -- folding -------------------------------------------------------
    def _capture_schema(self, delta: pa.Table) -> None:
        self.schema = delta.schema
        names = delta.schema.names
        self.wm_index = names.index(WM_COLUMN) if WM_COLUMN in names \
            else None

    def _widen_schema(self, incoming: pa.Schema) -> None:
        # Decimal partials widen per-epoch (literal scale tracks the
        # inserted values), and emission casts every stored value back
        # to self.schema — keep the union scale/precision or to_table
        # would refuse to rescale earlier wider sums.
        changed = False
        fields = list(self.schema)
        for i, f in enumerate(fields):
            if i >= len(incoming):
                break
            new = incoming.field(i).type
            if new.equals(f.type):
                continue
            if pa.types.is_decimal(f.type) and pa.types.is_decimal(new):
                scale = max(f.type.scale, new.scale)
                ints = max(f.type.precision - f.type.scale,
                           new.precision - new.scale)
                unified = pa.decimal128(min(38, ints + scale), scale)
                if not unified.equals(f.type):
                    fields[i] = f.with_type(unified)
                    changed = True
        if changed:
            self.schema = pa.schema(fields)

    def merge_delta(self, delta: pa.Table) -> List[tuple]:
        """Fold one epoch's partial-aggregate result into the store;
        returns the keys touched (for update-mode emission and the
        changelog)."""
        if self.schema is None:
            self._capture_schema(delta)
        else:
            self._widen_schema(delta.schema)
        key_pos = [i for i, k in enumerate(self.merge_kinds)
                   if k is None]
        cols = [delta.column(i).to_pylist()
                for i in range(delta.num_columns)]
        touched: List[tuple] = []
        for r in range(delta.num_rows):
            values = [c[r] for c in cols]
            hkey = tuple(_hashable(values[i]) for i in key_pos)
            current = self.rows.get(hkey)
            if current is None:
                self.rows[hkey] = values
            else:
                for i, kind in enumerate(self.merge_kinds):
                    if kind is not None:
                        current[i] = _fold(kind, current[i], values[i])
                if self.wm_index is not None:
                    current[self.wm_index] = _fold(
                        "max", current[self.wm_index],
                        values[self.wm_index])
            self._changed.add(hkey)
            touched.append(hkey)
        return touched

    def evict(self, horizon_seconds: float) -> int:
        """Drop keys whose event-time high-water mark fell behind the
        watermark (Spark semantics: state is evicted per KEY once no
        future row can belong to it)."""
        if self.wm_index is None or horizon_seconds is None:
            return 0
        from .streaming import _event_seconds
        dead = []
        for hkey, values in self.rows.items():
            ts = values[self.wm_index]
            if ts is not None and _event_seconds(ts) < horizon_seconds:
                dead.append(hkey)
        for hkey in dead:
            self._deleted[hkey] = self.rows.pop(hkey)
            self._changed.discard(hkey)
        return len(dead)

    # -- emission ------------------------------------------------------
    def to_table(self, keys=None, include_wm: bool = False) -> pa.Table:
        """Current state as an Arrow table (insertion order), hidden
        watermark column stripped unless ``include_wm``."""
        assert self.schema is not None
        drop_wm = self.wm_index is not None and not include_wm
        selected = self.rows.values() if keys is None else \
            [self.rows[k] for k in keys if k in self.rows]
        selected = list(selected)
        arrays, fields = [], []
        for i, f in enumerate(self.schema):
            if drop_wm and i == self.wm_index:
                continue
            arrays.append(pa.array([v[i] for v in selected],
                                   type=f.type))
            fields.append(f)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    # -- checkpoint serialization --------------------------------------
    def _flagged(self, rows: List[List[object]],
                 deleted_flags: List[bool]) -> pa.Table:
        arrays = [pa.array([v[i] for v in rows], type=f.type)
                  for i, f in enumerate(self.schema)]
        arrays.append(pa.array(deleted_flags, type=pa.bool_()))
        schema = pa.schema(list(self.schema)
                           + [pa.field(DELETED_COLUMN, pa.bool_())])
        return pa.Table.from_arrays(arrays, schema=schema)

    def snapshot_table(self) -> pa.Table:
        rows = list(self.rows.values())
        return self._flagged(rows, [False] * len(rows))

    def changelog_table(self) -> pa.Table:
        """Keys touched or evicted since the last checkpoint, fully
        folded — replay is last-write-wins in epoch order."""
        rows, flags = [], []
        for hkey in self._changed:
            if hkey in self.rows:
                rows.append(self.rows[hkey])
                flags.append(False)
        for values in self._deleted.values():
            rows.append(values)
            flags.append(True)
        return self._flagged(rows, flags)

    @property
    def dirty(self) -> bool:
        return bool(self._changed or self._deleted)

    def clear_dirty(self) -> None:
        self._changed.clear()
        self._deleted.clear()

    def load(self, table: pa.Table, changelog: bool) -> None:
        """Apply a snapshot (replaces nothing — the caller starts from
        an empty store) or one changelog delta in epoch order."""
        names = list(table.schema.names)
        if DELETED_COLUMN in names:
            flags = table.column(names.index(DELETED_COLUMN)).to_pylist()
            table = table.drop_columns([DELETED_COLUMN])
        else:
            flags = [False] * table.num_rows
        if self.schema is None:
            self._capture_schema(table)
        key_pos = [i for i, k in enumerate(self.merge_kinds)
                   if k is None]
        cols = [table.column(i).to_pylist()
                for i in range(table.num_columns)]
        for r in range(table.num_rows):
            values = [c[r] for c in cols]
            hkey = tuple(_hashable(values[i]) for i in key_pos)
            if changelog and flags[r]:
                self.rows.pop(hkey, None)
            else:
                self.rows[hkey] = values


def substitute_node(plan, target, replacement):
    """Replace ``target`` (by identity) anywhere in a spec plan tree."""
    if plan is target:
        return replacement
    if not dataclasses.is_dataclass(plan):
        return plan
    updates = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if isinstance(v, sp.QueryPlan):
            nv = substitute_node(v, target, replacement)
            if nv is not v:
                updates[f.name] = nv
        elif isinstance(v, tuple) and any(
                isinstance(item, sp.QueryPlan) for item in v):
            nv = tuple(substitute_node(item, target, replacement)
                       if isinstance(item, sp.QueryPlan) else item
                       for item in v)
            if any(a is not b for a, b in zip(nv, v)):
                updates[f.name] = nv
    return dataclasses.replace(plan, **updates) if updates else plan
