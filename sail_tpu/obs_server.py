"""Pull-based ops endpoint: Prometheus /metrics, health, JSON debug.

Reference role: the operational surface Tailwind (arXiv:2604.28079)
assumes of a serving fleet — SLOs are only real if they are
continuously MEASURED and scrapeable. The OTLP exporter (tracing.py)
pushes; this module is the pull side: a stdlib ``http.server`` on a
daemon thread (no new dependencies), gated by
``telemetry.http.{enabled,port}``:

- ``GET /metrics``   Prometheus text exposition (v0.0.4) of the FLEET
  metric view: every sample carries a ``worker`` label (``driver`` =
  this process; remote workers from heartbeat-shipped deltas).
  Counters render with the ``_total`` convention, histograms as
  ``_bucket``/``_sum``/``_count`` over the declared exponential
  bounds.
- ``GET /healthz``   liveness: the process is serving.
- ``GET /readyz``    readiness: 200 only when every registered cluster
  driver reports all workers heartbeating, no evicted worker pending
  readmission, and no wedged admission queue; 503 otherwise (body says
  why). A process with no cluster is ready by definition.
- ``GET /debug/queries | /debug/workers | /debug/admission |
  /debug/autoscaler | /debug/compile_cache | /debug/slo |
  /debug/events?n=N``  JSON introspection of the flight recorder,
  worker pool, admission state, the autoscaler (policy config, pool
  occupancy, draining set with handoff progress, newest decisions),
  the persistent compiled-program cache (entry count, bytes, hit
  ratio, top entries by compile time saved), the tenant SLO burn-rate
  view (evaluating the monitor is the tick; also refreshed on every
  /metrics scrape), and the newest N ring events.

The surface is auth-free and bound to ``telemetry.http.host``
(default loopback); it exposes statements and runtime state but never
serializes configuration or the environment, so credentials cannot
leak through it (locked by a test).
"""

from __future__ import annotations

import json
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from . import metrics as _metrics

_START_TS = time.time()


# ---------------------------------------------------------------------------
# cluster registration: drivers expose readiness/debug state to the
# process's ops endpoint without the HTTP layer importing the scheduler
# ---------------------------------------------------------------------------

_CLUSTERS: "weakref.WeakSet" = weakref.WeakSet()


def register_cluster(driver) -> None:
    """A cluster driver in this process joins the ops surface (weakly:
    a stopped/collected driver drops out on its own)."""
    _CLUSTERS.add(driver)


def unregister_cluster(driver) -> None:
    _CLUSTERS.discard(driver)


def _drivers() -> List:
    return [d for d in list(_CLUSTERS)]


# ---------------------------------------------------------------------------
# readiness
# ---------------------------------------------------------------------------

def readiness() -> dict:
    """Aggregate readiness: ready iff every registered driver is ready.
    Driver state is read cross-thread; every probe is defensive — a
    half-updated pool entry must degrade to 'not ready', never raise."""
    checks = []
    ready = True
    for d in _drivers():
        try:
            c = d.readiness()
        except Exception as e:  # noqa: BLE001 — degraded, not broken
            c = {"ready": False, "error": f"{type(e).__name__}: {e}"}
        checks.append(c)
        ready = ready and bool(c.get("ready"))
    return {"ready": ready, "clusters": checks,
            "uptime_s": round(time.time() - _START_TS, 3)}


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(attrs: Dict[str, str], worker: str,
            extra: Optional[Dict[str, str]] = None) -> str:
    pairs = dict(attrs)
    pairs["worker"] = worker
    if extra:
        pairs.update(extra)
    body = ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(pairs.items()))
    return "{" + body + "}"


def _fmt(value: float) -> str:
    f = float(value)
    return str(int(f)) if f == int(f) else repr(f)


def render_prometheus() -> str:
    """The fleet metric view in Prometheus text format. Series group
    per metric name under one # HELP / # TYPE header; a scrape of the
    driver therefore reads the whole fleet."""
    series = _metrics.FLEET.series()
    by_name: Dict[str, List] = {}
    for name, attrs, worker, value in series:
        by_name.setdefault(name, []).append((attrs, worker, value))
    lines: List[str] = []
    for name in sorted(by_name):
        d = _metrics.REGISTRY.definition(name)
        if d is None:
            continue
        prom = _metrics.prometheus_name(name, d.type)
        ptype = {"counter": "counter", "gauge": "gauge",
                 "histogram": "histogram"}[d.type]
        help_text = " ".join(d.description.split()) or name
        lines.append(f"# HELP {prom} {help_text}")
        lines.append(f"# TYPE {prom} {ptype}")
        for attrs, worker, value in sorted(
                by_name[name], key=lambda e: (e[1], sorted(e[0].items()))):
            if isinstance(value, _metrics.HistogramState):
                cum = 0
                for bound, count in zip(value.bounds, value.counts):
                    cum += count
                    lines.append(
                        f"{prom}_bucket"
                        f"{_labels(attrs, worker, {'le': _fmt(bound)})}"
                        f" {cum}")
                cum += value.counts[-1]
                lines.append(
                    f"{prom}_bucket"
                    f"{_labels(attrs, worker, {'le': '+Inf'})} {cum}")
                lines.append(f"{prom}_sum{_labels(attrs, worker)} "
                             f"{repr(float(value.sum))}")
                lines.append(f"{prom}_count{_labels(attrs, worker)} "
                             f"{value.count}")
            else:
                lines.append(
                    f"{prom}{_labels(attrs, worker)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# JSON debug views
# ---------------------------------------------------------------------------

def _debug_queries() -> dict:
    from .profiler import FLIGHT_RECORDER

    def brief(p, active: bool) -> dict:
        return {"query_id": p.query_id,
                "statement": (p.statement or "")[:200],
                "session": p.session, "tenant": p.tenant,
                "status": "running" if active else p.status,
                "phase": p.current_phase() if active else "",
                "total_ms": round(p.total_ms, 3),
                "rows_out": p.rows_out, "slow": p.slow}

    return {"active": [brief(p, True)
                       for p in FLIGHT_RECORDER.active()],
            "recent": [brief(p, False)
                       for p in FLIGHT_RECORDER.profiles()[:64]]}


def _debug_workers() -> dict:
    now = time.time()
    clusters = []
    for d in _drivers():
        try:
            workers = {}
            for wid, w in dict(d.workers).items():
                workers[wid] = {
                    "addr": w.get("addr", ""),
                    "slots": w.get("slots", 0),
                    "running_tasks": len(w.get("tasks", ())),
                    "heartbeat_age_s": round(
                        now - w.get("last_seen", now), 3),
                }
            clusters.append({
                "driver_id": getattr(d, "driver_id", ""),
                "workers": workers,
                "quarantined": sorted(dict(d.quarantined)),
                "pending_readmission": sorted(dict(d._readmit_info)),
            })
        except Exception as e:  # noqa: BLE001 — snapshot best-effort
            clusters.append({"error": f"{type(e).__name__}: {e}"})
    from .catalog.system import SYSTEM
    with SYSTEM._lock:
        known = {wid: dict(w) for wid, w in SYSTEM.workers.items()}
    return {"clusters": clusters, "registry": known}


def _debug_admission() -> dict:
    from .exec import admission as _adm
    gate = _adm.session_gate()
    out = {"session_gate": gate.debug_snapshot(), "clusters": []}
    for d in _drivers():
        try:
            out["clusters"].append(d.admission.debug_snapshot())
        except Exception as e:  # noqa: BLE001
            out["clusters"].append(
                {"error": f"{type(e).__name__}: {e}"})
    return out


def _debug_events(n: int) -> dict:
    from . import events as ev
    records = ev.events()
    return {"count": len(records), "events": records[-max(1, n):]}


def _debug_slo() -> dict:
    """Tenant SLO burn-rate view: evaluates the monitor (taking a
    fresh snapshot and refreshing the cluster.slo.burn_rate gauges)
    and returns the per-tenant/per-window rows alongside the newest
    anomaly verdicts. Pull-based: hitting this endpoint IS the
    evaluation tick."""
    from .analysis import anomaly as _anomaly
    try:
        rows = _anomaly.SLO_MONITOR.evaluate()
    except Exception as e:  # noqa: BLE001 — snapshot best-effort
        return {"error": f"{type(e).__name__}: {e}"}
    return {"slo": rows,
            "anomalies": _anomaly.anomalies()[-32:],
            "baselines": _anomaly.BASELINES.snapshot()[:64]}


def _debug_autoscaler() -> dict:
    """Autoscaler view per registered driver: effective policy config,
    the worker pool (occupancy/idle), the draining set with handoff
    progress, and the newest policy decisions (each carries the
    replayable canonical detail via /debug/events)."""
    now = time.time()
    clusters = []
    for d in _drivers():
        try:
            pool = {}
            draining = dict(getattr(d, "draining", {}))
            for wid, w in dict(d.workers).items():
                idle = w.get("idle_since")
                pool[wid] = {
                    "addr": w.get("addr", ""),
                    "slots": w.get("slots", 0),
                    "running_tasks": len(w.get("tasks", ())),
                    "idle_s": round(now - idle, 3)
                    if idle and not w.get("tasks") else 0.0,
                    "draining": wid in draining,
                }
            clusters.append({
                "driver_id": getattr(d, "driver_id", ""),
                "config": d.autoscaler_cfg.to_dict(),
                "state": {
                    "up_streak": d.autoscaler_state.up_streak,
                    "down_streak": d.autoscaler_state.down_streak,
                    "cooldown_left": d.autoscaler_state.cooldown_left,
                },
                "pool": pool,
                "draining": {
                    wid: {"reason": st.get("reason", ""),
                          "age_s": round(now - st.get("started", now),
                                         3),
                          "channels_moved": st.get("channels", 0),
                          "bytes_moved": st.get("bytes", 0)}
                    for wid, st in draining.items()},
                "decisions": list(d.autoscaler_log)[-32:],
            })
        except Exception as e:  # noqa: BLE001 — snapshot best-effort
            clusters.append({"error": f"{type(e).__name__}: {e}"})
    return {"clusters": clusters}


def _debug_compile_cache() -> dict:
    """Persistent compiled-program cache snapshot: store shape, the
    registry's hit/miss/evict/load-error counters, and the top entries
    by compile time saved. Serializes cache state only — never
    configuration or environment values."""
    from .exec import pcache
    out = pcache.stats()
    rows = {r["name"]: r for r in _metrics.REGISTRY.snapshot()
            if str(r.get("name", "")).startswith(
                ("execution.compile.persistent_",
                 "execution.compile.prewarm_"))}
    counters = {}
    for short in ("hit", "miss", "evict", "load_error"):
        name = f"execution.compile.persistent_{short}_count"
        counters[short] = int(rows.get(name, {}).get("value", 0))
    for short in ("prewarm_loaded", "prewarm_skipped"):
        name = f"execution.compile.{short}_count"
        counters[short] = int(rows.get(name, {}).get("value", 0))
    out["counters"] = counters
    # pinned capacity buckets ride along: the same debug surface that
    # explains compile behavior should show why capacities are stable
    from .exec import capacity
    out["capacity"] = capacity.snapshot()
    consults = counters["hit"] + counters["miss"]
    out["hit_ratio"] = round(counters["hit"] / consults, 4) \
        if consults else None
    return out


# ---------------------------------------------------------------------------
# the HTTP server
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    server_version = "sail-obs/1"

    def log_message(self, *args):  # silence per-request stderr lines
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, payload: dict, code: int = 200) -> None:
        self._send(code, json.dumps(payload, default=str,
                                    indent=1).encode("utf-8"),
                   "application/json")

    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        try:
            url = urlparse(self.path)
            path = url.path.rstrip("/") or "/"
            if path == "/metrics":
                # refresh the SLO burn-rate gauges so a scrape reads
                # window math current as of the scrape, not of the
                # last /debug/slo hit
                try:
                    from .analysis import anomaly as _anomaly
                    _anomaly.SLO_MONITOR.evaluate()
                except Exception:  # noqa: BLE001 — scrape still serves
                    pass
                self._send(200, render_prometheus().encode("utf-8"),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                self._json({"status": "ok",
                            "uptime_s": round(
                                time.time() - _START_TS, 3)})
            elif path == "/readyz":
                state = readiness()
                self._json(state, 200 if state["ready"] else 503)
            elif path == "/debug/queries":
                self._json(_debug_queries())
            elif path == "/debug/workers":
                self._json(_debug_workers())
            elif path == "/debug/admission":
                self._json(_debug_admission())
            elif path == "/debug/autoscaler":
                self._json(_debug_autoscaler())
            elif path == "/debug/compile_cache":
                self._json(_debug_compile_cache())
            elif path == "/debug/slo":
                self._json(_debug_slo())
            elif path == "/debug/events":
                q = parse_qs(url.query)
                try:
                    n = int(q.get("n", ["100"])[0])
                except ValueError:
                    n = 100
                self._json(_debug_events(n))
            else:
                self._json({"error": "not found", "paths": [
                    "/metrics", "/healthz", "/readyz",
                    "/debug/queries", "/debug/workers",
                    "/debug/admission", "/debug/autoscaler",
                    "/debug/compile_cache",
                    "/debug/slo", "/debug/events?n="]}, 404)
        except BrokenPipeError:  # client went away mid-write
            pass
        except Exception as e:  # noqa: BLE001 — ops surface never dies
            try:
                self._json({"error": f"{type(e).__name__}: {e}"}, 500)
            except Exception:  # noqa: BLE001
                pass


class ObsServer:
    """One process-wide ops HTTP server on a daemon thread."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="sail-obs-server")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except Exception:  # noqa: BLE001
            pass


_SERVER: Optional[ObsServer] = None
_SERVER_LOCK = threading.Lock()
_STARTED = False


def server() -> Optional[ObsServer]:
    return _SERVER


def start(host: Optional[str] = None,
          port: Optional[int] = None) -> ObsServer:
    """Start (or return) the process ops server, regardless of the
    config gate — tests and the bench call this explicitly."""
    global _SERVER, _STARTED
    with _SERVER_LOCK:
        if _SERVER is None:
            from .config import get as config_get
            if host is None:
                host = str(config_get("telemetry.http.host",
                                      "127.0.0.1") or "127.0.0.1")
            if port is None:
                try:
                    port = int(config_get("telemetry.http.port", 0))
                except (TypeError, ValueError):
                    port = 0
            _SERVER = ObsServer(host, port)
        _STARTED = True
        return _SERVER


def ensure_started() -> Optional[ObsServer]:
    """Config-gated start (``telemetry.http.enabled``, default off) —
    called from session and cluster construction; one check per
    process, one server per process."""
    global _STARTED
    if _STARTED:
        return _SERVER
    with _SERVER_LOCK:
        if _STARTED:
            return _SERVER
        _STARTED = True
    try:
        from .config import truthy
        enabled = truthy("telemetry.http.enabled", default="false")
    except Exception:  # noqa: BLE001 — ops surface must not break startup
        enabled = False
    if not enabled:
        return None
    try:
        return start()
    except OSError as e:
        # a bind failure (port taken by another process) degrades to no
        # ops endpoint — it must never fail session/cluster startup
        import logging
        logging.getLogger("sail_tpu.obs_server").warning(
            "ops endpoint disabled: cannot bind (%s)", e)
        return None


def stop() -> None:
    """Shut the server down and re-arm the config gate (tests)."""
    global _SERVER, _STARTED
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.close()
            _SERVER = None
        _STARTED = False
