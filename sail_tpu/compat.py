"""PySpark compatibility scanner.

Reference role: pysail's compatibility tooling —
python/pysail/examples/spark/compatibility_check.py scanning user code
for PySpark API usage and cross-referencing hand-maintained
data/compatibility/*.json status files. Redesign: instead of curated
JSON that drifts, support status derives LIVE from this engine —
DataFrame / Column / SparkSession / GroupedData / Catalog methods by
class introspection, and ``pyspark.sql.functions`` calls by actually
resolving a probe query through the planner (cached per name).

CLI: ``python -m sail_tpu compat <file-or-dir> ...``
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

_FUNCTION_MODULES = ("pyspark.sql.functions", "pyspark.sql.connect.functions")


# ---------------------------------------------------------------------------
# source scanning (pure AST — user code is never imported or executed)
# ---------------------------------------------------------------------------

@dataclass
class Usage:
    kind: str          # "function" | "method"
    name: str
    file: str
    line: int


class _Scanner(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.func_aliases: Set[str] = set()    # modules imported as F
        self.func_names: Set[str] = set()      # from functions import col
        self.usages: List[Usage] = []

    def visit_Import(self, node: ast.Import):
        for a in node.names:
            if a.name in _FUNCTION_MODULES:
                self.func_aliases.add(a.asname or a.name.split(".")[-1])
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom):
        if node.module in ("pyspark.sql", "pyspark.sql.connect"):
            for a in node.names:
                if a.name == "functions":
                    self.func_aliases.add(a.asname or "functions")
        elif node.module in _FUNCTION_MODULES:
            for a in node.names:
                self.func_names.add(a.asname or a.name)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id in self.func_aliases:
                self.usages.append(Usage("function", f.attr, self.path,
                                         node.lineno))
            else:
                self.usages.append(Usage("method", f.attr, self.path,
                                         node.lineno))
        elif isinstance(f, ast.Name) and f.id in self.func_names:
            self.usages.append(Usage("function", f.id, self.path,
                                     node.lineno))
        self.generic_visit(node)


def scan_source(text: str, path: str = "<string>") -> List[Usage]:
    s = _Scanner(path)
    s.visit(ast.parse(text))
    return s.usages


def scan_paths(paths: Iterable[str]
               ) -> Tuple[List[Usage], List[Tuple[str, str]]]:
    """→ (usages, skipped) where skipped is [(path, reason)] for files
    that are missing or do not parse."""
    out: List[Usage] = []
    skipped: List[Tuple[str, str]] = []

    def one(fp: str):
        try:
            with open(fp, "r", encoding="utf-8") as fh:
                out.extend(scan_source(fh.read(), fp))
        except (OSError, SyntaxError, ValueError) as e:
            skipped.append((fp, f"{type(e).__name__}: {e}"))

    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(".py"):
                        one(os.path.join(root, f))
        else:
            one(p)
    return out, skipped


# ---------------------------------------------------------------------------
# live support oracle
# ---------------------------------------------------------------------------

_PROBE_ARGS = ("", "NULL", "'a'", "1", "1.5", "NULL, NULL", "'a', 'a'",
               "'a', 1", "1, 1", "NULL, NULL, NULL", "'a', 1, 1")


class SupportOracle:
    """Support status straight from the engine, no curated data."""

    def __init__(self, session=None):
        self._session = session
        self._fn_cache: Dict[str, str] = {}
        self._methods: Optional[Dict[str, str]] = None

    def _spark(self):
        if self._session is None:
            from .session import SparkSession
            self._session = SparkSession(
                {"spark.sail.execution.mesh": "off"})
        return self._session

    def method_surface(self) -> Dict[str, str]:
        """method name -> owning class, for every public method of the
        session-layer API classes."""
        if self._methods is None:
            from . import session as ss
            self._methods = {}
            for cls in (ss.DataFrame, ss.Column, ss.SparkSession,
                        ss.GroupedData, ss.CoGroupedData, ss.Catalog,
                        ss.DataFrameReader, ss.DataFrameWriter):
                for m in dir(cls):
                    if not m.startswith("_"):
                        self._methods.setdefault(m, cls.__name__)
        return self._methods

    # method names shared with Python builtin types (str/list/dict/...):
    # the untyped AST scan cannot tell ",".join(...) from df.join(...),
    # so these report "ambiguous" instead of claiming PySpark usage
    _BUILTIN_METHODS = frozenset(
        m for t in (str, bytes, list, dict, set, tuple, frozenset)
        for m in dir(t) if not m.startswith("_"))

    def method_status(self, name: str) -> Tuple[str, str]:
        """→ (status, detail). Methods outside the engine surface are
        only *suspected* PySpark API (the scanner cannot type arbitrary
        receivers), so they report as unknown, not unsupported."""
        owner = self.method_surface().get(name)
        if owner is not None:
            if name in self._BUILTIN_METHODS:
                return "ambiguous", owner
            return "supported", owner
        return "unknown", ""

    def function_status(self, name: str) -> str:
        """Resolve `SELECT name(args)` over a probe table for a range of
        arities/types; any successful resolution → supported."""
        key = name.lower()
        cached = self._fn_cache.get(key)
        if cached is not None:
            return cached
        from .plan.resolver import ResolutionError
        from .sql import parse_one

        spark = self._spark()
        status = "unsupported"
        for args in _PROBE_ARGS:
            try:
                spark._resolve(parse_one(f"SELECT {name}({args})"))
                status = "supported"
                break
            except ResolutionError:
                continue
            except Exception:  # noqa: BLE001 — parse/type errors: next
                continue
        if status == "unsupported":
            # aggregates/windows need a relation or OVER clause
            for probe in (f"SELECT {name}(x) FROM (SELECT 1 AS x)",
                          f"SELECT {name}() OVER () FROM (SELECT 1 AS x)",
                          f"SELECT {name}(x) OVER () FROM (SELECT 1 AS x)"):
                try:
                    spark._resolve(parse_one(probe))
                    status = "supported"
                    break
                except Exception:  # noqa: BLE001
                    continue
        self._fn_cache[key] = status
        return status


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def check_paths(paths: Iterable[str], session=None) -> List[dict]:
    """→ rows {kind, name, status, detail, count, locations}; files that
    fail to read/parse become rows with kind "file" / status "skipped"."""
    oracle = SupportOracle(session)
    usages, skipped = scan_paths(paths)
    grouped: Dict[Tuple[str, str], List[Usage]] = {}
    for u in usages:
        grouped.setdefault((u.kind, u.name), []).append(u)
    rows = []
    for (kind, name), us in sorted(grouped.items()):
        if kind == "function":
            status, detail = oracle.function_status(name), "functions"
        else:
            status, detail = oracle.method_status(name)
            if status == "unknown":
                continue  # arbitrary non-PySpark method calls: noise
        rows.append({
            "kind": kind, "name": name, "status": status,
            "detail": detail, "count": len(us),
            "locations": [f"{u.file}:{u.line}" for u in us[:5]],
        })
    for path, reason in skipped:
        rows.append({"kind": "file", "name": path, "status": "skipped",
                     "detail": reason, "count": 0, "locations": []})
    return rows


def format_report(rows: List[dict]) -> str:
    if not rows:
        return "no PySpark API usage found"
    w = max(len(r["name"]) for r in rows) + 2
    lines = [f"{'API':<{w}} {'kind':<10} {'status':<13} uses",
             "-" * (w + 32)]
    unsupported = 0
    for r in rows:
        lines.append(f"{r['name']:<{w}} {r['kind']:<10} "
                     f"{r['status']:<13} {r['count']}")
        if r["status"] == "unsupported":
            unsupported += 1
    lines.append("")
    lines.append(f"{len(rows)} distinct APIs; "
                 f"{unsupported} unsupported")
    return "\n".join(lines)
