"""Native host-kernel compilation runtime.

Reference role: the performance-critical native execution substrate
(DataFusion's vectorized Rust operators, SURVEY.md §2.4-2.5). On TPU the
compute path is XLA; on the CPU fallback path (local dev, driver-side
stages, environments without accelerators) the engine JIT-compiles fused
operator pipelines to C++ via the system toolchain and runs them over the
batch's host buffers zero-copy. One query shape compiles once (disk +
in-process cache) and is reused across batches, mirroring how the
compiled-XLA op cache works for device programs.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Dict, Optional

_LOCK = threading.Lock()
_LIBS: Dict[str, ctypes.CDLL] = {}
_AVAILABLE: Optional[bool] = None

_CACHE_DIR = os.environ.get(
    "SAIL_NATIVE_CACHE",
    os.path.join(tempfile.gettempdir(), "sail_tpu_native"))


def enabled() -> bool:
    """Native host kernels are on unless explicitly disabled."""
    return os.environ.get("SAIL_NATIVE", "1") not in ("0", "false", "off")


_PROBE_LOCK = threading.Lock()


def available() -> bool:
    """True when a working C++ toolchain is present (checked once).

    The probe compiles a kernel via compile_and_load, which takes _LOCK
    internally — so the probe runs under its own lock, never _LOCK (a
    non-reentrant _LOCK here self-deadlocked in a prior revision).
    """
    global _AVAILABLE
    if _AVAILABLE is None:
        with _PROBE_LOCK:
            if _AVAILABLE is None:
                _AVAILABLE = _probe()
    return _AVAILABLE


def _probe() -> bool:
    if not enabled():
        return False
    try:
        lib = compile_and_load(
            'extern "C" long long sail_probe(long long x) { return x + 1; }',
            require=("sail_probe",))
        fn = lib.sail_probe
        fn.restype = ctypes.c_longlong
        return fn(ctypes.c_longlong(41)) == 42
    except Exception:
        return False


def compile_and_load(source: str,
                     require: tuple = ()) -> ctypes.CDLL:
    """Compile C++ source to a shared object (content-addressed cache on
    disk) and dlopen it. Raises on toolchain failure.

    ``require`` names symbols the loaded library must export: a valid
    ELF missing them (a concurrent builder once published a kernel
    compiled from a truncated source file) is dropped and rebuilt once
    instead of being cached broken in ``_LIBS`` for the process
    lifetime — an AttributeError at first symbol access would poison
    every later query sharing the kernel key."""
    key = hashlib.sha256(source.encode()).hexdigest()[:24]
    with _LOCK:
        lib = _LIBS.get(key)
        if lib is not None:
            return lib
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so_path = os.path.join(_CACHE_DIR, f"k{key}.so")
    last_err: Optional[Exception] = None
    for _attempt in range(2):
        if not os.path.exists(so_path):
            _build(source, key, so_path)
        load_path = so_path
        if _attempt:
            # dlopen caches by pathname: after a failed first load the
            # retry MUST go through a fresh path or glibc hands the
            # stale broken mapping back regardless of the rebuilt file.
            load_path = so_path + \
                f".r{os.getpid()}_{threading.get_ident()}"
            os.link(so_path, load_path)
        try:
            lib = ctypes.CDLL(load_path)
            for sym in require:
                getattr(lib, sym)
            break
        except (OSError, AttributeError) as e:
            # OSError: a TRUNCATED .so ("file too short").
            # AttributeError: loads but lacks a required symbol.
            # Either way drop the artifact and rebuild once.
            last_err = e
            try:
                os.unlink(so_path)
            except OSError:
                pass
        finally:
            if load_path is not so_path:
                try:  # mapping survives the unlink
                    os.unlink(load_path)
                except OSError:
                    pass
    else:
        raise RuntimeError(f"native kernel load failed: {last_err}")
    with _LOCK:
        _LIBS[key] = lib
    return lib


def _build(source: str, key: str, so_path: str) -> None:
    """Compile from a PRIVATE source file and publish both artifacts
    atomically. Tmp names are unique per (pid, thread) — cluster
    workers are THREADS sharing one pid — and g++ must never read the
    shared .cpp path: a concurrent builder's truncating open() there
    once raced another thread's in-flight compile into an EMPTY
    translation unit, publishing a symbol-less .so."""
    src_path = os.path.join(_CACHE_DIR, f"k{key}.cpp")
    suffix = f".tmp{os.getpid()}_{threading.get_ident()}"
    # g++ infers the language from the extension — keep .cpp last
    src_tmp = os.path.join(_CACHE_DIR, f"k{key}{suffix}.cpp")
    with open(src_tmp, "w") as f:
        f.write(source)
    tmp = so_path + suffix
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
           "-fPIC", "-pthread", "-o", tmp, src_tmp]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native kernel compile failed:\n{proc.stderr}")
        os.replace(src_tmp, src_path)  # keep the .cpp for debugging
    finally:
        try:
            os.unlink(src_tmp)
        except OSError:
            pass
    os.replace(tmp, so_path)  # atomic under concurrent builders
