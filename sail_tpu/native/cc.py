"""Native host-kernel compilation runtime.

Reference role: the performance-critical native execution substrate
(DataFusion's vectorized Rust operators, SURVEY.md §2.4-2.5). On TPU the
compute path is XLA; on the CPU fallback path (local dev, driver-side
stages, environments without accelerators) the engine JIT-compiles fused
operator pipelines to C++ via the system toolchain and runs them over the
batch's host buffers zero-copy. One query shape compiles once (disk +
in-process cache) and is reused across batches, mirroring how the
compiled-XLA op cache works for device programs.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Dict, Optional

_LOCK = threading.Lock()
_LIBS: Dict[str, ctypes.CDLL] = {}
_AVAILABLE: Optional[bool] = None

_CACHE_DIR = os.environ.get(
    "SAIL_NATIVE_CACHE",
    os.path.join(tempfile.gettempdir(), "sail_tpu_native"))


def enabled() -> bool:
    """Native host kernels are on unless explicitly disabled."""
    return os.environ.get("SAIL_NATIVE", "1") not in ("0", "false", "off")


_PROBE_LOCK = threading.Lock()


def available() -> bool:
    """True when a working C++ toolchain is present (checked once).

    The probe compiles a kernel via compile_and_load, which takes _LOCK
    internally — so the probe runs under its own lock, never _LOCK (a
    non-reentrant _LOCK here self-deadlocked in a prior revision).
    """
    global _AVAILABLE
    if _AVAILABLE is None:
        with _PROBE_LOCK:
            if _AVAILABLE is None:
                _AVAILABLE = _probe()
    return _AVAILABLE


def _probe() -> bool:
    if not enabled():
        return False
    try:
        lib = compile_and_load(
            'extern "C" long long sail_probe(long long x) { return x + 1; }')
        fn = lib.sail_probe
        fn.restype = ctypes.c_longlong
        return fn(ctypes.c_longlong(41)) == 42
    except Exception:
        return False


def compile_and_load(source: str) -> ctypes.CDLL:
    """Compile C++ source to a shared object (content-addressed cache on
    disk) and dlopen it. Raises on toolchain failure."""
    key = hashlib.sha256(source.encode()).hexdigest()[:24]
    with _LOCK:
        lib = _LIBS.get(key)
        if lib is not None:
            return lib
    os.makedirs(_CACHE_DIR, exist_ok=True)
    so_path = os.path.join(_CACHE_DIR, f"k{key}.so")
    last_err: Optional[OSError] = None
    for _attempt in range(2):
        if not os.path.exists(so_path):
            _build(source, key, so_path)
        try:
            lib = ctypes.CDLL(so_path)
            break
        except OSError as e:
            # a TRUNCATED .so ("file too short"): concurrent builders in
            # other threads/processes once collided on a shared tmp name
            # mid-write. Drop the bad artifact and rebuild once.
            last_err = e
            try:
                os.unlink(so_path)
            except OSError:
                pass
    else:
        raise RuntimeError(f"native kernel load failed: {last_err}")
    with _LOCK:
        _LIBS[key] = lib
    return lib


def _build(source: str, key: str, so_path: str) -> None:
    """Compile to a tmp path unique per (pid, thread) — cluster workers
    are THREADS sharing one pid, so a pid-only suffix let two builders
    of the same kernel interleave writes and publish a truncated .so —
    then atomically publish."""
    src_path = os.path.join(_CACHE_DIR, f"k{key}.cpp")
    with open(src_path, "w") as f:
        f.write(source)
    tmp = so_path + f".tmp{os.getpid()}_{threading.get_ident()}"
    cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared",
           "-fPIC", "-pthread", "-o", tmp, src_path]
    proc = subprocess.run(cmd, capture_output=True, text=True,
                          timeout=120)
    if proc.returncode != 0:
        raise RuntimeError(f"native kernel compile failed:\n{proc.stderr}")
    os.replace(tmp, so_path)  # atomic under concurrent builders
