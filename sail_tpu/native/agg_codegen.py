"""Fused filter→project→group-aggregate C++ codegen.

Translates a pipeline chain (Scan → Filter/Project… → Aggregate with
direct-binned group keys) into ONE C++ row loop compiled by cc.py and run
over the batch's host buffers zero-copy. This is the CPU-fallback hot path:
one pass over memory with all aggregates accumulated together, where the
XLA CPU backend would run one scatter pass per aggregate.

Reference role: DataFusion's vectorized hash-aggregate + fused filter
(crates/sail-physical-plan, SURVEY.md §2.4); semantics mirror
plan/compiler.py's device kernels exactly (decimal scale alignment,
Spark null rules, dictionary-code string ops via bind-time LUTs).

Raises NativeUnsupported for anything outside the supported subset; the
executor falls back to the jitted device path.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..plan import nodes as pn
from ..plan import rex as rx
from ..plan.compiler import like_pattern_to_regex
from ..spec import data_type as dt


class NativeUnsupported(Exception):
    pass


def _u(msg):
    raise NativeUnsupported(msg)


# C scalar types by physical dtype
_CTYPES = {"int8": "int8_t", "int16": "int16_t", "int32": "int32_t",
           "int64": "int64_t", "float32": "float", "float64": "double",
           "bool": "uint8_t"}


def _ctype_of(d: dt.DataType) -> str:
    name = d.physical_dtype
    if name is None or name not in _CTYPES:
        _u(f"no native representation for {d.simple_string()}")
    return _CTYPES[name]


def _is_str(d):
    return isinstance(d, (dt.StringType, dt.BinaryType))


def _dec_scale(d) -> Optional[int]:
    if isinstance(d, dt.DecimalType) and d.physical_dtype == "int64":
        return d.scale
    return None


def _is_float(d) -> bool:
    return d.physical_dtype in ("float32", "float64")


def _is_int(d) -> bool:
    return d.physical_dtype in ("int8", "int16", "int32", "int64")


class Val:
    """An emitted C expression: code string + validity expression (None =
    always valid) + logical dtype + optional string dictionary."""

    __slots__ = ("code", "valid", "dtype", "dictionary")

    def __init__(self, code, valid, dtype, dictionary=None):
        self.code = code
        self.valid = valid
        self.dtype = dtype
        self.dictionary = dictionary


def _vand(*vs) -> Optional[str]:
    parts = [v for v in vs if v is not None]
    if not parts:
        return None
    return "(" + " && ".join(parts) + ")"


class AggCodegen:
    """Builds the C++ source + argument plan for one fused aggregate."""

    def __init__(self, p: pn.AggregateExec, chain: List[pn.PlanNode],
                 bottom_schema: pn.Schema, dicts: Dict[int, object],
                 validity_present: Tuple[bool, ...], fold_const):
        self.p = p
        self.chain = chain
        self.bottom_schema = bottom_schema
        self.dicts = dicts                  # bottom column idx -> pa.Array
        self.validity_present = validity_present
        self.fold_const = fold_const        # rex -> (python value, dtype) | None
        self.stmts: List[str] = []          # per-row statements
        self.args: List[Tuple[str, object]] = []  # ordered array args
        self.luts: List[np.ndarray] = []    # bind-time lookup tables
        self._tmp = 0
        self._arg_slot: Dict[object, int] = {}

    # ---------------- argument slots ----------------
    def _slot(self, kind, payload) -> int:
        key = (kind, payload if kind != "lut" else id(payload))
        if key in self._arg_slot:
            return self._arg_slot[key]
        slot = len(self.args)
        self.args.append((kind, payload))
        self._arg_slot[key] = slot
        return slot

    def _col_ptr(self, idx: int, ctype: str) -> str:
        slot = self._slot("col", idx)
        return f"((const {ctype}*)data[{slot}])"

    def _validity_ptr(self, idx: int) -> str:
        slot = self._slot("validity", idx)
        return f"((const uint8_t*)data[{slot}])"

    def _lut_ptr(self, arr: np.ndarray, ctype: str) -> str:
        self.luts.append(arr)
        slot = self._slot("lut", arr)
        return f"((const {ctype}*)data[{slot}])"

    def _fresh(self, prefix="t") -> str:
        self._tmp += 1
        return f"{prefix}{self._tmp}"

    # ---------------- expression emission ----------------
    def emit(self, r: rx.Rex, env: Dict[int, Val]) -> Val:
        folded = self._try_fold(r)
        if folded is not None:
            return folded
        if isinstance(r, rx.BoundRef):
            v = env.get(r.index)
            if v is None:
                _u(f"unbound column {r.index}")
            return v
        if isinstance(r, rx.RLit):
            return self._emit_literal(r)
        if isinstance(r, rx.RCast):
            return self._emit_cast(r, env)
        if isinstance(r, rx.RCase):
            return self._emit_case(r, env)
        if isinstance(r, rx.RCall):
            return self._emit_call(r, env)
        _u(f"cannot emit {type(r).__name__}")

    def _try_fold(self, r: rx.Rex) -> Optional[Val]:
        if isinstance(r, (rx.BoundRef, rx.RLit)):
            return None
        if any(isinstance(n, (rx.BoundRef, rx.RLambda, rx.RLambdaVar))
               for n in rx.walk(r)):
            return None
        got = self.fold_const(r)
        if got is None:
            return None
        value, dtype = got
        if value is None:
            return Val("0", "false", dtype)
        if _is_str(dtype):
            import pyarrow as pa
            return Val("0", None, dtype, pa.array([value]))
        return Val(self._const(value, dtype), None, dtype)

    @staticmethod
    def _const(v, d: dt.DataType) -> str:
        if isinstance(d, dt.BooleanType):
            return "1" if v else "0"
        if _is_float(d):
            return repr(float(v))
        return f"{int(v)}LL"

    def _emit_literal(self, r: rx.RLit) -> Val:
        v = r.value
        d = v.data_type
        if v.is_null:
            return Val("0", "false", d)
        if _is_str(d):
            import pyarrow as pa
            return Val("0", None, d, pa.array([v.value]))
        pv = v.physical_value()
        if isinstance(pv, (bool, int, float)):
            return Val(self._const(pv, d), None, d)
        _u(f"literal {type(pv).__name__}")

    # cast semantics mirror plan/compiler.py::_compile_cast
    def _emit_cast(self, r: rx.RCast, env) -> Val:
        child = self.emit(r.child, env)
        src, dst = child.dtype, r.dtype
        if src == dst:
            return child
        if _is_str(src) or _is_str(dst):
            if _is_str(src) and child.dictionary is not None \
                    and not _is_str(dst):
                return self._dict_lut_cast(child, dst)
            _u("string cast")
        ss, ds_ = _dec_scale(src), _dec_scale(dst)
        x = child.code
        if ss is not None and ds_ is None:
            x = f"((double)({x}) / {10.0 ** ss!r})"
            src_f = True
        else:
            src_f = _is_float(src)
        if ds_ is not None:
            if ss is not None:
                if ds_ >= ss:
                    x = f"(({x}) * {10 ** (ds_ - ss)}LL)"
                else:
                    f = 10 ** (ss - ds_)
                    t = self._fresh("c")
                    self.stmts.append(f"int64_t {t} = {x};")
                    x = (f"({t} >= 0 ? ({t} + {f // 2}LL) / {f}LL"
                         f" : -((-{t} + {f // 2}LL) / {f}LL))")
            elif src_f:
                t = self._fresh("c")
                self.stmts.append(
                    f"double {t} = ({x}) * {10.0 ** ds_!r};")
                x = (f"(int64_t)({t} >= 0 ? floor({t} + 0.5)"
                     f" : -floor(-{t} + 0.5))")
            else:
                x = f"((int64_t)({x}) * {10 ** ds_}LL)"
            return Val(x, child.valid, dst)
        ct = _ctype_of(dst)
        if isinstance(dst, dt.BooleanType):
            return Val(f"(({x}) != 0)", child.valid, dst)
        return Val(f"(({ct})({x}))", child.valid, dst)

    def _dict_lut_cast(self, child: Val, dst: dt.DataType) -> Val:
        from ..plan.compiler import _dict_strings, _parse_string_value
        vals = _dict_strings(child.dictionary)
        out, ok = [], []
        for s in vals:
            v, good = _parse_string_value(s, dst)
            out.append(v)
            ok.append(good)
        npdt = np.dtype(dst.physical_dtype or "int64")
        lutp = self._lut_ptr(np.asarray(out, dtype=npdt), _CTYPES[npdt.name])
        okp = self._lut_ptr(np.asarray(ok, dtype=np.uint8), "uint8_t")
        code = f"{lutp}[{child.code}]"
        valid = _vand(child.valid, f"{okp}[{child.code}]")
        return Val(code, valid, dst)

    def _emit_case(self, r: rx.RCase, env) -> Val:
        if _is_str(r.dtype):
            _u("string CASE")
        ct = _ctype_of(r.dtype)
        out = self._fresh("cs")
        okv = f"{out}_ok"
        self.stmts.append(f"{ct} {out} = 0; bool {okv} = false;")
        closes = 0
        for cond, val in r.branches:
            c = self.emit(cond, env)
            cc = _vand(c.valid, f"(bool)({c.code})") or f"(bool)({c.code})"
            v = self.emit(val, env)
            self.stmts.append(f"if ({cc}) {{ {out} = ({ct})({v.code}); "
                              f"{okv} = {v.valid or 'true'}; }} else {{")
            closes += 1
        if r.else_value is not None:
            v = self.emit(r.else_value, env)
            self.stmts.append(f"{out} = ({ct})({v.code}); "
                              f"{okv} = {v.valid or 'true'};")
        self.stmts.append("}" * closes)
        return Val(out, okv, r.dtype)

    # ---------------- calls ----------------
    _CMP = {"==": "==", "!=": "!=", "<": "<", "<=": "<=",
            ">": ">", ">=": ">="}

    def _emit_call(self, r: rx.RCall, env) -> Val:
        name = r.fn
        if name in ("and", "or"):
            return self._emit_kleene(name, r, env)
        if name == "not":
            a = self.emit(r.args[0], env)
            return Val(f"(!(bool)({a.code}))", a.valid, dt.BooleanType())
        if name == "isnull":
            a = self.emit(r.args[0], env)
            return Val(f"(!({a.valid or 'true'}))", None, dt.BooleanType())
        if name == "isnotnull":
            a = self.emit(r.args[0], env)
            return Val(f"({a.valid or 'true'})", None, dt.BooleanType())
        args = [self.emit(a, env) for a in r.args]
        str_args = [a for a in args if _is_str(a.dtype)]
        if str_args:
            return self._emit_string_call(name, r, args)
        if name in self._CMP:
            return self._emit_cmp(name, args, r)
        if name in ("+", "-", "*"):
            return self._emit_arith(name, args, r)
        if name == "/":
            return self._emit_div(args)
        if name == "in":
            return self._emit_in(args)
        if name in ("if",):
            c, t, f = args
            code = (f"((bool)({c.code}) && {c.valid or 'true'} ? "
                    f"({t.code}) : ({f.code}))")
            valid = None
            if t.valid is not None or f.valid is not None:
                valid = (f"((bool)({c.code}) && {c.valid or 'true'} ? "
                         f"({t.valid or 'true'}) : ({f.valid or 'true'}))")
            return Val(code, valid, r.dtype)
        if name == "coalesce":
            return self._emit_coalesce(args, r)
        if name in ("year", "month", "day", "dayofmonth", "quarter"):
            return self._emit_date_field(name, args[0], r)
        if name in ("negative", "abs"):
            a = args[0]
            if name == "negative":
                return Val(f"(-({a.code}))", a.valid, r.dtype)
            fn = "fabs" if _is_float(a.dtype) else "llabs"
            return Val(f"({fn}({a.code}))", a.valid, r.dtype)
        _u(f"function {name!r}")

    def _emit_kleene(self, name, r, env) -> Val:
        a = self.emit(r.args[0], env)
        b = self.emit(r.args[1], env)
        if a.valid is None and b.valid is None:
            op = "&&" if name == "and" else "||"
            return Val(f"((bool)({a.code}) {op} (bool)({b.code}))", None,
                       dt.BooleanType())
        ad, av = f"(bool)({a.code})", a.valid or "true"
        bd, bv = f"(bool)({b.code})", b.valid or "true"
        t = self._fresh("k")
        if name == "and":
            # false if either side is definitively false
            self.stmts.append(
                f"bool {t}_af = ({av}) && !({ad});"
                f" bool {t}_bf = ({bv}) && !({bd});"
                f" bool {t}_ok = {t}_af || {t}_bf || (({av}) && ({bv}));"
                f" bool {t} = !({t}_af || {t}_bf) && ({ad}) && ({bd});")
        else:
            self.stmts.append(
                f"bool {t}_at = ({av}) && ({ad});"
                f" bool {t}_bt = ({bv}) && ({bd});"
                f" bool {t}_ok = {t}_at || {t}_bt || (({av}) && ({bv}));"
                f" bool {t} = {t}_at || {t}_bt;")
        return Val(t, f"{t}_ok", dt.BooleanType())

    def _align_decimals(self, a: Val, b: Val) -> Tuple[str, str, bool]:
        """Scale-align two numeric operands (mirrors _binary_numeric)."""
        sa, sb = _dec_scale(a.dtype), _dec_scale(b.dtype)
        x, y = a.code, b.code
        if sa is None and sb is None:
            if _is_float(a.dtype) or _is_float(b.dtype):
                return f"((double)({x}))", f"((double)({y}))", True
            return x, y, False
        s = max(sa or 0, sb or 0)
        fa, fb = _is_float(a.dtype), _is_float(b.dtype)
        if fa or fb:
            xs = x if sa is None else f"((double)({x}) / {10.0 ** sa!r})"
            ys = y if sb is None else f"((double)({y}) / {10.0 ** sb!r})"
            return f"((double)({xs}))", f"((double)({ys}))", True
        if sa is not None:
            x = f"(({x}) * {10 ** (s - sa)}LL)" if s > sa else f"({x})"
        else:
            x = f"((int64_t)({x}) * {10 ** s}LL)"
        if sb is not None:
            y = f"(({y}) * {10 ** (s - sb)}LL)" if s > sb else f"({y})"
        else:
            y = f"((int64_t)({y}) * {10 ** s}LL)"
        return x, y, False

    def _emit_cmp(self, name, args, r) -> Val:
        a, b = args
        x, y, _ = self._align_decimals(a, b)
        return Val(f"(({x}) {self._CMP[name]} ({y}))",
                   _vand(a.valid, b.valid), dt.BooleanType())

    def _emit_arith(self, name, args, r) -> Val:
        a, b = args
        valid = _vand(a.valid, b.valid)
        sa, sb = _dec_scale(a.dtype), _dec_scale(b.dtype)
        so = _dec_scale(r.dtype)
        ct = _ctype_of(r.dtype)
        if name in ("+", "-"):
            x, y, _ = self._align_decimals(a, b)
            return Val(f"(({ct})(({x}) {name} ({y})))", valid, r.dtype)
        # multiply: raw product then half-up rescale (compiler.py parity)
        x, y = a.code, b.code
        if _is_float(a.dtype) or _is_float(b.dtype) or \
                (sa is None and sb is None):
            if sa is not None:
                x = f"((double)({x}) / {10.0 ** sa!r})"
            if sb is not None:
                y = f"((double)({y}) / {10.0 ** sb!r})"
            return Val(f"(({ct})(({x}) * ({y})))", valid, r.dtype)
        extra = 0
        if sa is not None and sb is not None and so is not None:
            extra = sa + sb - so
        elif so is not None and (sa is None) != (sb is None):
            extra = (sa or 0) + (sb or 0) - so
        t = self._fresh("m")
        self.stmts.append(
            f"int64_t {t} = (int64_t)({x}) * (int64_t)({y});")
        if extra > 0:
            f = 10 ** extra
            return Val(f"({t} >= 0 ? ({t} + {f // 2}LL) / {f}LL"
                       f" : -((-{t} + {f // 2}LL) / {f}LL))", valid, r.dtype)
        return Val(t, valid, r.dtype)

    def _emit_div(self, args) -> Val:
        a, b = args
        sa, sb = _dec_scale(a.dtype), _dec_scale(b.dtype)
        x = a.code if sa is None else f"((double)({a.code}) / {10.0 ** sa!r})"
        y = b.code if sb is None else f"((double)({b.code}) / {10.0 ** sb!r})"
        t = self._fresh("dv")
        self.stmts.append(f"double {t}_y = (double)({y});"
                          f" double {t} = (double)({x}) /"
                          f" ({t}_y == 0.0 ? 1.0 : {t}_y);")
        return Val(t, _vand(a.valid, b.valid, f"({t}_y != 0.0)"),
                   dt.DoubleType())

    def _emit_in(self, args) -> Val:
        child = args[0]
        sc = _dec_scale(child.dtype)
        hits = []
        valid_terms = []
        for it in args[1:]:
            si = _dec_scale(it.dtype)
            x, y = child.code, it.code
            if sc is not None or si is not None:
                s = max(sc or 0, si or 0)
                if sc is not None and s > sc:
                    x = f"(({x}) * {10 ** (s - sc)}LL)"
                if si is not None and s > si:
                    y = f"(({y}) * {10 ** (s - si)}LL)"
            term = f"(({x}) == ({y}))"
            if it.valid is not None:
                term = f"(({it.valid}) && {term})"
            hits.append(term)
        return Val("(" + " || ".join(hits) + ")", child.valid,
                   dt.BooleanType())

    def _emit_coalesce(self, args, r) -> Val:
        ct = _ctype_of(r.dtype)
        out = self._fresh("co")
        self.stmts.append(f"{ct} {out} = 0; bool {out}_ok = false;")
        for a in args:
            self.stmts.append(f"if (!{out}_ok && ({a.valid or 'true'})) "
                              f"{{ {out} = ({ct})({a.code}); {out}_ok = true; }}")
        return Val(out, f"{out}_ok", r.dtype)

    def _emit_date_field(self, name, a: Val, r) -> Val:
        if not isinstance(a.dtype, dt.DateType):
            _u(f"{name} over non-date")
        t = self._fresh("dc")
        self.stmts.append(
            f"int64_t {t}_z = (int64_t)({a.code}) + 719468;"
            f" int64_t {t}_era = ({t}_z >= 0 ? {t}_z : {t}_z - 146096) / 146097;"
            f" int64_t {t}_doe = {t}_z - {t}_era * 146097;"
            f" int64_t {t}_yoe = ({t}_doe - {t}_doe/1460 + {t}_doe/36524 - {t}_doe/146096) / 365;"
            f" int64_t {t}_y = {t}_yoe + {t}_era * 400;"
            f" int64_t {t}_doy = {t}_doe - (365*{t}_yoe + {t}_yoe/4 - {t}_yoe/100);"
            f" int64_t {t}_mp = (5*{t}_doy + 2)/153;"
            f" int64_t {t}_d = {t}_doy - (153*{t}_mp+2)/5 + 1;"
            f" int64_t {t}_m = {t}_mp < 10 ? {t}_mp+3 : {t}_mp-9;"
            f" if ({t}_m <= 2) {t}_y += 1;")
        if name == "year":
            code = f"((int32_t){t}_y)"
        elif name == "month":
            code = f"((int32_t){t}_m)"
        elif name == "quarter":
            code = f"((int32_t)(({t}_m - 1)/3 + 1))"
        else:
            code = f"((int32_t){t}_d)"
        return Val(code, a.valid, r.dtype)

    # ---------------- string (dictionary LUT) calls ----------------
    def _emit_string_call(self, name, r, args) -> Val:
        from ..plan.compiler import _dict_strings
        import re as _re

        def lit_str(a: Val) -> Optional[str]:
            if a.dictionary is not None and len(a.dictionary) == 1:
                return _dict_strings(a.dictionary)[0]
            return None

        if name in ("==", "!=", "<", "<=", ">", ">="):
            a, b = args
            if not (_is_str(a.dtype) and _is_str(b.dtype)):
                _u("mixed string comparison")
            # column vs literal → bool LUT over codes
            col, lit, flip = (a, lit_str(b), False)
            if lit is None:
                col, lit, flip = (b, lit_str(a), True)
            if lit is None or col.dictionary is None:
                _u("string cmp needs a literal side")
            vals = _dict_strings(col.dictionary)
            op = name if not flip else {"<": ">", "<=": ">=", ">": "<",
                                        ">=": "<=", "==": "==",
                                        "!=": "!="}[name]
            import operator
            ops = {"==": operator.eq, "!=": operator.ne, "<": operator.lt,
                   "<=": operator.le, ">": operator.gt, ">=": operator.ge}
            lut = np.asarray([v is not None and ops[op](v, lit)
                              for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", _vand(a.valid, b.valid),
                       dt.BooleanType())
        if name in ("like", "ilike"):
            col, pat = args
            pattern = lit_str(pat)
            if pattern is None or col.dictionary is None:
                _u("non-literal LIKE")
            flags = _re.IGNORECASE if name == "ilike" else 0
            rxp = _re.compile(like_pattern_to_regex(
                pattern, dict(r.options).get("escape")), flags)
            vals = _dict_strings(col.dictionary)
            lut = np.asarray([v is not None and bool(rxp.fullmatch(v))
                              for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", col.valid, dt.BooleanType())
        if name == "rlike":
            col, pat = args
            pattern = lit_str(pat)
            if pattern is None or col.dictionary is None:
                _u("non-literal RLIKE")
            rxp = _re.compile(pattern)
            vals = _dict_strings(col.dictionary)
            lut = np.asarray([v is not None and bool(rxp.search(v))
                              for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", col.valid, dt.BooleanType())
        if name == "in":
            col = args[0]
            if col.dictionary is None:
                _u("IN over non-dictionary string")
            items = set()
            for a in args[1:]:
                s = lit_str(a)
                if s is None:
                    _u("non-literal IN item")
                items.add(s)
            vals = _dict_strings(col.dictionary)
            lut = np.asarray([v in items for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", col.valid, dt.BooleanType())
        _u(f"string function {name!r}")

    # ---------------- pipeline + aggregate assembly ----------------
    def build(self) -> Tuple[str, dict]:
        p = self.p
        # 1. bottom environment: lazy loads guarded by nothing (loads are
        # pure reads; dead rows read garbage that the sel guard discards)
        env: Dict[int, Val] = {}
        for i, f in enumerate(self.bottom_schema):
            ct = "int32_t" if _is_str(f.dtype) else _ctype_of(f.dtype)
            ptr = self._col_ptr(i, ct)
            valid = None
            if self.validity_present[i]:
                valid = f"({self._validity_ptr(i)}[i] != 0)"
            env[i] = Val(f"{ptr}[i]", valid, f.dtype, self.dicts.get(i))

        # 2. chain (stored top-down; emit bottom-up): filters become
        # guards, projects re-bind the env
        for node in reversed(self.chain):
            if isinstance(node, pn.FilterExec):
                c = self.emit(node.condition, env)
                cond = _vand(c.valid, f"(bool)({c.code})") \
                    or f"(bool)({c.code})"
                self.stmts.append(f"if (!({cond})) continue;")
            elif isinstance(node, pn.ProjectExec):
                new_env: Dict[int, Val] = {}
                for j, (name_, e) in enumerate(node.exprs):
                    v = self.emit(e, env)
                    # materialize into a local so downstream refs share it
                    if v.code.isidentifier() or _is_str(v.dtype):
                        new_env[j] = v
                    else:
                        ct = ("int32_t" if _is_str(v.dtype)
                              else _ctype_of(v.dtype))
                        t = self._fresh("p")
                        self.stmts.append(f"{ct} {t} = ({ct})({v.code});")
                        nv = v.valid
                        if nv is not None and not nv.isidentifier():
                            self.stmts.append(f"bool {t}_ok = {nv};")
                            nv = f"{t}_ok"
                        new_env[j] = Val(t, nv, v.dtype, v.dictionary)
                env = new_env
            else:
                _u(f"chain node {type(node).__name__}")

        # 3. group binning (direct domains: dictionary codes / booleans)
        in_schema = p.input.schema
        domains: List[int] = []
        key_vals: List[Val] = []
        for gi in p.group_indices:
            v = env.get(gi)
            if v is None:
                _u("group key not in environment")
            if v.dictionary is not None and _is_str(v.dtype):
                domains.append(len(v.dictionary))
            elif isinstance(v.dtype, dt.BooleanType):
                domains.append(2)
            else:
                _u("group key without small known domain")
            key_vals.append(v)
        strides: List[int] = []
        total = 1
        for d in reversed(domains):
            strides.insert(0, total)
            total *= (d + 1)
        if total > 65536:
            _u("group domain too large for direct binning")
        nseg = max(total, 1)
        seg_terms = []
        for v, d, s in zip(key_vals, domains, strides):
            code = f"(int64_t)({v.code})"
            if v.valid is not None:
                code = f"(({v.valid}) ? {code} : {d}LL)"
            seg_terms.append(f"{code} * {s}LL")
        seg = " + ".join(seg_terms) if seg_terms else "0"
        self.stmts.append(f"int64_t seg = {seg};")
        self.stmts.append("cnt_rows[seg] += 1;")

        # 4. aggregates
        f64_slots: List[int] = []
        i64_slots: List[int] = []
        agg_meta = []
        for j, a in enumerate(p.aggs):
            if a.distinct:
                _u("distinct agg")
            if a.fn not in ("sum", "count", "min", "max"):
                _u(f"aggregate {a.fn!r}")
            arg = None
            if a.arg is not None:
                arg = env.get(a.arg)
                if arg is None:
                    _u("agg arg not in environment")
                if _is_str(arg.dtype) or arg.dtype.physical_dtype is None:
                    _u("agg over non-numeric")
            filt = None
            if a.filter is not None:
                fv = self.emit(a.filter, env)
                filt = _vand(fv.valid, f"(bool)({fv.code})") \
                    or f"(bool)({fv.code})"
            if a.fn == "count":
                slot = ("i64", len(i64_slots))
                i64_slots.append(j)
                acc = f"acci[seg * {{NI}} + {slot[1]}]"
                guard = filt
                if arg is not None and arg.valid is not None:
                    guard = _vand(guard and f"({guard})", arg.valid) \
                        if guard else arg.valid
                stmt = f"{acc} += 1;"
                if guard:
                    stmt = f"if ({guard}) {{ {stmt} }}"
                self.stmts.append(stmt)
                agg_meta.append({"fn": "count", "slot": slot,
                                 "dtype": a.out_dtype})
                continue
            # sum/min/max: float args accumulate in f64, everything else
            # (ints, unscaled decimals, bools) in i64 — mirrors the device
            # path's dtype behavior
            use_f64 = _is_float(arg.dtype)
            if use_f64:
                slot = ("f64", len(f64_slots))
                f64_slots.append(j)
                acc = f"accd[seg * {{NF}} + {slot[1]}]"
                val = f"(double)({arg.code})"
            else:
                slot = ("i64", len(i64_slots))
                i64_slots.append(j)
                acc = f"acci[seg * {{NI}} + {slot[1]}]"
                val = f"(int64_t)({arg.code})"
            nn = f"cnt_nn[seg * {{NA}} + {j}]"
            if a.fn == "sum":
                if not use_f64:
                    body = (f"{acc} = (int64_t)((uint64_t){acc} + "
                            f"(uint64_t)({val})); {nn} += 1;")
                else:
                    body = f"{acc} += {val}; {nn} += 1;"
            elif a.fn == "min":
                body = (f"if (!{nn} || ({val}) < {acc}) {acc} = {val}; "
                        f"{nn} += 1;")
            else:
                body = (f"if (!{nn} || ({val}) > {acc}) {acc} = {val}; "
                        f"{nn} += 1;")
            guard = filt
            if arg.valid is not None:
                guard = _vand(guard and f"({guard})", arg.valid) \
                    if guard else arg.valid
            if guard:
                body = f"if ({guard}) {{ {body} }}"
            self.stmts.append(body)
            agg_meta.append({"fn": a.fn, "slot": slot, "dtype": a.out_dtype,
                             "arg_dtype": arg.dtype})

        nf, ni, na = max(len(f64_slots), 1), max(len(i64_slots), 1), \
            max(len(p.aggs), 1)
        body = "\n      ".join(s.replace("{NF}", str(nf))
                               .replace("{NI}", str(ni))
                               .replace("{NA}", str(na))
                               for s in self.stmts)
        sel_slot = self._slot("sel", None)
        source = f"""
#include <cstdint>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

static void run_range(const void** data, int64_t lo, int64_t hi,
                      double* accd, int64_t* acci,
                      int64_t* cnt_rows, int64_t* cnt_nn) {{
  const uint8_t* selp = (const uint8_t*)data[{sel_slot}];
  for (int64_t i = lo; i < hi; ++i) {{
      if (!selp[i]) continue;
      {body}
  }}
}}

extern "C" void run(const void** data, int64_t n,
                    double* accd, int64_t* acci,
                    int64_t* cnt_rows, int64_t* cnt_nn) {{
  int64_t nseg = {nseg};
  unsigned hw = std::thread::hardware_concurrency();
  int nt = (int)std::min<int64_t>(hw ? hw : 1, std::max<int64_t>(n / 1000000, 1));
  if (nt <= 1) {{
    run_range(data, 0, n, accd, acci, cnt_rows, cnt_nn);
    return;
  }}
  std::vector<std::vector<double>> ad(nt);
  std::vector<std::vector<int64_t>> ai(nt), cr(nt), cn(nt);
  std::vector<std::thread> ts;
  int64_t per = (n + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {{
    ad[t].assign(nseg * {nf}, 0.0);
    ai[t].assign(nseg * {ni}, 0);
    cr[t].assign(nseg, 0);
    cn[t].assign(nseg * {na}, 0);
    int64_t lo = t * per, hi = std::min(n, lo + per);
    ts.emplace_back(run_range, data, lo, hi, ad[t].data(), ai[t].data(),
                    cr[t].data(), cn[t].data());
  }}
  for (auto& th : ts) th.join();
  for (int t = 0; t < nt; ++t) {{
    for (int64_t s = 0; s < nseg; ++s) {{
      cnt_rows[s] += cr[t][s];
      {self._merge_code(agg_meta, nf, ni, na)}
    }}
  }}
}}
"""
        meta = {"nseg": nseg, "nf": nf, "ni": ni, "na": na,
                "domains": domains, "strides": strides,
                "agg_meta": agg_meta, "key_vals": key_vals}
        return source, meta

    @staticmethod
    def _merge_code(agg_meta, nf, ni, na) -> str:
        lines = []
        for j, m in enumerate(agg_meta):
            kind, off = m["slot"]
            if kind == "f64":
                acc, part = f"accd[s * {nf} + {off}]", f"ad[t][s * {nf} + {off}]"
            else:
                acc, part = f"acci[s * {ni} + {off}]", f"ai[t][s * {ni} + {off}]"
            nng = f"cn[t][s * {na} + {j}]"
            nn = f"cnt_nn[s * {na} + {j}]"
            if m["fn"] in ("sum", "count"):
                if m["fn"] == "count":
                    lines.append(f"{acc} += {part};")
                else:
                    if kind == "i64":
                        lines.append(
                            f"if ({nng}) {{ {acc} = (int64_t)((uint64_t){acc}"
                            f" + (uint64_t){part}); {nn} += {nng}; }}")
                    else:
                        lines.append(
                            f"if ({nng}) {{ {acc} += {part}; {nn} += {nng}; }}")
            elif m["fn"] == "min":
                lines.append(f"if ({nng}) {{ if (!{nn} || {part} < {acc}) "
                             f"{acc} = {part}; {nn} += {nng}; }}")
            elif m["fn"] == "max":
                lines.append(f"if ({nng}) {{ if (!{nn} || {part} > {acc}) "
                             f"{acc} = {part}; {nn} += {nng}; }}")
        return "\n      ".join(lines)
