"""Fused filter→project→group-aggregate C++ codegen.

Translates a pipeline chain (Scan → Filter/Project… → Aggregate with
direct-binned group keys) into ONE C++ row loop compiled by cc.py and run
over the batch's host buffers zero-copy. This is the CPU-fallback hot path:
one pass over memory with all aggregates accumulated together, where the
XLA CPU backend would run one scatter pass per aggregate.

Reference role: DataFusion's vectorized hash-aggregate + fused filter
(crates/sail-physical-plan, SURVEY.md §2.4); semantics mirror
plan/compiler.py's device kernels exactly (decimal scale alignment,
Spark null rules, dictionary-code string ops via bind-time LUTs).

Raises NativeUnsupported for anything outside the supported subset; the
executor falls back to the jitted device path.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..plan import nodes as pn
from ..plan import rex as rx
from ..plan.compiler import like_pattern_to_regex
from ..spec import data_type as dt


class NativeUnsupported(Exception):
    pass


def _u(msg):
    raise NativeUnsupported(msg)


# C scalar types by physical dtype
_CTYPES = {"int8": "int8_t", "int16": "int16_t", "int32": "int32_t",
           "int64": "int64_t", "float32": "float", "float64": "double",
           "bool": "uint8_t"}


def _ctype_of(d: dt.DataType) -> str:
    name = d.physical_dtype
    if name is None or name not in _CTYPES:
        _u(f"no native representation for {d.simple_string()}")
    return _CTYPES[name]


def _is_str(d):
    return isinstance(d, (dt.StringType, dt.BinaryType))


def _dec_scale(d) -> Optional[int]:
    if isinstance(d, dt.DecimalType) and d.physical_dtype == "int64":
        return d.scale
    return None


def _is_float(d) -> bool:
    return d.physical_dtype in ("float32", "float64")


def _is_int(d) -> bool:
    return d.physical_dtype in ("int8", "int16", "int32", "int64")


class Val:
    """An emitted C expression: code string + validity expression (None =
    always valid) + logical dtype + optional string dictionary."""

    __slots__ = ("code", "valid", "dtype", "dictionary")

    def __init__(self, code, valid, dtype, dictionary=None):
        self.code = code
        self.valid = valid
        self.dtype = dtype
        self.dictionary = dictionary


def _vand(*vs) -> Optional[str]:
    parts = [v for v in vs if v is not None]
    if not parts:
        return None
    return "(" + " && ".join(parts) + ")"


class AggCodegen:
    """Builds the C++ source + argument plan for one fused aggregate."""

    def __init__(self, p: pn.AggregateExec, chain: List[pn.PlanNode],
                 bottom_schema: pn.Schema, dicts: Dict[int, object],
                 validity_present: Tuple[bool, ...], fold_const):
        self.p = p
        self.chain = chain
        self.bottom_schema = bottom_schema
        self.dicts = dicts                  # bottom column idx -> pa.Array
        self.validity_present = validity_present
        self.fold_const = fold_const        # rex -> (python value, dtype) | None
        self.stmts: List[str] = []          # per-row statements
        self.args: List[Tuple[str, object]] = []  # ordered array args
        self.luts: List[np.ndarray] = []    # bind-time lookup tables
        self._tmp = 0
        self._arg_slot: Dict[object, int] = {}
        # slot -> C element type for the hoisted __restrict pointer decls:
        # the accumulator buffers are freshly allocated per call and can
        # never alias the input columns, but the compiler cannot prove
        # that through the void** indirection — without the hoisted
        # restrict pointers every accumulator store forces the next
        # column load to re-read memory (measured ~2x on TPC-H q1)
        self._ptr_ctype: Dict[int, str] = {}
        # expression CSE within one env generation (the resolver's
        # pre-projection frequently repeats subexpressions, e.g. q1's
        # extendedprice*(1-discount) feeding two aggregates)
        self._emit_cache: Dict[object, Val] = {}
        self._env_gen = 0
        # CASE emission nests statements in C++ blocks; temps declared
        # there are block-scoped and must not be CSE-reused outside
        self._block_depth = 0

    # ---------------- argument slots ----------------
    def _slot(self, kind, payload) -> int:
        key = (kind, payload if kind != "lut" else id(payload))
        if key in self._arg_slot:
            return self._arg_slot[key]
        slot = len(self.args)
        self.args.append((kind, payload))
        self._arg_slot[key] = slot
        return slot

    def _ptr(self, slot: int, ctype: str) -> str:
        self._ptr_ctype[slot] = ctype
        return f"a{slot}"

    def _col_ptr(self, idx: int, ctype: str) -> str:
        return self._ptr(self._slot("col", idx), ctype)

    def _validity_ptr(self, idx: int) -> str:
        return self._ptr(self._slot("validity", idx), "uint8_t")

    def _lut_ptr(self, arr: np.ndarray, ctype: str) -> str:
        self.luts.append(arr)
        return self._ptr(self._slot("lut", arr), ctype)

    def ptr_decls(self) -> str:
        """Hoisted ``const T* __restrict`` declarations for every input
        array slot, emitted at the top of the row loop's function."""
        return "\n  ".join(
            f"const {ct}* __restrict a{slot} = "
            f"(const {ct}*)data[{slot}];"
            for slot, ct in sorted(self._ptr_ctype.items()))

    def _fresh(self, prefix="t") -> str:
        self._tmp += 1
        return f"{prefix}{self._tmp}"

    # ---------------- expression emission ----------------
    def emit(self, r: rx.Rex, env: Dict[int, Val]) -> Val:
        try:
            key = (self._env_gen, r)
            hit = self._emit_cache.get(key)
        except TypeError:
            key = None
            hit = None
        if hit is not None:
            return hit
        v = self._emit(r, env)
        if key is not None and self._block_depth == 0 and \
                not isinstance(r, rx.BoundRef):
            self._emit_cache[key] = v
        return v

    def _emit(self, r: rx.Rex, env: Dict[int, Val]) -> Val:
        folded = self._try_fold(r)
        if folded is not None:
            return folded
        if isinstance(r, rx.BoundRef):
            v = env.get(r.index)
            if v is None:
                _u(f"unbound column {r.index}")
            return v
        if isinstance(r, rx.RLit):
            return self._emit_literal(r)
        if isinstance(r, rx.RCast):
            return self._emit_cast(r, env)
        if isinstance(r, rx.RCase):
            return self._emit_case(r, env)
        if isinstance(r, rx.RCall):
            return self._emit_call(r, env)
        _u(f"cannot emit {type(r).__name__}")

    def _try_fold(self, r: rx.Rex) -> Optional[Val]:
        if isinstance(r, (rx.BoundRef, rx.RLit)):
            return None
        if any(isinstance(n, (rx.BoundRef, rx.RLambda, rx.RLambdaVar))
               for n in rx.walk(r)):
            return None
        got = self.fold_const(r)
        if got is None:
            return None
        value, dtype = got
        if value is None:
            return Val("0", "false", dtype)
        if _is_str(dtype):
            import pyarrow as pa
            return Val("0", None, dtype, pa.array([value]))
        return Val(self._const(value, dtype), None, dtype)

    @staticmethod
    def _const(v, d: dt.DataType) -> str:
        if isinstance(d, dt.BooleanType):
            return "1" if v else "0"
        if _is_float(d):
            return repr(float(v))
        return f"{int(v)}LL"

    def _emit_literal(self, r: rx.RLit) -> Val:
        v = r.value
        d = v.data_type
        if v.is_null:
            return Val("0", "false", d)
        if _is_str(d):
            import pyarrow as pa
            return Val("0", None, d, pa.array([v.value]))
        pv = v.physical_value()
        if isinstance(pv, (bool, int, float)):
            return Val(self._const(pv, d), None, d)
        _u(f"literal {type(pv).__name__}")

    # cast semantics mirror plan/compiler.py::_compile_cast
    def _emit_cast(self, r: rx.RCast, env) -> Val:
        child = self.emit(r.child, env)
        src, dst = child.dtype, r.dtype
        if src == dst:
            return child
        if _is_str(src) or _is_str(dst):
            if _is_str(src) and child.dictionary is not None \
                    and not _is_str(dst):
                return self._dict_lut_cast(child, dst)
            _u("string cast")
        ss, ds_ = _dec_scale(src), _dec_scale(dst)
        x = child.code
        if ss is not None and ds_ is None:
            x = f"((double)({x}) / {10.0 ** ss!r})"
            src_f = True
        else:
            src_f = _is_float(src)
        if ds_ is not None:
            if ss is not None:
                if ds_ >= ss:
                    x = f"(({x}) * {10 ** (ds_ - ss)}LL)"
                else:
                    f = 10 ** (ss - ds_)
                    t = self._fresh("c")
                    self.stmts.append(f"int64_t {t} = {x};")
                    x = (f"({t} >= 0 ? ({t} + {f // 2}LL) / {f}LL"
                         f" : -((-{t} + {f // 2}LL) / {f}LL))")
            elif src_f:
                t = self._fresh("c")
                self.stmts.append(
                    f"double {t} = ({x}) * {10.0 ** ds_!r};")
                x = (f"(int64_t)({t} >= 0 ? floor({t} + 0.5)"
                     f" : -floor(-{t} + 0.5))")
            else:
                x = f"((int64_t)({x}) * {10 ** ds_}LL)"
            return Val(x, child.valid, dst)
        ct = _ctype_of(dst)
        if isinstance(dst, dt.BooleanType):
            return Val(f"(({x}) != 0)", child.valid, dst)
        return Val(f"(({ct})({x}))", child.valid, dst)

    def _dict_lut_cast(self, child: Val, dst: dt.DataType) -> Val:
        from ..plan.compiler import _dict_strings, _parse_string_value
        vals = _dict_strings(child.dictionary)
        out, ok = [], []
        for s in vals:
            v, good = _parse_string_value(s, dst)
            out.append(v)
            ok.append(good)
        npdt = np.dtype(dst.physical_dtype or "int64")
        lutp = self._lut_ptr(np.asarray(out, dtype=npdt), _CTYPES[npdt.name])
        okp = self._lut_ptr(np.asarray(ok, dtype=np.uint8), "uint8_t")
        code = f"{lutp}[{child.code}]"
        valid = _vand(child.valid, f"{okp}[{child.code}]")
        return Val(code, valid, dst)

    def _emit_case(self, r: rx.RCase, env) -> Val:
        if _is_str(r.dtype):
            _u("string CASE")
        ct = _ctype_of(r.dtype)
        out = self._fresh("cs")
        okv = f"{out}_ok"
        self.stmts.append(f"{ct} {out} = 0; bool {okv} = false;")
        closes = 0
        self._block_depth += 1
        try:
            for cond, val in r.branches:
                c = self.emit(cond, env)
                cc = _vand(c.valid, f"(bool)({c.code})") \
                    or f"(bool)({c.code})"
                v = self.emit(val, env)
                self.stmts.append(f"if ({cc}) {{ {out} = ({ct})({v.code}); "
                                  f"{okv} = {v.valid or 'true'}; }} else {{")
                closes += 1
            if r.else_value is not None:
                v = self.emit(r.else_value, env)
                self.stmts.append(f"{out} = ({ct})({v.code}); "
                                  f"{okv} = {v.valid or 'true'};")
        finally:
            self._block_depth -= 1
        self.stmts.append("}" * closes)
        return Val(out, okv, r.dtype)

    # ---------------- calls ----------------
    _CMP = {"==": "==", "!=": "!=", "<": "<", "<=": "<=",
            ">": ">", ">=": ">="}

    def _emit_call(self, r: rx.RCall, env) -> Val:
        name = r.fn
        if name in ("and", "or"):
            return self._emit_kleene(name, r, env)
        if name == "not":
            a = self.emit(r.args[0], env)
            return Val(f"(!(bool)({a.code}))", a.valid, dt.BooleanType())
        if name == "isnull":
            a = self.emit(r.args[0], env)
            return Val(f"(!({a.valid or 'true'}))", None, dt.BooleanType())
        if name == "isnotnull":
            a = self.emit(r.args[0], env)
            return Val(f"({a.valid or 'true'})", None, dt.BooleanType())
        args = [self.emit(a, env) for a in r.args]
        str_args = [a for a in args if _is_str(a.dtype)]
        if str_args:
            return self._emit_string_call(name, r, args)
        if name in self._CMP:
            return self._emit_cmp(name, args, r)
        if name in ("+", "-", "*"):
            return self._emit_arith(name, args, r)
        if name == "/":
            return self._emit_div(args)
        if name == "in":
            return self._emit_in(args)
        if name in ("if",):
            c, t, f = args
            code = (f"((bool)({c.code}) && {c.valid or 'true'} ? "
                    f"({t.code}) : ({f.code}))")
            valid = None
            if t.valid is not None or f.valid is not None:
                valid = (f"((bool)({c.code}) && {c.valid or 'true'} ? "
                         f"({t.valid or 'true'}) : ({f.valid or 'true'}))")
            return Val(code, valid, r.dtype)
        if name == "coalesce":
            return self._emit_coalesce(args, r)
        if name in ("year", "month", "day", "dayofmonth", "quarter"):
            return self._emit_date_field(name, args[0], r)
        if name in ("negative", "abs"):
            a = args[0]
            if name == "negative":
                return Val(f"(-({a.code}))", a.valid, r.dtype)
            fn = "fabs" if _is_float(a.dtype) else "llabs"
            return Val(f"({fn}({a.code}))", a.valid, r.dtype)
        _u(f"function {name!r}")

    def _emit_kleene(self, name, r, env) -> Val:
        a = self.emit(r.args[0], env)
        b = self.emit(r.args[1], env)
        if a.valid is None and b.valid is None:
            op = "&&" if name == "and" else "||"
            return Val(f"((bool)({a.code}) {op} (bool)({b.code}))", None,
                       dt.BooleanType())
        ad, av = f"(bool)({a.code})", a.valid or "true"
        bd, bv = f"(bool)({b.code})", b.valid or "true"
        t = self._fresh("k")
        if name == "and":
            # false if either side is definitively false
            self.stmts.append(
                f"bool {t}_af = ({av}) && !({ad});"
                f" bool {t}_bf = ({bv}) && !({bd});"
                f" bool {t}_ok = {t}_af || {t}_bf || (({av}) && ({bv}));"
                f" bool {t} = !({t}_af || {t}_bf) && ({ad}) && ({bd});")
        else:
            self.stmts.append(
                f"bool {t}_at = ({av}) && ({ad});"
                f" bool {t}_bt = ({bv}) && ({bd});"
                f" bool {t}_ok = {t}_at || {t}_bt || (({av}) && ({bv}));"
                f" bool {t} = {t}_at || {t}_bt;")
        return Val(t, f"{t}_ok", dt.BooleanType())

    def _align_decimals(self, a: Val, b: Val) -> Tuple[str, str, bool]:
        """Scale-align two numeric operands (mirrors _binary_numeric)."""
        sa, sb = _dec_scale(a.dtype), _dec_scale(b.dtype)
        x, y = a.code, b.code
        if sa is None and sb is None:
            if _is_float(a.dtype) or _is_float(b.dtype):
                return f"((double)({x}))", f"((double)({y}))", True
            return x, y, False
        s = max(sa or 0, sb or 0)
        fa, fb = _is_float(a.dtype), _is_float(b.dtype)
        if fa or fb:
            xs = x if sa is None else f"((double)({x}) / {10.0 ** sa!r})"
            ys = y if sb is None else f"((double)({y}) / {10.0 ** sb!r})"
            return f"((double)({xs}))", f"((double)({ys}))", True
        if sa is not None:
            x = f"(({x}) * {10 ** (s - sa)}LL)" if s > sa else f"({x})"
        else:
            x = f"((int64_t)({x}) * {10 ** s}LL)"
        if sb is not None:
            y = f"(({y}) * {10 ** (s - sb)}LL)" if s > sb else f"({y})"
        else:
            y = f"((int64_t)({y}) * {10 ** s}LL)"
        return x, y, False

    def _emit_cmp(self, name, args, r) -> Val:
        a, b = args
        x, y, _ = self._align_decimals(a, b)
        return Val(f"(({x}) {self._CMP[name]} ({y}))",
                   _vand(a.valid, b.valid), dt.BooleanType())

    def _emit_arith(self, name, args, r) -> Val:
        a, b = args
        valid = _vand(a.valid, b.valid)
        sa, sb = _dec_scale(a.dtype), _dec_scale(b.dtype)
        so = _dec_scale(r.dtype)
        ct = _ctype_of(r.dtype)
        if name in ("+", "-"):
            x, y, _ = self._align_decimals(a, b)
            return Val(f"(({ct})(({x}) {name} ({y})))", valid, r.dtype)
        # multiply: raw product then half-up rescale (compiler.py parity)
        x, y = a.code, b.code
        if _is_float(a.dtype) or _is_float(b.dtype) or \
                (sa is None and sb is None):
            if sa is not None:
                x = f"((double)({x}) / {10.0 ** sa!r})"
            if sb is not None:
                y = f"((double)({y}) / {10.0 ** sb!r})"
            return Val(f"(({ct})(({x}) * ({y})))", valid, r.dtype)
        extra = 0
        if sa is not None and sb is not None and so is not None:
            extra = sa + sb - so
        elif so is not None and (sa is None) != (sb is None):
            extra = (sa or 0) + (sb or 0) - so
        t = self._fresh("m")
        self.stmts.append(
            f"int64_t {t} = (int64_t)({x}) * (int64_t)({y});")
        if extra > 0:
            f = 10 ** extra
            return Val(f"({t} >= 0 ? ({t} + {f // 2}LL) / {f}LL"
                       f" : -((-{t} + {f // 2}LL) / {f}LL))", valid, r.dtype)
        return Val(t, valid, r.dtype)

    def _emit_div(self, args) -> Val:
        a, b = args
        sa, sb = _dec_scale(a.dtype), _dec_scale(b.dtype)
        x = a.code if sa is None else f"((double)({a.code}) / {10.0 ** sa!r})"
        y = b.code if sb is None else f"((double)({b.code}) / {10.0 ** sb!r})"
        t = self._fresh("dv")
        self.stmts.append(f"double {t}_y = (double)({y});"
                          f" double {t} = (double)({x}) /"
                          f" ({t}_y == 0.0 ? 1.0 : {t}_y);")
        return Val(t, _vand(a.valid, b.valid, f"({t}_y != 0.0)"),
                   dt.DoubleType())

    def _emit_in(self, args) -> Val:
        child = args[0]
        sc = _dec_scale(child.dtype)
        hits = []
        valid_terms = []
        for it in args[1:]:
            si = _dec_scale(it.dtype)
            x, y = child.code, it.code
            if sc is not None or si is not None:
                s = max(sc or 0, si or 0)
                if sc is not None and s > sc:
                    x = f"(({x}) * {10 ** (s - sc)}LL)"
                if si is not None and s > si:
                    y = f"(({y}) * {10 ** (s - si)}LL)"
            term = f"(({x}) == ({y}))"
            if it.valid is not None:
                term = f"(({it.valid}) && {term})"
            hits.append(term)
        return Val("(" + " || ".join(hits) + ")", child.valid,
                   dt.BooleanType())

    def _emit_coalesce(self, args, r) -> Val:
        ct = _ctype_of(r.dtype)
        out = self._fresh("co")
        self.stmts.append(f"{ct} {out} = 0; bool {out}_ok = false;")
        for a in args:
            self.stmts.append(f"if (!{out}_ok && ({a.valid or 'true'})) "
                              f"{{ {out} = ({ct})({a.code}); {out}_ok = true; }}")
        return Val(out, f"{out}_ok", r.dtype)

    def _emit_date_field(self, name, a: Val, r) -> Val:
        if not isinstance(a.dtype, dt.DateType):
            _u(f"{name} over non-date")
        t = self._fresh("dc")
        self.stmts.append(
            f"int64_t {t}_z = (int64_t)({a.code}) + 719468;"
            f" int64_t {t}_era = ({t}_z >= 0 ? {t}_z : {t}_z - 146096) / 146097;"
            f" int64_t {t}_doe = {t}_z - {t}_era * 146097;"
            f" int64_t {t}_yoe = ({t}_doe - {t}_doe/1460 + {t}_doe/36524 - {t}_doe/146096) / 365;"
            f" int64_t {t}_y = {t}_yoe + {t}_era * 400;"
            f" int64_t {t}_doy = {t}_doe - (365*{t}_yoe + {t}_yoe/4 - {t}_yoe/100);"
            f" int64_t {t}_mp = (5*{t}_doy + 2)/153;"
            f" int64_t {t}_d = {t}_doy - (153*{t}_mp+2)/5 + 1;"
            f" int64_t {t}_m = {t}_mp < 10 ? {t}_mp+3 : {t}_mp-9;"
            f" if ({t}_m <= 2) {t}_y += 1;")
        if name == "year":
            code = f"((int32_t){t}_y)"
        elif name == "month":
            code = f"((int32_t){t}_m)"
        elif name == "quarter":
            code = f"((int32_t)(({t}_m - 1)/3 + 1))"
        else:
            code = f"((int32_t){t}_d)"
        return Val(code, a.valid, r.dtype)

    # ---------------- string (dictionary LUT) calls ----------------
    def _emit_string_call(self, name, r, args) -> Val:
        from ..plan.compiler import _dict_strings
        import re as _re

        def lit_str(a: Val) -> Optional[str]:
            if a.dictionary is not None and len(a.dictionary) == 1:
                return _dict_strings(a.dictionary)[0]
            return None

        if name in ("==", "!=", "<", "<=", ">", ">="):
            a, b = args
            if not (_is_str(a.dtype) and _is_str(b.dtype)):
                _u("mixed string comparison")
            # column vs literal → bool LUT over codes
            col, lit, flip = (a, lit_str(b), False)
            if lit is None:
                col, lit, flip = (b, lit_str(a), True)
            if lit is None or col.dictionary is None:
                _u("string cmp needs a literal side")
            vals = _dict_strings(col.dictionary)
            op = name if not flip else {"<": ">", "<=": ">=", ">": "<",
                                        ">=": "<=", "==": "==",
                                        "!=": "!="}[name]
            import operator
            ops = {"==": operator.eq, "!=": operator.ne, "<": operator.lt,
                   "<=": operator.le, ">": operator.gt, ">=": operator.ge}
            lut = np.asarray([v is not None and ops[op](v, lit)
                              for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", _vand(a.valid, b.valid),
                       dt.BooleanType())
        if name in ("like", "ilike"):
            col, pat = args
            pattern = lit_str(pat)
            if pattern is None or col.dictionary is None:
                _u("non-literal LIKE")
            flags = _re.IGNORECASE if name == "ilike" else 0
            rxp = _re.compile(like_pattern_to_regex(
                pattern, dict(r.options).get("escape")), flags)
            vals = _dict_strings(col.dictionary)
            lut = np.asarray([v is not None and bool(rxp.fullmatch(v))
                              for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", col.valid, dt.BooleanType())
        if name == "rlike":
            col, pat = args
            pattern = lit_str(pat)
            if pattern is None or col.dictionary is None:
                _u("non-literal RLIKE")
            rxp = _re.compile(pattern)
            vals = _dict_strings(col.dictionary)
            lut = np.asarray([v is not None and bool(rxp.search(v))
                              for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", col.valid, dt.BooleanType())
        if name == "in":
            col = args[0]
            if col.dictionary is None:
                _u("IN over non-dictionary string")
            items = set()
            for a in args[1:]:
                s = lit_str(a)
                if s is None:
                    _u("non-literal IN item")
                items.add(s)
            vals = _dict_strings(col.dictionary)
            lut = np.asarray([v in items for v in vals], dtype=np.uint8)
            p = self._lut_ptr(lut, "uint8_t")
            return Val(f"{p}[{col.code}]", col.valid, dt.BooleanType())
        _u(f"string function {name!r}")

    # ---------------- pipeline + aggregate assembly ----------------
    def build(self) -> Tuple[str, dict]:
        p = self.p
        # 1. bottom environment: lazy loads guarded by nothing (loads are
        # pure reads; dead rows read garbage that the sel guard discards)
        env: Dict[int, Val] = {}
        for i, f in enumerate(self.bottom_schema):
            ct = "int32_t" if _is_str(f.dtype) else _ctype_of(f.dtype)
            ptr = self._col_ptr(i, ct)
            valid = None
            if self.validity_present[i]:
                valid = f"({self._validity_ptr(i)}[i] != 0)"
            env[i] = Val(f"{ptr}[i]", valid, f.dtype, self.dicts.get(i))

        # 2. chain (stored top-down; emit bottom-up): filters become
        # guards, projects re-bind the env
        for node in reversed(self.chain):
            if isinstance(node, pn.FilterExec):
                c = self.emit(node.condition, env)
                cond = _vand(c.valid, f"(bool)({c.code})") \
                    or f"(bool)({c.code})"
                self.stmts.append(f"if (!({cond})) continue;")
            elif isinstance(node, pn.ProjectExec):
                new_env: Dict[int, Val] = {}
                for j, (name_, e) in enumerate(node.exprs):
                    v = self.emit(e, env)
                    # materialize into a local so downstream refs share it
                    if v.code.isidentifier() or _is_str(v.dtype):
                        new_env[j] = v
                    else:
                        ct = ("int32_t" if _is_str(v.dtype)
                              else _ctype_of(v.dtype))
                        t = self._fresh("p")
                        self.stmts.append(f"{ct} {t} = ({ct})({v.code});")
                        nv = v.valid
                        if nv is not None and not nv.isidentifier():
                            self.stmts.append(f"bool {t}_ok = {nv};")
                            nv = f"{t}_ok"
                        new_env[j] = Val(t, nv, v.dtype, v.dictionary)
                env = new_env
                # BoundRefs now resolve against the new projection: CSE
                # entries from the previous binding must not be reused
                self._env_gen += 1
            else:
                _u(f"chain node {type(node).__name__}")

        # 3. group binning. Two strategies, mirroring DataFusion's grouped
        # accumulator design (SURVEY.md §2.4): direct segment binning when
        # every key has a small known domain (dictionary codes / booleans),
        # otherwise an open-addressing hash table over the int64-encoded
        # key tuple (plain ints, dates, decimals, floats, high-cardinality
        # dictionary codes).
        in_schema = p.input.schema
        domains: List[int] = []
        key_vals: List[Val] = []
        seg_mode = True
        for gi in p.group_indices:
            v = env.get(gi)
            if v is None:
                _u("group key not in environment")
            key_vals.append(v)
            if v.dictionary is not None and _is_str(v.dtype):
                domains.append(len(v.dictionary))
            elif isinstance(v.dtype, dt.BooleanType):
                domains.append(2)
            elif v.dtype.physical_dtype is not None:
                seg_mode = False
            else:
                _u(f"group key type {v.dtype.simple_string()}")
        strides: List[int] = []
        nseg = 1
        if seg_mode:
            total = 1
            for d in reversed(domains):
                strides.insert(0, total)
                total *= (d + 1)
            if total > 65536:
                seg_mode = False
            else:
                nseg = max(total, 1)
        if seg_mode:
            seg_terms = []
            for v, d, s in zip(key_vals, domains, strides):
                code = f"(int64_t)({v.code})"
                if v.valid is not None:
                    code = f"(({v.valid}) ? {code} : {d}LL)"
                seg_terms.append(f"{code} * {s}LL")
            seg = " + ".join(seg_terms) if seg_terms else "0"
            self.stmts.append(f"int64_t seg = {seg};")
            # interleaved per-seg accumulator block (one cache line
            # covers a group's row count + every i64 slot + null counts):
            # AI[seg*SI + 0]=rows, +1..=i64 slots, +CN..=null counts;
            # f64 slots live in AD[seg*NF + k]
            self.stmts.append("AI[seg * {SI}] += 1;")
        else:
            domains, strides = [], []
            self._emit_hash_keys(key_vals)
            self.stmts.append("cnt_rows[seg] += 1;")

        # 4. aggregates
        f64_slots: List[int] = []
        i64_slots: List[int] = []
        agg_meta = []
        for j, a in enumerate(p.aggs):
            if a.distinct:
                _u("distinct agg")
            if a.fn not in ("sum", "count", "min", "max"):
                _u(f"aggregate {a.fn!r}")
            arg = None
            if a.arg is not None:
                arg = env.get(a.arg)
                if arg is None:
                    _u("agg arg not in environment")
                if _is_str(arg.dtype) or arg.dtype.physical_dtype is None:
                    _u("agg over non-numeric")
            filt = None
            if a.filter is not None:
                fv = self.emit(a.filter, env)
                filt = _vand(fv.valid, f"(bool)({fv.code})") \
                    or f"(bool)({fv.code})"
            if a.fn == "count":
                guard = filt
                if arg is not None and arg.valid is not None:
                    guard = _vand(guard and f"({guard})", arg.valid) \
                        if guard else arg.valid
                if guard is None:
                    # unguarded COUNT ≡ the per-group row count the kernel
                    # already tracks — emit nothing, read cnt_rows later
                    agg_meta.append({"fn": "count", "slot": ("rows", 0),
                                     "dtype": a.out_dtype})
                    continue
                slot = ("i64", len(i64_slots))
                i64_slots.append(j)
                acc = (f"AI[seg * {{SI}} + {1 + slot[1]}]" if seg_mode
                       else f"acci[seg * {{NI}} + {slot[1]}]")
                self.stmts.append(f"if ({guard}) {{ {acc} += 1; }}")
                agg_meta.append({"fn": "count", "slot": slot,
                                 "dtype": a.out_dtype})
                continue
            # sum/min/max: float args accumulate in f64, everything else
            # (ints, unscaled decimals, bools) in i64 — mirrors the device
            # path's dtype behavior
            use_f64 = _is_float(arg.dtype)
            if use_f64:
                slot = ("f64", len(f64_slots))
                f64_slots.append(j)
                acc = f"AD[seg * {{NF}} + {slot[1]}]" if seg_mode \
                    else f"accd[seg * {{NF}} + {slot[1]}]"
                val = f"(double)({arg.code})"
            else:
                slot = ("i64", len(i64_slots))
                i64_slots.append(j)
                acc = (f"AI[seg * {{SI}} + {1 + slot[1]}]" if seg_mode
                       else f"acci[seg * {{NI}} + {slot[1]}]")
                val = f"(int64_t)({arg.code})"
            guard = filt
            if arg.valid is not None:
                guard = _vand(guard and f"({guard})", arg.valid) \
                    if guard else arg.valid
            # unguarded SUM never needs a non-null counter: every row of an
            # existing group contributes, so validity is just "group
            # exists". min/max always track it (first-touch initializer).
            track_nn = guard is not None or a.fn in ("min", "max")
            nn = f"AI[seg * {{SI}} + {{CN}} + {j}]" if seg_mode \
                else f"cnt_nn[seg * {{NA}} + {j}]"
            if a.fn == "sum":
                bump = f" {nn} += 1;" if track_nn else ""
                if not use_f64:
                    body = (f"{acc} = (int64_t)((uint64_t){acc} + "
                            f"(uint64_t)({val}));{bump}")
                else:
                    body = f"{acc} += {val};{bump}"
            elif a.fn == "min":
                body = (f"if (!{nn} || ({val}) < {acc}) {acc} = {val}; "
                        f"{nn} += 1;")
            else:
                body = (f"if (!{nn} || ({val}) > {acc}) {acc} = {val}; "
                        f"{nn} += 1;")
            if guard:
                body = f"if ({guard}) {{ {body} }}"
            self.stmts.append(body)
            agg_meta.append({"fn": a.fn, "slot": slot, "dtype": a.out_dtype,
                             "arg_dtype": arg.dtype, "nn": track_nn})

        nf, ni, na = max(len(f64_slots), 1), max(len(i64_slots), 1), \
            max(len(p.aggs), 1)
        # interleaved accumulator block strides (segment mode): one
        # int64 row per seg = [row_count, i64 slots…, null counts…]
        si = 1 + len(i64_slots) + len(p.aggs)
        cn = 1 + len(i64_slots)
        body = "\n      ".join(s.replace("{NF}", str(nf))
                               .replace("{NI}", str(ni))
                               .replace("{NA}", str(na))
                               .replace("{SI}", str(si))
                               .replace("{CN}", str(cn))
                               for s in self.stmts)
        sel_slot = self._slot("sel", None)
        if not seg_mode:
            source = self._hash_source(body, sel_slot, len(key_vals),
                                       nf, ni, na, agg_meta)
            meta = {"mode": "hash", "nf": nf, "ni": ni, "na": na,
                    "nseg": 0, "domains": [], "strides": [],
                    "agg_meta": agg_meta, "key_vals": key_vals}
            return source, meta
        merge = self._interleaved_merge(agg_meta, si, cn, nf)
        copyout = self._interleaved_copyout(agg_meta, si, cn, nf, ni, na,
                                            len(p.aggs))
        source = f"""
#include <cstdint>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

template <bool DENSE>
static void run_range(const void** data, int64_t lo, int64_t hi,
                      int64_t* __restrict AI, double* __restrict AD) {{
  {self.ptr_decls()}
  const uint8_t* __restrict selp = (const uint8_t*)data[{sel_slot}];
  for (int64_t i = lo; i < hi; ++i) {{
      if (!DENSE && !selp[i]) continue;
      {body}
  }}
}}

// A selection that is all-true up to some prefix (the common case for a
// freshly scanned batch: live rows then padding) lets the hot loop skip
// the per-row mask load entirely. Two SIMD memchr sweeps decide it.
static int64_t dense_prefix(const uint8_t* selp, int64_t n) {{
  const void* z = memchr(selp, 0, (size_t)n);
  int64_t k = z ? (const uint8_t*)z - selp : n;
  if (k < n && memchr(selp + k, 1, (size_t)(n - k)) != nullptr)
    return -1;  // holes: not a prefix mask
  return k;
}}

static void run_part(const void** data, int64_t lo, int64_t hi,
                     int64_t* AI, double* AD) {{
  const uint8_t* selp = (const uint8_t*)data[{sel_slot}];
  int64_t k = dense_prefix(selp + lo, hi - lo);
  if (k >= 0)
    run_range<true>(data, lo, lo + k, AI, AD);
  else
    run_range<false>(data, lo, hi, AI, AD);
}}

extern "C" void run(const void** data, int64_t n,
                    double* accd, int64_t* acci,
                    int64_t* cnt_rows, int64_t* cnt_nn) {{
  const int64_t nseg = {nseg};
  unsigned hw = std::thread::hardware_concurrency();
  int nt = (int)std::min<int64_t>(hw ? hw : 1, std::max<int64_t>(n / 1000000, 1));
  std::vector<std::vector<int64_t>> ai(nt);
  std::vector<std::vector<double>> ad(nt);
  for (int t = 0; t < nt; ++t) {{
    ai[t].assign(nseg * {si}, 0);
    ad[t].assign(nseg * {nf}, 0.0);
  }}
  if (nt <= 1) {{
    run_part(data, 0, n, ai[0].data(), ad[0].data());
  }} else {{
    std::vector<std::thread> ts;
    int64_t per = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {{
      int64_t lo = t * per, hi = std::min(n, lo + per);
      ts.emplace_back(run_part, data, lo, hi, ai[t].data(), ad[t].data());
    }}
    for (auto& th : ts) th.join();
    int64_t* __restrict bi = ai[0].data();
    double* __restrict bd = ad[0].data();
    for (int t = 1; t < nt; ++t) {{
      const int64_t* __restrict pi = ai[t].data();
      const double* __restrict pd = ad[t].data();
      for (int64_t s = 0; s < nseg; ++s) {{
        {merge}
      }}
    }}
  }}
  {{
    const int64_t* __restrict bi = ai[0].data();
    const double* __restrict bd = ad[0].data();
    for (int64_t s = 0; s < nseg; ++s) {{
      {copyout}
    }}
  }}
}}
"""
        meta = {"mode": "segment", "nseg": nseg, "nf": nf, "ni": ni,
                "na": na, "domains": domains, "strides": strides,
                "agg_meta": agg_meta, "key_vals": key_vals}
        return source, meta

    @staticmethod
    def _interleaved_merge(agg_meta, si: int, cn: int, nf: int) -> str:
        """Per-seg statements folding one thread's interleaved partial
        block (pi/pd) into the base block (bi/bd)."""
        lines = [f"bi[s * {si}] += pi[s * {si}];"]
        for j, m in enumerate(agg_meta):
            kind, off = m["slot"]
            if kind == "rows":
                continue  # rides the row count merged above
            if kind == "f64":
                acc, part = f"bd[s * {nf} + {off}]", f"pd[s * {nf} + {off}]"
            else:
                acc = f"bi[s * {si} + {1 + off}]"
                part = f"pi[s * {si} + {1 + off}]"
            nn = f"bi[s * {si} + {cn} + {j}]"
            nng = f"pi[s * {si} + {cn} + {j}]"
            if m["fn"] == "count":
                lines.append(f"{acc} += {part};")
            elif m["fn"] == "sum":
                add = (f"{acc} = (int64_t)((uint64_t){acc}"
                       f" + (uint64_t){part});" if kind == "i64"
                       else f"{acc} += {part};")
                if m.get("nn", True):
                    lines.append(f"if ({nng}) {{ {add} {nn} += {nng}; }}")
                else:
                    lines.append(add)
            elif m["fn"] == "min":
                lines.append(f"if ({nng}) {{ if (!{nn} || {part} < {acc}) "
                             f"{acc} = {part}; {nn} += {nng}; }}")
            else:
                lines.append(f"if ({nng}) {{ if (!{nn} || {part} > {acc}) "
                             f"{acc} = {part}; {nn} += {nng}; }}")
        return "\n        ".join(lines)

    @staticmethod
    def _interleaved_copyout(agg_meta, si: int, cn: int, nf: int, ni: int,
                             na: int, n_aggs: int) -> str:
        """Scatter the merged interleaved block out to the caller's
        separate (zero-initialized) accd/acci/cnt_rows/cnt_nn arrays —
        the ctypes interface the Python side reads stays unchanged."""
        lines = [f"cnt_rows[s] = bi[s * {si}];"]
        for m in agg_meta:
            kind, off = m["slot"]
            if kind == "rows":
                continue
            if kind == "f64":
                lines.append(
                    f"accd[s * {nf} + {off}] = bd[s * {nf} + {off}];")
            else:
                lines.append(
                    f"acci[s * {ni} + {off}] = bi[s * {si} + {1 + off}];")
        for j in range(n_aggs):
            lines.append(
                f"cnt_nn[s * {na} + {j}] = bi[s * {si} + {cn} + {j}];")
        return "\n      ".join(lines)

    # ---------------- hash-mode group keys ----------------
    def _emit_hash_keys(self, key_vals: List[Val]) -> None:
        """Encode each group key as an int64 + null flag, insert the tuple
        into the per-thread open-addressing table, and rebind the
        accumulator pointers (the insert may grow/move the table)."""
        nk = len(key_vals)
        for j, v in enumerate(key_vals):
            if v.dictionary is not None or not _is_float(v.dtype):
                conv = f"int64_t gk{j} = (int64_t)({v.code});"
            else:
                # float keys: hash the bit pattern with NaN canonicalized
                # and -0.0 normalized to +0.0 (Spark grouping semantics)
                conv = (f"double kd{j} = (double)({v.code});"
                        f" if (kd{j} == 0.0) kd{j} = 0.0;"
                        f" int64_t gk{j};"
                        f" if (std::isnan(kd{j}))"
                        f" gk{j} = 0x7FF8000000000000LL;"
                        f" else std::memcpy(&gk{j}, &kd{j}, 8);")
            if v.valid is not None:
                nl = (f"uint8_t gn{j} = ({v.valid}) ? 0 : 1;"
                      f" if (gn{j}) gk{j} = 0;")
            else:
                nl = f"uint8_t gn{j} = 0;"
            self.stmts.append(conv + " " + nl)
        self.stmts.append(
            "int64_t gkarr[" + str(nk) + "] = {"
            + ", ".join(f"gk{j}" for j in range(nk)) + "};"
            " uint8_t gnarr[" + str(nk) + "] = {"
            + ", ".join(f"gn{j}" for j in range(nk)) + "};"
            " int64_t seg = tab_insert(T, gkarr, gnarr);"
            " double* accd = T->accd; int64_t* acci = T->acci;"
            " int64_t* cnt_rows = T->cnt_rows;"
            " int64_t* cnt_nn = T->cnt_nn;")

    def _hash_source(self, body, sel_slot, nk, nf, ni, na, agg_meta) -> str:
        merge = self._merge_code_fmt(
            agg_meta, nf, ni, na,
            dst_d="G->accd[d * {nf} + {off}]",
            src_d="S->accd[s * {nf} + {off}]",
            dst_i="G->acci[d * {ni} + {off}]",
            src_i="S->acci[s * {ni} + {off}]",
            dst_nn="G->cnt_nn[d * {na} + {j}]",
            src_nn="S->cnt_nn[s * {na} + {j}]")
        return f"""
#include <cstdint>
#include <cmath>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

static const int64_t NK = {nk}, NF_ = {nf}, NI_ = {ni}, NA_ = {na};

struct Tab {{
  int64_t cap, mask, size;
  int64_t* keys;     // cap * NK
  uint8_t* knull;    // cap * NK
  uint8_t* occ;      // cap
  double* accd;      // cap * NF_
  int64_t* acci;     // cap * NI_
  int64_t* cnt_rows; // cap
  int64_t* cnt_nn;   // cap * NA_
}};

static void tab_init(Tab* T, int64_t cap) {{
  T->cap = cap; T->mask = cap - 1; T->size = 0;
  T->keys = (int64_t*)calloc(cap * NK, sizeof(int64_t));
  T->knull = (uint8_t*)calloc(cap * NK, 1);
  T->occ = (uint8_t*)calloc(cap, 1);
  T->accd = (double*)calloc(cap * NF_, sizeof(double));
  T->acci = (int64_t*)calloc(cap * NI_, sizeof(int64_t));
  T->cnt_rows = (int64_t*)calloc(cap, sizeof(int64_t));
  T->cnt_nn = (int64_t*)calloc(cap * NA_, sizeof(int64_t));
}}

static void tab_free(Tab* T) {{
  free(T->keys); free(T->knull); free(T->occ); free(T->accd);
  free(T->acci); free(T->cnt_rows); free(T->cnt_nn);
}}

static inline uint64_t mix64(uint64_t x) {{
  x += 0x9e3779b97f4a7c15ULL;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ULL;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebULL;
  return x ^ (x >> 31);
}}

static inline uint64_t hash_keys(const int64_t* k, const uint8_t* nl) {{
  uint64_t h = 0x9e3779b97f4a7c15ULL;
  for (int64_t j = 0; j < NK; ++j)
    h = mix64(h ^ (uint64_t)k[j] ^ ((uint64_t)nl[j] << 56));
  return h;
}}

static void tab_grow(Tab* T) {{
  Tab N; tab_init(&N, T->cap * 2);
  for (int64_t s = 0; s < T->cap; ++s) {{
    if (!T->occ[s]) continue;
    uint64_t h = hash_keys(T->keys + s * NK, T->knull + s * NK);
    int64_t i = (int64_t)(h & (uint64_t)N.mask);
    while (N.occ[i]) i = (i + 1) & N.mask;  // keys are distinct
    N.occ[i] = 1;
    std::memcpy(N.keys + i * NK, T->keys + s * NK, NK * sizeof(int64_t));
    std::memcpy(N.knull + i * NK, T->knull + s * NK, NK);
    std::memcpy(N.accd + i * NF_, T->accd + s * NF_, NF_ * sizeof(double));
    std::memcpy(N.acci + i * NI_, T->acci + s * NI_, NI_ * sizeof(int64_t));
    N.cnt_rows[i] = T->cnt_rows[s];
    std::memcpy(N.cnt_nn + i * NA_, T->cnt_nn + s * NA_,
                NA_ * sizeof(int64_t));
  }}
  N.size = T->size;
  tab_free(T);
  *T = N;
}}

static inline int64_t tab_insert(Tab* T, const int64_t* k,
                                 const uint8_t* nl) {{
  if ((T->size + 1) * 10 >= T->cap * 7) tab_grow(T);
  uint64_t h = hash_keys(k, nl);
  int64_t i = (int64_t)(h & (uint64_t)T->mask);
  for (;;) {{
    if (!T->occ[i]) {{
      T->occ[i] = 1;
      std::memcpy(T->keys + i * NK, k, NK * sizeof(int64_t));
      std::memcpy(T->knull + i * NK, nl, NK);
      T->size += 1;
      return i;
    }}
    if (!std::memcmp(T->keys + i * NK, k, NK * sizeof(int64_t)) &&
        !std::memcmp(T->knull + i * NK, nl, NK))
      return i;
    i = (i + 1) & T->mask;
  }}
}}

template <bool DENSE>
static void run_range(const void** data, int64_t lo, int64_t hi, Tab* T) {{
  {self.ptr_decls()}
  const uint8_t* selp = (const uint8_t*)data[{sel_slot}];
  for (int64_t i = lo; i < hi; ++i) {{
      if (!DENSE && !selp[i]) continue;
      {body}
  }}
}}

// prefix-dense selection (live rows then padding) → unguarded hot loop
static int64_t dense_prefix(const uint8_t* selp, int64_t n) {{
  const void* z = memchr(selp, 0, (size_t)n);
  int64_t k = z ? (const uint8_t*)z - selp : n;
  if (k < n && memchr(selp + k, 1, (size_t)(n - k)) != nullptr)
    return -1;
  return k;
}}

static void run_part(const void** data, int64_t lo, int64_t hi, Tab* T) {{
  const uint8_t* selp = (const uint8_t*)data[{sel_slot}];
  int64_t k = dense_prefix(selp + lo, hi - lo);
  if (k >= 0)
    run_range<true>(data, lo, lo + k, T);
  else
    run_range<false>(data, lo, hi, T);
}}

extern "C" int64_t run_hash(const void** data, int64_t n, void** out) {{
  unsigned hw = std::thread::hardware_concurrency();
  int nt = (int)std::min<int64_t>(hw ? hw : 1,
                                  std::max<int64_t>(n / 500000, 1));
  Tab* G = (Tab*)malloc(sizeof(Tab));
  if (nt <= 1) {{
    tab_init(G, 4096);
    run_part(data, 0, n, G);
  }} else {{
    std::vector<Tab> parts(nt);
    std::vector<std::thread> ts;
    int64_t per = (n + nt - 1) / nt;
    for (int t = 0; t < nt; ++t) {{
      tab_init(&parts[t], 4096);
      int64_t lo = t * per, hi = std::min(n, lo + per);
      ts.emplace_back(run_part, data, lo, hi, &parts[t]);
    }}
    for (auto& th : ts) th.join();
    tab_init(G, 8192);
    for (int t = 0; t < nt; ++t) {{
      Tab* S = &parts[t];
      for (int64_t s = 0; s < S->cap; ++s) {{
        if (!S->occ[s]) continue;
        int64_t d = tab_insert(G, S->keys + s * NK, S->knull + s * NK);
        G->cnt_rows[d] += S->cnt_rows[s];
        {merge}
      }}
      tab_free(S);
    }}
  }}
  *out = (void*)G;
  return G->size;
}}

extern "C" void fetch_hash(void* handle, int64_t* keys, uint8_t* knull,
                           double* accd, int64_t* acci,
                           int64_t* cnt_rows, int64_t* cnt_nn) {{
  Tab* T = (Tab*)handle;
  int64_t o = 0;
  for (int64_t s = 0; s < T->cap; ++s) {{
    if (!T->occ[s]) continue;
    std::memcpy(keys + o * NK, T->keys + s * NK, NK * sizeof(int64_t));
    std::memcpy(knull + o * NK, T->knull + s * NK, NK);
    std::memcpy(accd + o * NF_, T->accd + s * NF_, NF_ * sizeof(double));
    std::memcpy(acci + o * NI_, T->acci + s * NI_, NI_ * sizeof(int64_t));
    cnt_rows[o] = T->cnt_rows[s];
    std::memcpy(cnt_nn + o * NA_, T->cnt_nn + s * NA_,
                NA_ * sizeof(int64_t));
    ++o;
  }}
}}

extern "C" void release_hash(void* handle) {{
  Tab* T = (Tab*)handle;
  tab_free(T);
  free(T);
}}
"""

    @staticmethod
    def _merge_code_fmt(agg_meta, nf, ni, na, dst_d, src_d, dst_i, src_i,
                        dst_nn, src_nn) -> str:
        """Merge statements combining a source accumulator row into a
        destination row, with index expressions supplied as templates."""
        lines = []
        for j, m in enumerate(agg_meta):
            kind, off = m["slot"]
            if kind == "rows":
                continue  # read from cnt_rows, merged separately
            sub = dict(nf=nf, ni=ni, na=na, off=off, j=j)
            if kind == "f64":
                acc, part = dst_d.format(**sub), src_d.format(**sub)
            else:
                acc, part = dst_i.format(**sub), src_i.format(**sub)
            nn = dst_nn.format(**sub)
            nng = src_nn.format(**sub)
            if m["fn"] == "count":
                lines.append(f"{acc} += {part};")
            elif m["fn"] == "sum":
                add = (f"{acc} = (int64_t)((uint64_t){acc}"
                       f" + (uint64_t){part});" if kind == "i64"
                       else f"{acc} += {part};")
                if m.get("nn", True):
                    lines.append(f"if ({nng}) {{ {add} {nn} += {nng}; }}")
                else:
                    lines.append(add)
            elif m["fn"] == "min":
                lines.append(f"if ({nng}) {{ if (!{nn} || {part} < {acc}) "
                             f"{acc} = {part}; {nn} += {nng}; }}")
            elif m["fn"] == "max":
                lines.append(f"if ({nng}) {{ if (!{nn} || {part} > {acc}) "
                             f"{acc} = {part}; {nn} += {nng}; }}")
        return "\n        ".join(lines)
