"""Native (C++) host-kernel execution for the CPU fallback path.

The TPU compute path is XLA; when the engine runs on host CPUs (local
dev, driver-resident stages, no-accelerator deployments) the hot
aggregation pipeline JIT-compiles to a fused C++ row loop instead, which
makes one pass over memory where XLA CPU makes one scatter pass per
aggregate. Reference role: the vectorized native operator layer
(DataFusion's Rust aggregates, SURVEY.md §2.4-2.5).

Entry point: ``try_native_agg`` — returns a HostBatch or None (fall back
to the jitted device path). Zero-copy over the batch's CPU buffers.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from . import cc
from .agg_codegen import AggCodegen, NativeUnsupported

_C_PTR = ctypes.POINTER(ctypes.c_void_p)

# plan shapes the translator already rejected (avoid re-binding per query)
_REJECTED: set = set()


def _np_of(jarr) -> np.ndarray:
    a = np.asarray(jarr)
    if not a.flags["C_CONTIGUOUS"]:
        a = np.ascontiguousarray(a)
    return a


def native_active() -> bool:
    import jax
    if not cc.enabled():
        return False
    try:
        if jax.default_backend() != "cpu":
            return False
    except Exception:
        return False
    return cc.available()


def try_native_agg(executor, p, chain, child, bottom_node):
    """Attempt the fused native aggregate; None → caller falls back."""
    if not native_active():
        return None
    from ..exec.local import _OP_CACHE, _col_name
    bottom_schema = bottom_node.schema
    dev = child.device
    validity_present = tuple(
        dev.columns[_col_name(i)].validity is not None
        for i in range(len(bottom_schema)))

    from ..plan import stages as pst
    key = executor._op_key(
        "native_agg", pst.stage_fingerprint([p] + chain, bottom_schema),
        validity_present)
    if key is None or key in _REJECTED:
        return None

    def builder():
        comp = executor._compiler(child, bottom_schema)

        def fold_const(r):
            try:
                compiled = comp.compile(r)
                d, v = compiled.fn([])
                if v is not None and not bool(np.asarray(v)[0]):
                    return (None, compiled.dtype)
                if compiled.dictionary is not None:
                    return (compiled.dictionary[0].as_py(), compiled.dtype)
                return (np.asarray(d)[0].item(), compiled.dtype)
            except Exception:
                return None

        dicts = {i: d for i, d in (
            (i, child.dicts.get(_col_name(i)))
            for i in range(len(bottom_schema))) if d is not None}
        gen = AggCodegen(p, chain, bottom_schema, dicts,
                         validity_present, fold_const)
        source, meta = gen.build()
        need = ("run_hash", "fetch_hash", "release_hash") \
            if meta["mode"] == "hash" else ("run",)
        lib = cc.compile_and_load(source, require=need)
        if meta["mode"] == "hash":
            fn = lib.run_hash
            fn.restype = ctypes.c_int64
            meta["lib"] = lib
        else:
            fn = lib.run
            fn.restype = None
        meta["args"] = gen.args
        meta["luts"] = gen.luts  # keep LUT arrays alive with the entry
        return fn, meta

    try:
        # NOT _jitted: the compiled kernel is a ctypes fn, not a jax fn
        fn, meta = _OP_CACHE.get(key, executor._dict_objs(child), builder)
    except NativeUnsupported:
        _REJECTED.add(key)
        return None
    except RuntimeError:
        _REJECTED.add(key)
        return None  # toolchain failure: fall back to the device path
    return _run(fn, meta, p, child, bottom_schema)


def _run(fn, meta, p, child, bottom_schema):
    import jax.numpy as jnp

    from ..columnar.batch import HostBatch, make_batch
    from ..exec.local import _col_name
    from ..spec import data_type as dt

    dev = child.device
    n = dev.capacity
    ptrs = []
    keepalive = []
    for kind, payload in meta["args"]:
        if kind == "col":
            a = _np_of(dev.columns[_col_name(payload)].data)
        elif kind == "validity":
            a = _np_of(dev.columns[_col_name(payload)].validity)
        elif kind == "sel":
            a = _np_of(dev.sel)
        else:  # lut
            a = payload
        keepalive.append(a)
        ptrs.append(a.ctypes.data_as(ctypes.c_void_p))
    arr_t = ctypes.c_void_p * len(ptrs)
    data = arr_t(*[pt.value for pt in ptrs])

    if meta["mode"] == "hash":
        return _run_hash(fn, meta, p, data, keepalive, n)

    nseg, nf, ni, na = meta["nseg"], meta["nf"], meta["ni"], meta["na"]
    accd = np.zeros(nseg * nf, dtype=np.float64)
    acci = np.zeros(nseg * ni, dtype=np.int64)
    cnt_rows = np.zeros(nseg, dtype=np.int64)
    cnt_nn = np.zeros(nseg * na, dtype=np.int64)
    fn(data, ctypes.c_int64(n),
       accd.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
       acci.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
       cnt_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
       cnt_nn.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))

    if p.group_indices:
        exists = np.flatnonzero(cnt_rows > 0)
    else:
        exists = np.asarray([0])  # global aggregate: always one row
    ngroups = len(exists)
    accd = accd.reshape(nseg, nf)[exists]
    acci = acci.reshape(nseg, ni)[exists]
    cnt_nn = cnt_nn.reshape(nseg, na)[exists]

    in_schema = p.input.schema
    columns = {}
    out_dicts = {}
    domains, strides = meta["domains"], meta["strides"]
    key_vals = meta["key_vals"]
    seg = exists.copy()
    for k, gi in enumerate(p.group_indices):
        d, s = domains[k], strides[k]
        code = (seg // s) % (d + 1)
        seg_valid = code != d
        kv = key_vals[k]
        f = in_schema[gi]
        if isinstance(kv.dtype, dt.BooleanType) and kv.dictionary is None:
            values = code.astype(bool)
        else:
            values = code.astype(np.int32)
            out_dicts[_col_name(k)] = kv.dictionary
        validity = None if seg_valid.all() else seg_valid
        columns[_col_name(k)] = (values, validity, f.dtype)

    nk = len(p.group_indices)
    _fill_agg_columns(columns, p, meta, accd, acci, cnt_nn, cnt_rows[exists],
                      nk)

    batch = make_batch(columns, ngroups)
    return HostBatch(batch, out_dicts)


def _fill_agg_columns(columns, p, meta, accd, acci, cnt_nn, cnt_rows, nk):
    from ..exec.local import _col_name

    for j, (a, m) in enumerate(zip(p.aggs, meta["agg_meta"])):
        kind, off = m["slot"]
        if kind == "rows":
            raw = cnt_rows
        elif kind == "f64":
            raw = accd[:, off]
        else:
            raw = acci[:, off]
        out_dtype = a.out_dtype
        npdt = np.dtype(out_dtype.physical_dtype or "int64")
        values = raw.astype(npdt)
        if a.fn == "count":
            validity = None
        elif not m.get("nn", True):
            # unguarded sum: valid wherever the group saw any row (the
            # forced single row of an empty GLOBAL aggregate has
            # cnt_rows == 0 and must be NULL)
            nonnull = cnt_rows > 0
            validity = None if nonnull.all() else nonnull
        else:
            nonnull = cnt_nn[:, j] > 0
            validity = None if nonnull.all() else nonnull
        columns[_col_name(nk + j)] = (values, validity, out_dtype)


def _run_hash(fn, meta, p, data, keepalive, n):
    """Hash-mode native aggregate: the C++ kernel owns the group table;
    two-phase fetch copies the compacted groups into numpy and frees it."""
    from ..columnar.batch import HostBatch, make_batch
    from ..exec.local import _col_name
    from ..spec import data_type as dt

    lib = meta["lib"]
    handle = ctypes.c_void_p()
    ngroups = int(fn(data, ctypes.c_int64(n), ctypes.byref(handle)))

    nk = len(p.group_indices)
    nf, ni, na = meta["nf"], meta["ni"], meta["na"]
    keys = np.zeros((max(ngroups, 1), nk), dtype=np.int64)
    knull = np.zeros((max(ngroups, 1), nk), dtype=np.uint8)
    accd = np.zeros((max(ngroups, 1), nf), dtype=np.float64)
    acci = np.zeros((max(ngroups, 1), ni), dtype=np.int64)
    cnt_rows = np.zeros(max(ngroups, 1), dtype=np.int64)
    cnt_nn = np.zeros((max(ngroups, 1), na), dtype=np.int64)
    lib.fetch_hash(
        handle,
        keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        knull.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        accd.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        acci.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cnt_rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        cnt_nn.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    lib.release_hash(handle)

    # deterministic group order (table iteration order depends on the
    # thread split): lexsort over the encoded key tuple
    if ngroups > 1:
        sort_cols = []
        for k in range(nk - 1, -1, -1):
            sort_cols.append(keys[:ngroups, k])
            sort_cols.append(knull[:ngroups, k])
        order = np.lexsort(tuple(sort_cols))
        keys, knull = keys[:ngroups][order], knull[:ngroups][order]
        accd, acci = accd[:ngroups][order], acci[:ngroups][order]
        cnt_nn, cnt_rows = cnt_nn[:ngroups][order], cnt_rows[:ngroups][order]
    else:
        keys, knull = keys[:ngroups], knull[:ngroups]
        accd, acci, cnt_nn = accd[:ngroups], acci[:ngroups], cnt_nn[:ngroups]
        cnt_rows = cnt_rows[:ngroups]

    in_schema = p.input.schema
    key_vals = meta["key_vals"]
    columns = {}
    out_dicts = {}
    for k, gi in enumerate(p.group_indices):
        kv = key_vals[k]
        f = in_schema[gi]
        raw = keys[:, k]
        valid_mask = knull[:, k] == 0
        if kv.dictionary is not None:
            values = raw.astype(np.int32)
            out_dicts[_col_name(k)] = kv.dictionary
        elif isinstance(kv.dtype, dt.BooleanType):
            values = raw.astype(bool)
        elif kv.dtype.physical_dtype in ("float32", "float64"):
            values = np.ascontiguousarray(raw).view(np.float64).astype(
                np.dtype(kv.dtype.physical_dtype))
        else:
            values = raw.astype(np.dtype(kv.dtype.physical_dtype))
        validity = None if valid_mask.all() else valid_mask
        columns[_col_name(k)] = (values, validity, f.dtype)

    _fill_agg_columns(columns, p, meta, accd, acci, cnt_nn, cnt_rows, nk)
    batch = make_batch(columns, ngroups)
    return HostBatch(batch, out_dicts)
