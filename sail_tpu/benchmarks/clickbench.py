"""ClickBench: the 43-query web-analytics suite over a synthetic hits
table.

Reference role: python/pysail/data/clickbench/queries.sql +
tests/spark/test_clickbench.py (snapshot-tested there). The real dataset
is 100M rows of ClickHouse web logs; this generator produces a
schema-compatible synthetic table at any scale with the high-cardinality
string columns (URL, Title, SearchPhrase, Referer) that make the suite a
stress test for string-heavy execution.
"""

from __future__ import annotations

import datetime
import os
from typing import Dict, List

import numpy as np

QUERIES_PATH = os.path.join(os.path.dirname(__file__), "data",
                            "clickbench_queries.sql")


def load_queries() -> List[str]:
    with open(QUERIES_PATH, "r", encoding="utf-8") as f:
        text = f.read()
    return [q.strip() for q in text.split(";") if q.strip()]


def generate_hits(n_rows: int = 100_000, seed: int = 0):
    """Synthetic hits table covering every column the 43 queries touch."""
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    n = n_rows

    # user/session shape: Zipf-ish heavy hitters, many singletons
    user_pool = rng.integers(1, max(n // 3, 10), n).astype(np.uint64)
    user_id = (user_pool * np.uint64(2_654_435_761)
               % np.uint64(1 << 62)).astype(np.int64)

    epoch = datetime.date(1970, 1, 1)
    d0 = (datetime.date(2013, 7, 1) - epoch).days
    event_date = (d0 + rng.integers(0, 31, n)).astype("datetime64[D]")
    event_time = (event_date.astype("datetime64[s]")
                  + rng.integers(0, 86400, n).astype("timedelta64[s]"))

    phrases = np.array(
        ["", "", "", "", "", "", "",  # most hits have no search phrase
         "weather", "news today", "cat videos", "python tutorial",
         "cheap flights", "karelia wood", "holiday photos"])
    search_phrase = phrases[rng.integers(0, len(phrases), n)]

    # near-unique URLs: the high-cardinality string cliff the engine must
    # survive (VERDICT round-4 weak point #7)
    host_ids = rng.integers(0, 500, n)
    page_ids = rng.integers(0, max(n // 2, 10), n)
    url = np.char.add(
        np.char.add("http://site", host_ids.astype(str)),
        np.char.add(".example/page?id=", page_ids.astype(str)))
    referer = np.where(rng.random(n) < 0.4, "",
                       np.char.add("http://ref", host_ids.astype(str)))
    title = np.char.add("Page title ", rng.integers(0, max(n // 4, 10),
                                                    n).astype(str))
    mobile_models = np.array(["", "", "", "iPhone", "Galaxy S4", "Nexus 4",
                              "Lumia 920"])

    def u8(hi):
        return rng.integers(0, hi, n).astype(np.int16)

    table = pa.table({
        "WatchID": pa.array(rng.integers(1, 1 << 62, n), type=pa.int64()),
        "UserID": pa.array(user_id, type=pa.int64()),
        "CounterID": pa.array(rng.integers(1, 10_000, n), type=pa.int32()),
        "ClientIP": pa.array(rng.integers(0, 1 << 31, n), type=pa.int64()),
        "RegionID": pa.array(rng.integers(1, 6_000, n), type=pa.int32()),
        "AdvEngineID": pa.array(
            np.where(rng.random(n) < 0.95, 0,
                     rng.integers(1, 60, n)).astype(np.int16),
            type=pa.int16()),
        "SearchEngineID": pa.array(
            np.where(search_phrase == "", 0,
                     rng.integers(1, 100, n)).astype(np.int16),
            type=pa.int16()),
        "SearchPhrase": pa.array(search_phrase),
        "MobilePhone": pa.array(u8(8), type=pa.int16()),
        "MobilePhoneModel": pa.array(
            mobile_models[rng.integers(0, len(mobile_models), n)]),
        "EventDate": pa.array(event_date),
        "EventTime": pa.array(event_time),
        "ResolutionWidth": pa.array(
            rng.choice(np.array([0, 1024, 1280, 1366, 1440, 1536, 1600,
                                 1920], dtype=np.int32), n),
            type=pa.int32()),
        "WindowClientWidth": pa.array(rng.integers(0, 2000, n),
                                      type=pa.int32()),
        "WindowClientHeight": pa.array(rng.integers(0, 1200, n),
                                       type=pa.int32()),
        "IsRefresh": pa.array((rng.random(n) < 0.1).astype(np.int16),
                              type=pa.int16()),
        "IsLink": pa.array((rng.random(n) < 0.2).astype(np.int16),
                           type=pa.int16()),
        "IsDownload": pa.array((rng.random(n) < 0.02).astype(np.int16),
                               type=pa.int16()),
        "DontCountHits": pa.array((rng.random(n) < 0.05).astype(np.int16),
                                  type=pa.int16()),
        "TraficSourceID": pa.array(rng.integers(-1, 10, n).astype(np.int16),
                                   type=pa.int16()),
        "Title": pa.array(title),
        "URL": pa.array(url),
        "Referer": pa.array(referer),
        "URLHash": pa.array(
            rng.integers(-(1 << 62), 1 << 62, n), type=pa.int64()),
        "RefererHash": pa.array(
            rng.integers(-(1 << 62), 1 << 62, n), type=pa.int64()),
    })
    return table


def register_hits(spark, n_rows: int = 100_000, seed: int = 0):
    table = generate_hits(n_rows, seed)
    spark.createDataFrame(table).createOrReplaceTempView("hits")
    return table
