"""TPC-H data generator (dbgen-lite).

Generates the eight TPC-H tables at a given scale factor with the standard
schemas and value domains (distributions simplified where the spec's exact
text-pool grammar doesn't affect query semantics). Used for correctness
testing against a pandas oracle and for benchmarking; the reference drives
the same queries against apache/datafusion-benchmarks data (SURVEY.md §6).
"""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2),
    ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0),
    ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3), ("SAUDI ARABIA", 4),
    ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_INSTRUCTS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
_TYPES_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPES_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPES_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_CONTAINERS_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
_CONTAINERS_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "black", "blanched", "blue", "blush", "brown", "burlywood",
           "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
           "cornsilk", "cream", "cyan", "dark", "deep", "dim", "dodger",
           "drab", "firebrick", "floral", "forest", "frosted", "gainsboro",
           "ghost", "gold", "goldenrod", "green", "grey", "honeydew",
           "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
           "lemon", "light", "lime", "linen", "magenta", "maroon", "medium",
           "metallic", "midnight", "mint", "misty", "moccasin", "navajo",
           "navy", "olive", "orange", "orchid", "pale", "papaya", "peach",
           "peru", "pink", "plum", "powder", "puff", "purple", "red",
           "rose", "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
           "sienna", "sky", "slate", "smoke", "snow", "spring", "steel",
           "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
           "white", "yellow"]
_COMMENT_WORDS = ("the of with regular final special express pending unusual "
                  "requests deposits packages accounts instructions theodolites "
                  "foxes ideas carefully slyly quickly blithely furiously bold "
                  "even silent daring Customer Complaints").split()

_EPOCH = datetime.date(1970, 1, 1)
_START = (datetime.date(1992, 1, 1) - _EPOCH).days
_END = (datetime.date(1998, 12, 1) - _EPOCH).days


def _dec(vals: np.ndarray, scale: int = 2, precision: int = 15) -> pa.Array:
    return pa.array([None if v is None else v for v in vals]).cast(
        pa.float64()).cast(pa.decimal128(precision, scale), safe=False)


def _comments(rng, n, maxwords=8) -> pa.Array:
    words = rng.choice(_COMMENT_WORDS, size=(n, maxwords))
    counts = rng.integers(3, maxwords + 1, n)
    return pa.array([" ".join(words[i, :counts[i]]) for i in range(n)])


def generate_tpch(sf: float = 0.01, seed: int = 0) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)
    n_part = max(1, int(200_000 * sf))
    n_supp = max(1, int(10_000 * sf))
    n_cust = max(1, int(150_000 * sf))
    n_order = max(1, int(1_500_000 * sf))
    tables: Dict[str, pa.Table] = {}

    # region / nation
    tables["region"] = pa.table({
        "r_regionkey": pa.array(np.arange(5), type=pa.int64()),
        "r_name": pa.array(_REGIONS),
        "r_comment": _comments(rng, 5),
    })
    tables["nation"] = pa.table({
        "n_nationkey": pa.array(np.arange(25), type=pa.int64()),
        "n_name": pa.array([n for n, _ in _NATIONS]),
        "n_regionkey": pa.array(np.array([r for _, r in _NATIONS]), type=pa.int64()),
        "n_comment": _comments(rng, 25),
    })

    # part
    pk = np.arange(1, n_part + 1)
    p_name = [" ".join(rng.choice(_COLORS, 5, replace=False)) for _ in range(n_part)]
    mfgr = rng.integers(1, 6, n_part)
    brand = mfgr * 10 + rng.integers(1, 6, n_part)
    p_type = [f"{rng.choice(_TYPES_1)} {rng.choice(_TYPES_2)} {rng.choice(_TYPES_3)}"
              for _ in range(n_part)]
    p_container = [f"{rng.choice(_CONTAINERS_1)} {rng.choice(_CONTAINERS_2)}"
                   for _ in range(n_part)]
    retail = (90000 + (pk % 200001) / 10 + 100 * (pk % 1000)) / 100
    tables["part"] = pa.table({
        "p_partkey": pa.array(pk, type=pa.int64()),
        "p_name": pa.array(p_name),
        "p_mfgr": pa.array([f"Manufacturer#{m}" for m in mfgr]),
        "p_brand": pa.array([f"Brand#{b}" for b in brand]),
        "p_type": pa.array(p_type),
        "p_size": pa.array(rng.integers(1, 51, n_part), type=pa.int32()),
        "p_container": pa.array(p_container),
        "p_retailprice": _dec(retail),
        "p_comment": _comments(rng, n_part, 5),
    })

    # supplier
    sk = np.arange(1, n_supp + 1)
    s_nation = rng.integers(0, 25, n_supp)
    tables["supplier"] = pa.table({
        "s_suppkey": pa.array(sk, type=pa.int64()),
        "s_name": pa.array([f"Supplier#{i:09d}" for i in sk]),
        "s_address": pa.array([f"addr {i}" for i in sk]),
        "s_nationkey": pa.array(s_nation, type=pa.int64()),
        "s_phone": pa.array([f"{10 + n}-{rng.integers(100, 999)}-"
                             f"{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
                             for n in s_nation]),
        "s_acctbal": _dec(np.round(rng.uniform(-999.99, 9999.99, n_supp), 2)),
        "s_comment": _comments(rng, n_supp),
    })

    # partsupp: 4 suppliers per part
    ps_part = np.repeat(pk, 4)
    ps_supp = np.empty(n_part * 4, dtype=np.int64)
    for j in range(4):
        ps_supp[j::4] = (pk + j * (n_supp // 4 + 1)) % n_supp + 1
    tables["partsupp"] = pa.table({
        "ps_partkey": pa.array(ps_part, type=pa.int64()),
        "ps_suppkey": pa.array(ps_supp, type=pa.int64()),
        "ps_availqty": pa.array(rng.integers(1, 10000, len(ps_part)), type=pa.int32()),
        "ps_supplycost": _dec(np.round(rng.uniform(1.0, 1000.0, len(ps_part)), 2)),
        "ps_comment": _comments(rng, len(ps_part)),
    })

    # customer
    ck = np.arange(1, n_cust + 1)
    c_nation = rng.integers(0, 25, n_cust)
    tables["customer"] = pa.table({
        "c_custkey": pa.array(ck, type=pa.int64()),
        "c_name": pa.array([f"Customer#{i:09d}" for i in ck]),
        "c_address": pa.array([f"addr {i}" for i in ck]),
        "c_nationkey": pa.array(c_nation, type=pa.int64()),
        "c_phone": pa.array([f"{10 + n}-{rng.integers(100, 999)}-"
                             f"{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
                             for n in c_nation]),
        "c_acctbal": _dec(np.round(rng.uniform(-999.99, 9999.99, n_cust), 2)),
        "c_mktsegment": pa.array(rng.choice(_SEGMENTS, n_cust)),
        "c_comment": _comments(rng, n_cust),
    })

    # orders: only ~2/3 of customers have orders (spec: custkey % 3 != 0 pattern)
    ok = np.arange(1, n_order + 1) * 4 - 3  # sparse order keys, as in dbgen
    o_cust = rng.integers(1, n_cust + 1, n_order)
    o_cust = np.where(o_cust % 3 == 0, (o_cust % (max(n_cust - 1, 1))) + 1, o_cust)
    o_cust = np.where(o_cust % 3 == 0, 1 + (o_cust + 1) % max(n_cust, 1), o_cust)
    o_date = rng.integers(_START, _END - 151, n_order)
    tables["orders"] = pa.table({
        "o_orderkey": pa.array(ok, type=pa.int64()),
        "o_custkey": pa.array(o_cust, type=pa.int64()),
        "o_orderstatus": pa.array(rng.choice(["F", "O", "P"], n_order,
                                             p=[0.49, 0.49, 0.02])),
        "o_totalprice": _dec(np.round(rng.uniform(850, 550000, n_order), 2)),
        "o_orderdate": pa.array(o_date.astype("datetime64[D]")),
        "o_orderpriority": pa.array(rng.choice(_PRIORITIES, n_order)),
        "o_clerk": pa.array([f"Clerk#{rng.integers(1, 1001):09d}"
                             for _ in range(n_order)]),
        "o_shippriority": pa.array(np.zeros(n_order), type=pa.int32()),
        "o_comment": _comments(rng, n_order),
    })

    # lineitem: 1-7 lines per order
    lines_per = rng.integers(1, 8, n_order)
    l_order = np.repeat(ok, lines_per)
    l_odate = np.repeat(o_date, lines_per)
    n_line = len(l_order)
    l_num = np.concatenate([np.arange(1, c + 1) for c in lines_per])
    qty = rng.integers(1, 51, n_line)
    l_part = rng.integers(1, n_part + 1, n_line)
    l_supp = (l_part + rng.integers(0, 4, n_line) * (n_supp // 4 + 1)) % n_supp + 1
    extended = qty * np.round((90000 + (l_part % 200001) / 10
                               + 100 * (l_part % 1000)) / 100, 2)
    discount = rng.integers(0, 11, n_line) / 100.0
    tax = rng.integers(0, 9, n_line) / 100.0
    ship_delta = rng.integers(1, 122, n_line)
    l_ship = l_odate + ship_delta
    l_commit = l_odate + rng.integers(30, 92, n_line)
    l_receipt = l_ship + rng.integers(1, 31, n_line)
    returnflag = np.where(
        l_receipt <= (datetime.date(1995, 6, 17) - _EPOCH).days,
        rng.choice(["R", "A"], n_line), "N")
    linestatus = np.where(l_ship > (datetime.date(1995, 6, 17) - _EPOCH).days,
                          "O", "F")
    tables["lineitem"] = pa.table({
        "l_orderkey": pa.array(l_order, type=pa.int64()),
        "l_partkey": pa.array(l_part, type=pa.int64()),
        "l_suppkey": pa.array(l_supp, type=pa.int64()),
        "l_linenumber": pa.array(l_num, type=pa.int32()),
        "l_quantity": _dec(qty.astype(np.float64)),
        "l_extendedprice": _dec(np.round(extended, 2)),
        "l_discount": _dec(discount),
        "l_tax": _dec(tax),
        "l_returnflag": pa.array(returnflag),
        "l_linestatus": pa.array(linestatus),
        "l_shipdate": pa.array(l_ship.astype("datetime64[D]")),
        "l_commitdate": pa.array(l_commit.astype("datetime64[D]")),
        "l_receiptdate": pa.array(l_receipt.astype("datetime64[D]")),
        "l_shipinstruct": pa.array(rng.choice(_INSTRUCTS, n_line)),
        "l_shipmode": pa.array(rng.choice(_SHIPMODES, n_line)),
        "l_comment": _comments(rng, n_line, 4),
    })
    return tables


def register_tpch(spark, sf: float = 0.01, seed: int = 0):
    """Create the TPC-H tables as temp views on a session."""
    tables = generate_tpch(sf, seed)
    for name, table in tables.items():
        spark.createDataFrame(table).createOrReplaceTempView(name)
    return tables
