"""Application config: YAML defaults ⊕ SAIL_* environment layering.

Reference role: crates/sail-common/src/config/ (AppConfig from
application.yaml via figment, env layering with SAIL_ prefix and __
nesting — e.g. SAIL_CLUSTER__DRIVER_LISTEN_PORT).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional


_DEFAULTS: Optional[Dict[str, Any]] = None


def _load_defaults() -> Dict[str, Any]:
    global _DEFAULTS
    if _DEFAULTS is None:
        import yaml
        path = os.path.join(os.path.dirname(__file__), "application.yaml")
        with open(path, "r", encoding="utf-8") as f:
            _DEFAULTS = yaml.safe_load(f) or {}
    return _DEFAULTS


def _flatten(tree: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in tree.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def app_config() -> Dict[str, Any]:
    """Flattened config: YAML defaults overridden by SAIL_* env vars
    (double underscore nests: SAIL_CLUSTER__TASK_MAX_ATTEMPTS=5 →
    cluster.task_max_attempts)."""
    conf = _flatten(_load_defaults())
    for name, value in os.environ.items():
        if not name.startswith("SAIL_"):
            continue
        key = name[len("SAIL_"):].lower().replace("__", ".")
        conf[key] = value
    return conf


def get(key: str, default: Any = None) -> Any:
    return app_config().get(key, default)


def truthy_value(value: Any, default: Any = "true") -> bool:
    """Boolean parse of an already-fetched value (session conf, env):
    everything except 0/false/no/off (any case) is on; None falls back
    to ``default``. The one parser every gate shares, so the accepted
    falsy spellings cannot drift between call sites."""
    if value is None:
        value = default
    return str(value).strip().lower() not in ("0", "false", "no", "off")


def truthy(key: str, default: Any = "true") -> bool:
    """Boolean config key (see :func:`truthy_value`)."""
    return truthy_value(get(key, default))
