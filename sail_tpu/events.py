"""Cluster flight-data recorder: one append-only structured event log.

Reference role: the Spark event-log analogue for sail-tpu, motivated by
Theseus (arXiv:2508.05029 — at scale the engine is a data-movement
scheduler, so wall-clock attribution is a scheduling question only a
cluster-wide timeline can answer) and Tailwind (arXiv:2604.28079 — the
same event stream is the ops surface of a multi-tenant serving layer).

Every autonomous runtime decision the engine makes — task dispatch and
retry, governor admission, adaptive replanning, speculation, eviction,
streaming epoch commits — lands in ONE typed, versioned, replayable
stream spanning driver and workers:

- a bounded in-memory ring (``telemetry.event_ring_capacity``), always
  on, queryable as ``system.telemetry.events`` /
  ``system.telemetry.task_timeline``;
- an optional durable JSONL log (``telemetry.event_log.{enabled,dir,
  max_mb,max_segments}``, surfaced as
  ``spark.sail.telemetry.eventLog.*``) rotated in bounded segments
  that ``scripts/sail_timeline.py`` replays offline across segment
  boundaries — the post-mortem ground truth for "why was this query
  slow";
- worker-side events ship to the driver piggybacked on the terminal
  task-status report (``ReportTaskStatusRequest.events_json``), so the
  driver's log is the cluster-wide merge;
- every event carries the query's ``trace_id``, so OTLP spans and the
  event log cross-reference.

The vocabulary is DECLARED (:data:`EVENT_TYPES`) and enforced both at
emit time (unknown type / undeclared attribute raises) and statically
by the ``events`` lint (scripts/sail_lint.py): every ``emit(EventType.X)``
call site must use a declared type with the declared attribute set, and
every declared type must be emitted somewhere.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("sail_tpu.events")

#: bump when a record's shape changes incompatibly; replay tooling keys
#: off it (``sail_timeline.py`` refuses records from the future)
EVENT_SCHEMA_VERSION = 1

#: record keys owned by the envelope — never event attributes. ``task``
#: is stamped by the DRIVER when it ingests a worker report's events
#: ("s<stage>p<partition>a<attempt>"), so records the worker could not
#: scope itself (compile events) still attribute to the right task.
RESERVED_KEYS = ("v", "seq", "ts", "type", "query_id", "trace_id",
                 "task")

#: typed causes a compile miss can be attributed to (exec/retrace.py).
#: The ``slo-taxonomy`` lint enforces that every cause literal emitted
#: in code appears here and vice versa. ``first-ever`` is the benign
#: cold compile of a never-seen program; everything else is a RETRACE —
#: a program the process (or pcache) had and lost, or a shape drift.
RETRACE_CAUSES: Tuple[str, ...] = (
    "first-ever",          # fingerprint never compiled in this process
    "new-aval-signature",  # genuinely new arg structure/dtype/shape
    "capacity-bucket",     # same structure, only a leading (padded
                           # capacity) dim changed — round_capacity churn
    "eviction",            # in-memory op-cache evicted the program
    "pcache-eviction",     # persistent store had it and lost it
    "pcache-poison",       # persistent entry poisoned (undeserializable)
    "env-skew",            # persistent entry refused: env fingerprint skew
)

#: ranked root-cause verdict categories the anomaly classifier
#: (analysis/anomaly.py) may emit; lint-enforced both ways like
#: :data:`RETRACE_CAUSES`.
VERDICT_CATEGORIES: Tuple[str, ...] = (
    "retrace",
    "credit-stall",
    "admission-queue-wait",
    "fetch-wait",
    "spill",
    "cache-invalidation",
    "governor-defer",
    "unexplained",
)

#: the declared vocabulary: event type → attribute keys. ``stage`` /
#: ``partition`` on fetch events are the PRODUCER task's coordinates;
#: ``dst_stage`` / ``dst_partition`` the consuming task's
#: (``dst_partition`` -1 = the driver's root-stage merge fetch).
EVENT_TYPES: Dict[str, Tuple[str, ...]] = {
    # query lifecycle (driver/session side, all execution paths)
    "query_start": ("statement", "session", "tenant"),
    "query_end": ("status", "rows_out", "total_ms", "fingerprint",
                  "spill_bytes", "cache_status"),
    # a stage program was bound: source=trace is a compiled-operator
    # cache miss (JIT wall time in ms), source=persistent a stored AOT
    # executable loaded from the cross-process cache (load wall time)
    "compile": ("key", "ms", "source"),
    # a compile miss attributed to a typed cause (exec/retrace.py):
    # ``fp`` is the program fingerprint the retrace ledger keys on,
    # ``cause`` ∈ RETRACE_CAUSES, ``ms`` the compile wall time,
    # ``site`` the decision site (memory | pcache)
    "retrace": ("key", "fp", "cause", "ms", "site"),
    # per-stage backend routing decision (exec/router.py): backend in
    # native | xla | mesh; stage -1 = the plan-level mesh-vs-local
    # gate; reason names the deciding rule (forced, cost-model,
    # compile-bound, dispatch-bound, unsupported, default, unavailable)
    "backend_route": ("stage", "kind", "backend", "reason"),
    # distributed stage lifecycle (driver)
    "stage_submit": ("job_id", "stage", "partitions", "pipelined"),
    "stage_complete": ("job_id", "stage", "rows"),
    # per-attempt task lifecycle: dispatch + finish on the driver,
    # start on the worker (shipped back in the terminal report)
    "task_dispatch": ("job_id", "stage", "partition", "attempt",
                      "worker", "reason"),
    "task_start": ("job_id", "stage", "partition", "attempt", "worker",
                   "tenant"),
    "task_finish": ("job_id", "stage", "partition", "attempt", "worker",
                    "state", "rows", "fetch_wait_ms", "error"),
    # shuffle fetch over the peer data plane (worker + driver consumers)
    "fetch_begin": ("job_id", "stage", "partition", "channel", "addr",
                    "dst_stage", "dst_partition"),
    "fetch_end": ("job_id", "stage", "partition", "channel", "addr",
                  "dst_stage", "dst_partition", "bytes", "ms", "ok"),
    # memory-footprint task governor (driver)
    "governor_admit": ("job_id", "stage", "partition", "worker",
                       "projected_bytes"),
    "governor_defer": ("job_id", "stage", "partition", "attempt"),
    # multi-tenant admission control (exec/admission.py): job_id is ""
    # for session-path (local query) decisions; ``cost`` is the DRR
    # cost — stage-launch opportunities for cluster jobs, 1 per query
    # on the session path
    "admission_enqueue": ("job_id", "tenant", "queue_depth", "cost"),
    "admission_admit": ("job_id", "tenant", "waited_ms"),
    "admission_defer": ("job_id", "tenant", "reason", "stage",
                        "partition"),
    "admission_shed": ("job_id", "tenant", "reason", "queue_depth"),
    # per-tenant memory-quota ledger: ``bytes`` is the task's projected
    # decoded input (observed producer channel sizes — AQE stats, not
    # static estimates); ``used_bytes`` the tenant total after debit
    "quota_debit": ("job_id", "tenant", "stage", "partition", "bytes",
                    "used_bytes"),
    # per-query deadline enforcement through the CancelJob path
    "deadline_cancel": ("job_id", "tenant", "deadline_ms",
                        "overrun_ms"),
    # adaptive query execution: ``detail`` is the canonical JSON of the
    # decision record (sort_keys), bit-identical to the profile's
    # adaptive event — replaying the log reconstructs the decision
    # sequence exactly
    "adaptive_applied": ("job_id", "kind", "detail"),
    "adaptive_rollback": ("job_id", "kind", "stages"),
    # speculative execution (driver)
    "speculation_launch": ("job_id", "stage", "partition", "attempt",
                           "worker"),
    "speculation_win": ("job_id", "stage", "partition", "attempt"),
    # worker pool health (driver, cluster-scoped: no query id)
    "worker_evict": ("worker", "reason"),
    "worker_quarantine": ("worker", "failures"),
    # elastic autoscaler (exec/autoscaler.py): one record per policy
    # tick that changes fleet intent. ``action`` ∈ scale_up |
    # scale_down | hold, ``worker`` the drain target ("" for
    # scale-up/hold), ``pool`` the live pool size the decision saw,
    # ``detail`` the canonical sort_keys JSON of the full signal
    # snapshot + decision record — replaying the durable log re-derives
    # the decision sequence bit-identically (same contract as
    # adaptive_applied / anomaly)
    "autoscaler_decision": ("action", "worker", "reason", "pool",
                            "detail"),
    # graceful-drain lifecycle for one worker (driver): ``phase`` ∈
    # begin | handoff | done | abort; ``channels``/``bytes`` count the
    # shuffle channels donated to peers so far, ``ms`` the elapsed
    # drain wall time at the phase edge
    "worker_drain": ("worker", "phase", "channels", "bytes", "ms"),
    # streaming epoch commit protocol (streaming.py)
    "epoch_stage": ("epoch", "rows"),
    "epoch_commit": ("epoch", "commit_ms"),
    "epoch_replay": ("epoch",),
    # continuous record-at-a-time streaming (exec/continuous.py):
    # a resident (long-lived) stage task dispatched; a marker injected
    # at the sources; a marker aligning mid-flight at one task's inputs
    # (wait_ms = first-input-blocked → all-aligned, buffered_bytes =
    # post-marker entries held for the slow sibling); a sender stalled
    # on exhausted channel credit (the backpressure signal)
    "task_resident": ("job_id", "stage", "partition", "attempt",
                      "worker"),
    "marker_inject": ("job_id", "marker"),
    "marker_align": ("job_id", "stage", "partition", "marker",
                     "wait_ms", "buffered_bytes"),
    "backpressure": ("job_id", "stage", "partition", "channel",
                     "stall_ms"),
    # a completed profile classified as a tail-latency outlier
    # (analysis/anomaly.py): ``verdict`` ∈ VERDICT_CATEGORIES,
    # ``excess_ms`` total_ms minus the baseline p50, ``detail`` the
    # canonical sort_keys JSON of the ranked evidence — replaying the
    # durable log re-derives verdicts bit-identically
    "anomaly": ("fingerprint", "verdict", "excess_ms", "detail"),
}


class EventType:
    """Symbolic names for the declared vocabulary — every emit site must
    use one of these (the ``events`` lint enforces it)."""

    QUERY_START = "query_start"
    QUERY_END = "query_end"
    COMPILE = "compile"
    RETRACE = "retrace"
    ANOMALY = "anomaly"
    BACKEND_ROUTE = "backend_route"
    STAGE_SUBMIT = "stage_submit"
    STAGE_COMPLETE = "stage_complete"
    TASK_DISPATCH = "task_dispatch"
    TASK_START = "task_start"
    TASK_FINISH = "task_finish"
    FETCH_BEGIN = "fetch_begin"
    FETCH_END = "fetch_end"
    GOVERNOR_ADMIT = "governor_admit"
    GOVERNOR_DEFER = "governor_defer"
    ADMISSION_ENQUEUE = "admission_enqueue"
    ADMISSION_ADMIT = "admission_admit"
    ADMISSION_DEFER = "admission_defer"
    ADMISSION_SHED = "admission_shed"
    QUOTA_DEBIT = "quota_debit"
    DEADLINE_CANCEL = "deadline_cancel"
    ADAPTIVE_APPLIED = "adaptive_applied"
    ADAPTIVE_ROLLBACK = "adaptive_rollback"
    SPECULATION_LAUNCH = "speculation_launch"
    SPECULATION_WIN = "speculation_win"
    WORKER_EVICT = "worker_evict"
    WORKER_QUARANTINE = "worker_quarantine"
    AUTOSCALER_DECISION = "autoscaler_decision"
    WORKER_DRAIN = "worker_drain"
    EPOCH_STAGE = "epoch_stage"
    EPOCH_COMMIT = "epoch_commit"
    EPOCH_REPLAY = "epoch_replay"
    TASK_RESIDENT = "task_resident"
    MARKER_INJECT = "marker_inject"
    MARKER_ALIGN = "marker_align"
    BACKPRESSURE = "backpressure"


def _validate(etype: str, attrs: Dict[str, object]) -> None:
    declared = EVENT_TYPES.get(etype)
    if declared is None:
        raise KeyError(f"event type {etype!r} is not declared in "
                       f"events.EVENT_TYPES")
    extra = set(attrs) - set(declared)
    if extra:
        raise KeyError(f"event {etype!r} does not declare attributes "
                       f"{sorted(extra)}")


def _drop_metric(count: int, reason: str) -> None:
    try:
        from .metrics import record as _record_metric
        _record_metric("telemetry.events.dropped_count", count,
                       reason=reason)
    except Exception:  # noqa: BLE001 — telemetry must never raise
        pass


class EventLog:
    """Bounded ring of event records + optional durable JSONL tail.

    The ring keeps the NEWEST ``capacity`` records (deque eviction).
    When a JSONL path is configured every appended record is also
    written as one ``json.dumps`` line and flushed, so a crash loses at
    most the half-written final line — the replay loader tolerates a
    truncated tail.

    ``max_bytes`` bounds each SEGMENT: a line that would push the
    active file past it first ROTATES — the active file shifts to
    ``<path>.1`` (older segments to ``.2``, ``.3``, …) and a fresh
    active segment opens, keeping at most ``max_segments`` files in
    total. Only when the oldest segment falls off the retention window
    are its events actually dropped from the durable log (counted per
    line in ``telemetry.events.dropped_count{reason=rotated}``).
    :func:`load_event_log` and ``scripts/sail_timeline.py`` read across
    segment boundaries, so replay sees one continuous stream."""

    def __init__(self, capacity: int = 4096, path: Optional[str] = None,
                 max_bytes: int = 0, max_segments: int = 4):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._seq = 0
        self._path = path
        self._file = None
        self._max_bytes = max(0, int(max_bytes))
        self._max_segments = max(1, int(max_segments))
        self._written = 0
        self._file_failed = False

    @property
    def path(self) -> Optional[str]:
        return self._path

    def emit(self, etype: str, query_id: str = "",
             trace_id: Optional[str] = None,
             ts: Optional[float] = None, **attrs) -> None:
        _validate(etype, attrs)
        record = {"v": EVENT_SCHEMA_VERSION,
                  "ts": ts if ts is not None else time.time(),
                  "type": etype, "query_id": query_id or "",
                  "trace_id": trace_id}
        record.update(attrs)
        self.append(record)

    def ingest(self, record: dict, query_id: str = "",
               trace_id: Optional[str] = None,
               task: Optional[str] = None) -> None:
        """Adopt a record produced elsewhere (a worker's shipped task
        events): stamp the envelope the remote side could not know and
        append. Unknown types are dropped, not raised — a version-skewed
        worker must not poison the driver's log."""
        if not isinstance(record, dict) or \
                record.get("type") not in EVENT_TYPES:
            _drop_metric(1, "malformed")
            return
        record.setdefault("v", EVENT_SCHEMA_VERSION)
        record.setdefault("ts", time.time())
        if query_id:
            record["query_id"] = query_id
        else:
            record.setdefault("query_id", "")
        if trace_id is not None:
            record["trace_id"] = trace_id
        else:
            record.setdefault("trace_id", None)
        if task is not None:
            record.setdefault("task", task)
        self.append(record)

    def append(self, record: dict) -> None:
        with self._lock:
            self._seq += 1
            record["seq"] = self._seq
            self._ring.append(record)
            if self._path is not None:
                self._write_line(record)

    @staticmethod
    def _count_lines(path: str) -> int:
        """Complete lines in one segment (drop accounting at rotation
        — segments are bounded by max_bytes, so this is one bounded
        read on a rare path)."""
        try:
            n = 0
            with open(path, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        return n
                    n += chunk.count(b"\n")
        except OSError:
            return 0

    def _rotate(self) -> None:
        # under self._lock; the active file is open and full
        if self._file is not None:
            try:
                self._file.close()
            except OSError:
                pass
            self._file = None
        keep = self._max_segments - 1     # rotated slots beside active
        oldest = f"{self._path}.{keep}" if keep else self._path
        if os.path.exists(oldest):
            dropped = self._count_lines(oldest)
            try:
                os.remove(oldest)
            except OSError:
                pass
            if dropped:
                _drop_metric(dropped, "rotated")
        for i in range(keep - 1, 0, -1):
            src = f"{self._path}.{i}"
            if os.path.exists(src):
                try:
                    os.replace(src, f"{self._path}.{i + 1}")
                except OSError:
                    pass
        if keep and os.path.exists(self._path):
            try:
                os.replace(self._path, f"{self._path}.1")
            except OSError:
                pass
        self._written = 0

    def _write_line(self, record: dict) -> None:
        # under self._lock
        if self._file_failed:
            _drop_metric(1, "log_error")
            return
        try:
            if self._file is None:
                d = os.path.dirname(self._path)
                if d:
                    os.makedirs(d, exist_ok=True)
                self._file = open(self._path, "a", encoding="utf-8")
                self._written = self._file.tell()
            line = json.dumps(record, default=str,
                              separators=(",", ":")) + "\n"
            if self._max_bytes and self._written and \
                    self._written + len(line) > self._max_bytes:
                self._rotate()
            if self._file is None:
                self._file = open(self._path, "a", encoding="utf-8")
                self._written = self._file.tell()
            self._file.write(line)
            self._file.flush()
            self._written += len(line)
        except OSError:
            # an unwritable log must never fail the query path: fall
            # back to ring-only, keep COUNTING every skipped event, and
            # say so once — a clean-looking truncated file must not
            # masquerade as a complete log
            self._file_failed = True
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
            _drop_metric(1, "log_error")
            logger.warning(
                "event log %s became unwritable; further events stay "
                "in the ring only (dropped events count in "
                "telemetry.events.dropped_count{reason=log_error})",
                self._path)

    def events(self, query_id: Optional[str] = None) -> List[dict]:
        """Snapshot, oldest → newest (append order = decision order)."""
        with self._lock:
            out = list(self._ring)
        if query_id is not None:
            out = [e for e in out if e.get("query_id") == query_id]
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None


class TaskEventCollector:
    """Worker-side per-task event buffer: execution threads (and the
    task's fetch pool threads) emit here; the terminal task-status
    report ships the drained buffer to the driver, which stamps the
    query envelope and merges it into the cluster-wide log."""

    #: events one task may buffer; beyond it the newest are dropped
    #: (counted) — a pathological task must not balloon its report
    LIMIT = 1024

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._dropped = 0

    def emit(self, etype: str, ts: Optional[float] = None,
             **attrs) -> None:
        if not enabled():
            return
        _validate(etype, attrs)
        record = {"v": EVENT_SCHEMA_VERSION,
                  "ts": ts if ts is not None else time.time(),
                  "type": etype}
        record.update(attrs)
        with self._lock:
            if len(self._events) >= self.LIMIT:
                self._dropped += 1
                dropped = True
            else:
                self._events.append(record)
                dropped = False
        if dropped:
            # count EVERY drop (only the overflow path pays the metric)
            _drop_metric(1, "collector_cap")

    def drain(self) -> List[dict]:
        with self._lock:
            out, self._events = self._events, []
        return out


# ---------------------------------------------------------------------------
# process-global log + the module-level emit every call site uses
# ---------------------------------------------------------------------------

def _log_from_config() -> EventLog:
    from .config import get as config_get
    from .config import truthy
    try:
        cap = int(config_get("telemetry.event_ring_capacity", 4096))
    except (TypeError, ValueError):
        cap = 4096
    path = None
    max_bytes = 0
    max_segments = 4
    try:
        if truthy("telemetry.event_log.enabled", default="false"):
            d = str(config_get("telemetry.event_log.dir", "") or "")
            if d:
                path = os.path.join(d, f"events-{os.getpid()}.jsonl")
                max_mb = float(config_get(
                    "telemetry.event_log.max_mb", 64))
                max_bytes = int(max_mb * (1 << 20))
                max_segments = int(config_get(
                    "telemetry.event_log.max_segments", 4))
    except (TypeError, ValueError):
        path = None
    return EventLog(cap, path=path, max_bytes=max_bytes,
                    max_segments=max_segments)


EVENT_LOG = _log_from_config()

_ENABLED: "bool | None" = None
_tls = threading.local()


def enabled() -> bool:
    """``telemetry.events_enabled`` gate, read once per process (emit
    sits on scheduling hot paths). The bench A/B knob
    ``SAIL_BENCH_DISABLE_EVENTS=1`` flips it for a whole run."""
    global _ENABLED
    if _ENABLED is None:
        try:
            from .config import truthy
            _ENABLED = truthy("telemetry.events_enabled")
        except Exception:  # noqa: BLE001 — events must not break imports
            _ENABLED = True
    return _ENABLED


def reload() -> None:
    """Re-read the event config and swap in a fresh global log (tests,
    bench A/B runs)."""
    global _ENABLED, EVENT_LOG
    _ENABLED = None
    old = EVENT_LOG
    EVENT_LOG = _log_from_config()
    old.close()


@contextmanager
def collecting(collector: TaskEventCollector):
    """Install a worker-task collector as this thread's event sink:
    events emitted on the thread (e.g. compile events from the local
    executor) buffer into the task's report instead of the global log."""
    prev = getattr(_tls, "collector", None)
    _tls.collector = collector
    try:
        yield collector
    finally:
        _tls.collector = prev


def emit(etype: str, query_id: Optional[str] = None,
         trace_id: Optional[str] = None, ts: Optional[float] = None,
         **attrs) -> None:
    """Emit one event. Routes to the thread's task collector when one
    is installed (worker task threads), otherwise to the global log.
    ``query_id``/``trace_id`` default from the thread's active query
    profile; driver-side sites pass them explicitly (the driver actor
    thread profiles nothing)."""
    if not enabled():
        return
    collector = getattr(_tls, "collector", None)
    if collector is not None:
        collector.emit(etype, ts=ts, **attrs)
        return
    if query_id is None:
        from . import profiler
        p = profiler.current_profile()
        query_id = p.query_id if p is not None else ""
        if trace_id is None and p is not None:
            trace_id = p.trace_id
    EVENT_LOG.emit(etype, query_id=query_id or "", trace_id=trace_id,
                   ts=ts, **attrs)


def events(query_id: Optional[str] = None) -> List[dict]:
    """Snapshot of the global ring (convenience for tables/tests)."""
    return EVENT_LOG.events(query_id=query_id)


# ---------------------------------------------------------------------------
# durable-log replay
# ---------------------------------------------------------------------------

def _load_one(path: str) -> Tuple[List[dict], bool]:
    """One segment: (records, clean). ``clean`` is False when the file
    ended at a truncated or malformed line — everything after that
    point (including NEWER segments) is untrusted."""
    out: List[dict] = []
    clean = True
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            if not line.endswith("\n"):
                clean = False
                break  # truncated tail: the crash cut this record short
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                clean = False
                break
            if not isinstance(record, dict):
                clean = False
                break
            if int(record.get("v", 0)) > EVENT_SCHEMA_VERSION:
                raise ValueError(
                    f"event log {path} carries schema v{record.get('v')} "
                    f"(this build reads ≤ v{EVENT_SCHEMA_VERSION})")
            out.append(record)
    return out, clean


def log_segments(path: str) -> List[str]:
    """Every existing segment of a rotated log, OLDEST first:
    ``<path>.N`` … ``<path>.1``, then the active ``<path>``."""
    rotated = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        rotated.append(f"{path}.{i}")
        i += 1
    return rotated[::-1] + ([path] if os.path.exists(path)
                            else [])


def load_event_log(path: str) -> List[dict]:
    """Read a JSONL event log back — across rotated segments
    (``<path>.N`` oldest → ``<path>`` newest) — tolerating a truncated
    tail: a crash mid-write leaves at most one partial final line, and
    replay must reconstruct everything up to the last COMPLETE record.
    A malformed line mid-segment ends the replay there (everything
    after it, newer segments included, is untrusted). Records from a
    future schema version raise."""
    segments = log_segments(path)
    if not segments:
        # preserve the single-file contract: a missing log raises
        with open(path, "r", encoding="utf-8"):
            pass
        return []
    out: List[dict] = []
    for seg in segments:
        records, clean = _load_one(seg)
        out.extend(records)
        if not clean:
            break
    return out
