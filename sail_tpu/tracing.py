"""Distributed tracing: spans, W3C traceparent propagation, OTLP export.

Reference role: sail-telemetry's fastrace spans with client/server tower
layers propagating trace context across RPCs and the OTLP pipeline
(crates/sail-telemetry/src/layers/{client,server}.rs, src/telemetry.rs:
47-120). The image ships only ``opentelemetry-api`` (no SDK, no exporter),
so this is a from-scratch implementation:

- ``span(name)``: thread-local span stack; ids follow the W3C trace
  context format.
- ``inject_context()`` / ``extract_context()``: ``traceparent`` metadata
  for gRPC calls — one cluster query yields ONE connected trace across
  driver and workers.
- ``OtlpHttpExporter``: background-batched POST of OTLP/HTTP **JSON**
  (``/v1/traces``) — the encoding every OTLP collector accepts alongside
  protobuf. Configured via ``telemetry.otlp_endpoint``.
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

_local = threading.local()
_lock = threading.Lock()


@dataclass
class Span:
    trace_id: str          # 32 hex chars
    span_id: str           # 16 hex chars
    parent_id: Optional[str]
    name: str
    start_ns: int
    end_ns: int = 0
    attributes: Dict[str, object] = field(default_factory=dict)
    status_ok: bool = True


@dataclass
class SpanContext:
    trace_id: str
    span_id: str


def _current() -> Optional[SpanContext]:
    stack = getattr(_local, "span_stack", None)
    return stack[-1] if stack else None


def current_trace_id() -> Optional[str]:
    ctx = _current()
    return ctx.trace_id if ctx else None


@contextmanager
def span(name: str, attributes: Optional[Dict] = None,
         parent: Optional[SpanContext] = None):
    """Open a span; nests under the thread's current span (or an explicit
    remote ``parent`` extracted from RPC metadata)."""
    stack = getattr(_local, "span_stack", None)
    if stack is None:
        stack = _local.span_stack = []
    if parent is None:
        parent = stack[-1] if stack else None
    trace_id = parent.trace_id if parent else secrets.token_hex(16)
    s = Span(trace_id=trace_id, span_id=secrets.token_hex(8),
             parent_id=parent.span_id if parent else None,
             name=name, start_ns=time.time_ns(),
             attributes=dict(attributes or {}))
    ctx = SpanContext(trace_id, s.span_id)
    stack.append(ctx)
    try:
        yield s
    except BaseException:
        s.status_ok = False
        raise
    finally:
        stack.pop()
        s.end_ns = time.time_ns()
        exporter = _exporter()
        if exporter is not None:
            exporter.add(s)


# ---------------------------------------------------------------------------
# W3C trace context over gRPC metadata
# ---------------------------------------------------------------------------

def inject_context(parent: Optional[SpanContext] = None
                   ) -> List[Tuple[str, str]]:
    """Metadata to attach to an outgoing RPC (client layer). An
    explicit ``parent`` overrides the thread-local span — RPCs issued
    from threads that never opened a span (the driver actor thread, a
    fetch pool worker) still propagate the owning query's context."""
    ctx = parent if parent is not None else _current()
    if ctx is None:
        return []
    return [("traceparent", f"00-{ctx.trace_id}-{ctx.span_id}-01")]


def extract_context(metadata) -> Optional[SpanContext]:
    """Parse ``traceparent`` from incoming RPC metadata (server layer)."""
    if metadata is None:
        return None
    for key, value in metadata:
        if key.lower() == "traceparent":
            parts = value.split("-")
            if len(parts) == 4 and len(parts[1]) == 32 and len(parts[2]) == 16:
                return SpanContext(parts[1], parts[2])
    return None


# ---------------------------------------------------------------------------
# OTLP/HTTP JSON export
# ---------------------------------------------------------------------------

@dataclass
class LogEvent:
    time_ns: int
    severity_number: int
    severity_text: str
    body: str
    attributes: Dict[str, object] = field(default_factory=dict)
    trace_id: Optional[str] = None
    span_id: Optional[str] = None


class OtlpHttpExporter:
    """Batched OTLP/HTTP JSON exporter: spans to ``/v1/traces`` and log
    records to ``/v1/logs`` (the reference's log-export pipeline,
    sail-telemetry src/telemetry.rs)."""

    #: signals that already warned about buffer overflow — CLASS level,
    #: so the warning dedupes per signal per PROCESS lifetime (a flappy
    #: collector must not re-warn per exporter instance or per outage
    #: burst; the dropped_count metric carries the ongoing tally)
    _warned_signals: "set[str]" = set()

    def __init__(self, endpoint: str, service_name: str = "sail-tpu",
                 flush_interval_s: float = 1.0, max_batch: int = 512):
        self.endpoint = endpoint.rstrip("/")
        self.service_name = service_name
        self.max_batch = max_batch
        self._buf: List[Span] = []
        self._log_buf: List[LogEvent] = []
        self._buf_lock = threading.Lock()
        self.dropped = {"spans": 0, "logs": 0}
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, args=(flush_interval_s,), daemon=True)
        self._thread.start()

    @classmethod
    def reset_drop_warnings(cls):
        """Forget which signals already warned (tests only)."""
        cls._warned_signals.clear()

    def _note_dropped(self, signal: str, count: int):
        """Account buffer-overflow drops: registry counter + ONE
        warning per signal per process lifetime (called outside the
        buffer lock — the warning itself re-enters add_log through the
        stdlib bridge, and a repeat warning per burst would flood the
        very pipeline that is already dropping)."""
        try:
            from .metrics import record as _record_metric
            _record_metric("telemetry.export.dropped_count", count,
                           signal=signal)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass
        if signal not in OtlpHttpExporter._warned_signals:
            OtlpHttpExporter._warned_signals.add(signal)
            logging.getLogger("sail_tpu.tracing").warning(
                "OTLP export buffer overflow: dropped %d %s "
                "(collector unreachable or slow); further %s drops "
                "count in telemetry.export.dropped_count without "
                "re-warning", count, signal, signal)

    def add(self, s: Span):
        """Enqueue only — span exit must never do network I/O on the hot
        path; the background flush thread posts. Bounded buffer drops the
        oldest spans under sustained collector outage."""
        with self._buf_lock:
            self._buf.append(s)
            dropped = 0
            if len(self._buf) > 16 * self.max_batch:
                dropped = 8 * self.max_batch
                del self._buf[:dropped]
                self.dropped["spans"] += dropped
        if dropped:
            self._note_dropped("spans", dropped)

    def add_log(self, ev: LogEvent):
        with self._buf_lock:
            self._log_buf.append(ev)
            dropped = 0
            if len(self._log_buf) > 16 * self.max_batch:
                dropped = 8 * self.max_batch
                del self._log_buf[:dropped]
                self.dropped["logs"] += dropped
        if dropped:
            self._note_dropped("logs", dropped)

    def _loop(self, interval: float):
        while not self._stop.wait(interval):
            self.flush()

    def flush(self):
        with self._buf_lock:
            batch, self._buf = self._buf, []
            logs, self._log_buf = self._log_buf, []
        if batch:
            self._post(batch)
        if logs:
            self._post_logs(logs)
        from .metrics import REGISTRY
        if REGISTRY.take_dirty():
            self._send("/v1/metrics",
                       REGISTRY.otlp_payload(self.service_name))

    def shutdown(self):
        self._stop.set()
        self.flush()

    @staticmethod
    def _attr(k: str, v) -> dict:
        if isinstance(v, bool):
            value = {"boolValue": v}
        elif isinstance(v, int):
            value = {"intValue": str(v)}
        elif isinstance(v, float):
            value = {"doubleValue": v}
        else:
            value = {"stringValue": str(v)}
        return {"key": k, "value": value}

    def _send(self, suffix: str, payload: dict):
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + suffix,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            urllib.request.urlopen(req, timeout=10).read()
        except Exception:  # noqa: BLE001 — telemetry must never break queries
            pass

    def _post(self, batch: List[Span]):
        payload = {
            "resourceSpans": [{
                "resource": {"attributes": [
                    self._attr("service.name", self.service_name)]},
                "scopeSpans": [{
                    "scope": {"name": "sail_tpu"},
                    "spans": [{
                        "traceId": s.trace_id,
                        "spanId": s.span_id,
                        **({"parentSpanId": s.parent_id}
                           if s.parent_id else {}),
                        "name": s.name,
                        "kind": 1,
                        "startTimeUnixNano": str(s.start_ns),
                        "endTimeUnixNano": str(s.end_ns),
                        "attributes": [self._attr(k, v)
                                       for k, v in s.attributes.items()],
                        "status": {"code": 1 if s.status_ok else 2},
                    } for s in batch],
                }],
            }],
        }
        self._send("/v1/traces", payload)

    def _post_logs(self, logs: List[LogEvent]):
        payload = {
            "resourceLogs": [{
                "resource": {"attributes": [
                    self._attr("service.name", self.service_name)]},
                "scopeLogs": [{
                    "scope": {"name": "sail_tpu"},
                    "logRecords": [{
                        "timeUnixNano": str(ev.time_ns),
                        "severityNumber": ev.severity_number,
                        "severityText": ev.severity_text,
                        "body": {"stringValue": ev.body},
                        "attributes": [self._attr(k, v)
                                       for k, v in ev.attributes.items()],
                        **({"traceId": ev.trace_id} if ev.trace_id else {}),
                        **({"spanId": ev.span_id} if ev.span_id else {}),
                    } for ev in logs],
                }],
            }],
        }
        self._send("/v1/logs", payload)


# severityNumber per the OTLP spec
_SEVERITY = {"DEBUG": 5, "INFO": 9, "WARNING": 13, "WARN": 13,
             "ERROR": 17, "CRITICAL": 21, "FATAL": 21}


def log_event(severity: str, body: str, **attributes):
    """Emit one log record to the OTLP pipeline (no-op when no exporter
    is configured). Records correlate with the active span."""
    exporter = _exporter()
    if exporter is None:
        return
    ctx = _current()
    exporter.add_log(LogEvent(
        time_ns=time.time_ns(),
        severity_number=_SEVERITY.get(severity.upper(), 9),
        severity_text=severity.upper(), body=body,
        attributes=attributes,
        trace_id=ctx.trace_id if ctx else None,
        span_id=ctx.span_id if ctx else None))


class OtlpLogHandler(logging.Handler):
    """stdlib ``logging`` bridge: attach to a logger and every record
    flows into the OTLP log export."""

    def emit(self, record):
        try:
            log_event(record.levelname, record.getMessage(),
                      logger=record.name)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass


def install_log_handler(logger_name: str = "sail_tpu"):
    """Route the engine's stdlib logger into the OTLP pipeline."""
    logger = logging.getLogger(logger_name)
    if logger.level == logging.NOTSET:
        # without an explicit level the logger inherits root's WARNING
        # and INFO/DEBUG records would never reach the handler
        logger.setLevel(logging.DEBUG)
    for h in logger.handlers:
        if isinstance(h, OtlpLogHandler):
            return h
    h = OtlpLogHandler()
    logger.addHandler(h)
    return h


_EXPORTER: Optional[OtlpHttpExporter] = None
_EXPORTER_INIT = False


def _exporter() -> Optional[OtlpHttpExporter]:
    global _EXPORTER, _EXPORTER_INIT
    if not _EXPORTER_INIT:
        with _lock:
            if not _EXPORTER_INIT:
                from .config import get as config_get
                endpoint = os.environ.get("SAIL_TELEMETRY__OTLP_ENDPOINT") \
                    or str(config_get("telemetry.otlp_endpoint", "") or "")
                if endpoint:
                    _EXPORTER = OtlpHttpExporter(endpoint)
                    install_log_handler()
                _EXPORTER_INIT = True
    return _EXPORTER


def configure_exporter(endpoint: Optional[str]):
    """Explicit (re)configuration — used by tests and the CLI."""
    global _EXPORTER, _EXPORTER_INIT
    with _lock:
        if _EXPORTER is not None:
            _EXPORTER.shutdown()
        _EXPORTER = OtlpHttpExporter(endpoint) if endpoint else None
        if _EXPORTER is not None:
            install_log_handler()
        _EXPORTER_INIT = True


def flush():
    if _EXPORTER is not None:
        _EXPORTER.flush()
