"""SQL protocol server + session manager.

Reference role: sail-spark-connect's service layer + sail-session's
SessionManager (session map keyed by id with timeout eviction —
crates/sail-session/src/session_manager/mod.rs). The wire contract is the
engine's own protobuf service (sql_service.proto) pending vendored Spark
Connect protos; results stream to the client as Arrow IPC chunks exactly
as Spark Connect's ExecutePlanResponse does.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import uuid
from concurrent import futures
from typing import Dict, Optional, Tuple

import grpc

from .exec.proto import sql_service_pb2 as spb

_SQL_SERVICE = "sail_tpu.sql.SqlService"


class SessionManager:
    """Sessions keyed by id, evicted after ``timeout_s`` of inactivity."""

    def __init__(self, timeout_s: float = 3600.0):
        from .session import SparkSession
        self._factory = lambda conf: SparkSession(conf)
        self._sessions: Dict[str, Tuple[object, float]] = {}
        self._lock = threading.Lock()
        self.timeout_s = timeout_s

    def get_or_create(self, session_id: str, conf: Optional[dict] = None):
        now = time.time()
        with self._lock:
            self._evict(now)
            from .catalog.system import SYSTEM
            SYSTEM.record_session(session_id)
            if session_id in self._sessions:
                session, _ = self._sessions[session_id]
                self._sessions[session_id] = (session, now)
                return session
            session = self._factory(dict(conf or {}))
            self._sessions[session_id] = (session, now)
            return session

    def release(self, session_id: str):
        from .catalog.system import SYSTEM
        SYSTEM.end_session(session_id)
        with self._lock:
            self._sessions.pop(session_id, None)

    def _evict(self, now: float):
        dead = [sid for sid, (_, t) in self._sessions.items()
                if now - t > self.timeout_s]
        for sid in dead:
            del self._sessions[sid]

    def __len__(self):
        return len(self._sessions)


class SqlServer:
    """gRPC server speaking the engine's SQL protocol."""

    CHUNK_ROWS = 65536

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_timeout_s: float = 3600.0):
        self.sessions = SessionManager(session_timeout_s)
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((self._service(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def start(self):
        self._server.start()
        return self

    def stop(self, grace: float = 1.0):
        self._server.stop(grace=grace)

    def wait(self):
        self._server.wait_for_termination()

    # -- service ---------------------------------------------------------
    def _service(self):
        def execute_sql(request: spb.ExecuteSqlRequest, context):
            import pyarrow as pa
            try:
                if request.session_id:
                    session = self.sessions.get_or_create(
                        request.session_id, dict(request.conf))
                else:
                    # anonymous one-shot: never registered, dies with the RPC
                    from .session import SparkSession
                    session = SparkSession(dict(request.conf))
                table = session.sql(request.sql).toArrow()
                for chunk_start in range(0, max(table.num_rows, 1),
                                         self.CHUNK_ROWS):
                    chunk = table.slice(chunk_start, self.CHUNK_ROWS)
                    sink = pa.BufferOutputStream()
                    with pa.ipc.new_stream(sink, table.schema) as w:
                        w.write_table(chunk)
                    last = chunk_start + self.CHUNK_ROWS >= table.num_rows
                    yield spb.ExecuteSqlResponse(
                        arrow_ipc=sink.getvalue().to_pybytes(), last=last)
            except Exception as e:  # noqa: BLE001 — errors cross the wire
                yield spb.ExecuteSqlResponse(error=f"{type(e).__name__}: {e}",
                                             last=True)

        def new_session(request: spb.SessionRequest, context):
            sid = request.session_id or uuid.uuid4().hex
            self.sessions.get_or_create(sid)
            return spb.SessionResponse(session_id=sid)

        def release_session(request: spb.SessionRequest, context):
            self.sessions.release(request.session_id)
            return spb.SessionResponse(session_id=request.session_id)

        return grpc.method_handlers_generic_handler(_SQL_SERVICE, {
            "ExecuteSql": grpc.unary_stream_rpc_method_handler(
                execute_sql,
                request_deserializer=spb.ExecuteSqlRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
            "NewSession": grpc.unary_unary_rpc_method_handler(
                new_session,
                request_deserializer=spb.SessionRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
            "ReleaseSession": grpc.unary_unary_rpc_method_handler(
                release_session,
                request_deserializer=spb.SessionRequest.FromString,
                response_serializer=lambda m: m.SerializeToString()),
        })


class SqlClient:
    """Thin client for the SQL protocol (used by the shell and tests)."""

    def __init__(self, address: str, session_id: Optional[str] = None):
        self._channel = grpc.insecure_channel(address)
        self.session_id = session_id or uuid.uuid4().hex

    def sql(self, query: str, conf: Optional[dict] = None):
        import pyarrow as pa
        rpc = self._channel.unary_stream(
            f"/{_SQL_SERVICE}/ExecuteSql",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=spb.ExecuteSqlResponse.FromString)
        chunks = []
        for resp in rpc(spb.ExecuteSqlRequest(session_id=self.session_id,
                                              sql=query,
                                              conf=dict(conf or {}))):
            if resp.error:
                raise RuntimeError(resp.error)
            if resp.arrow_ipc:
                chunks.append(pa.ipc.open_stream(resp.arrow_ipc).read_all())
        if not chunks:
            return pa.table({})
        return pa.concat_tables(chunks)

    def close(self):
        self._channel.close()
