"""Unresolved plan IR (queries and commands).

Mirrors the role of the reference's plan spec — 55 query-node and 67
command-node variants (reference: crates/sail-common/src/spec/plan.rs:75-553).
This v0 covers the relational core plus common commands; the inventory grows
with each subsystem (streaming, lakehouse DML, catalog commands).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from .data_type import DataType, Schema
from .expression import Expr, SortOrder


@dataclass(frozen=True)
class Plan:
    """Top-level plan: either a query or a command."""


@dataclass(frozen=True)
class QueryPlan(Plan):
    """Base for relational query nodes."""


# ---------------------------------------------------------------------------
# Leaf nodes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ReadNamedTable(QueryPlan):
    name: Tuple[str, ...]
    temporal: Optional[str] = None  # time-travel spec
    options: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class ReadDataSource(QueryPlan):
    format: str
    paths: Tuple[str, ...] = ()
    schema: Optional[Schema] = None
    options: Tuple[Tuple[str, str], ...] = ()
    predicates: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class ReadUdtf(QueryPlan):
    name: str
    args: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class LocalRelation(QueryPlan):
    """In-memory data; ``data`` is Arrow IPC bytes or a host table handle."""

    data: object = None
    schema: Optional[Schema] = None


@dataclass(frozen=True)
class Range(QueryPlan):
    start: int = 0
    end: int = 0
    step: int = 1
    num_partitions: Optional[int] = None


@dataclass(frozen=True)
class Values(QueryPlan):
    rows: Tuple[Tuple[Expr, ...], ...] = ()


@dataclass(frozen=True)
class OneRow(QueryPlan):
    """A single anonymous row — the relation behind FROM-less SELECTs."""


# ---------------------------------------------------------------------------
# Unary nodes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Project(QueryPlan):
    input: Optional[QueryPlan]
    expressions: Tuple[Expr, ...]


@dataclass(frozen=True)
class Filter(QueryPlan):
    input: QueryPlan
    condition: Expr


@dataclass(frozen=True)
class Sort(QueryPlan):
    input: QueryPlan
    order: Tuple[SortOrder, ...]
    is_global: bool = True


@dataclass(frozen=True)
class Limit(QueryPlan):
    input: QueryPlan
    limit: Optional[int] = None
    offset: int = 0


@dataclass(frozen=True)
class Tail(QueryPlan):
    input: QueryPlan
    limit: int = 0


@dataclass(frozen=True)
class Aggregate(QueryPlan):
    input: QueryPlan
    group: Tuple[Expr, ...] = ()
    aggregate: Tuple[Expr, ...] = ()
    having: Optional[Expr] = None
    grouping_sets: Optional[Tuple[Tuple[Expr, ...], ...]] = None
    rollup: bool = False
    cube: bool = False


@dataclass(frozen=True)
class Deduplicate(QueryPlan):
    input: QueryPlan
    columns: Tuple[str, ...] = ()  # empty → all columns
    within_watermark: bool = False


@dataclass(frozen=True)
class Sample(QueryPlan):
    input: QueryPlan
    lower_bound: float = 0.0
    upper_bound: float = 1.0
    with_replacement: bool = False
    seed: Optional[int] = None


@dataclass(frozen=True)
class Offset(QueryPlan):
    input: QueryPlan
    offset: int = 0


@dataclass(frozen=True)
class SubqueryAlias(QueryPlan):
    input: QueryPlan
    alias: str
    qualifier: Tuple[str, ...] = ()
    columns: Tuple[str, ...] = ()  # optional column renames


@dataclass(frozen=True)
class Repartition(QueryPlan):
    input: QueryPlan
    num_partitions: Optional[int] = None
    expressions: Tuple[Expr, ...] = ()  # empty → round-robin


@dataclass(frozen=True)
class WithColumns(QueryPlan):
    input: QueryPlan
    aliases: Tuple[Expr, ...] = ()  # Alias exprs


@dataclass(frozen=True)
class WithColumnsRenamed(QueryPlan):
    input: QueryPlan
    renames: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class Drop(QueryPlan):
    input: QueryPlan
    columns: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ToSchema(QueryPlan):
    input: QueryPlan
    schema: Schema = None


@dataclass(frozen=True)
class WithCtes(QueryPlan):
    input: QueryPlan
    ctes: Tuple[Tuple[str, QueryPlan], ...] = ()
    recursive: bool = False


@dataclass(frozen=True)
class Pivot(QueryPlan):
    input: QueryPlan
    group: Tuple[Expr, ...] = ()
    aggregate: Tuple[Expr, ...] = ()
    pivot_column: Optional[Expr] = None
    pivot_values: Tuple[Expr, ...] = ()


@dataclass(frozen=True)
class Unpivot(QueryPlan):
    input: QueryPlan
    ids: Tuple[Expr, ...] = ()
    values: Tuple[Expr, ...] = ()
    variable_column_name: str = "variable"
    value_column_name: str = "value"


@dataclass(frozen=True)
class UdtfCall(QueryPlan):
    """Pickle-delivered Python UDTF in relation position (reference:
    sail-python-udf pyspark_udtf.rs — handler class with eval/terminate)."""
    handler: object = None        # the decoded UDTF class
    args: Tuple[Expr, ...] = ()
    return_type: object = None    # dt.StructType
    name: str = "udtf"


@dataclass(frozen=True)
class GroupMap(QueryPlan):
    """groupBy(...).applyInPandas / apply — one host UDF call per group
    (reference: sail-python-udf grouped-map kinds,
    pyspark_udf.rs:19-27 + MapPartitionsExec plumbing)."""
    input: QueryPlan = None
    grouping: Tuple[Expr, ...] = ()
    udf: object = None            # functions.udf.UserDefinedFunction


@dataclass(frozen=True)
class CoGroupMap(QueryPlan):
    """cogroup(...).applyInPandas — UDF over aligned key groups of two
    inputs (reference: pyspark_cogroup_map_udf)."""
    input: QueryPlan = None
    other: QueryPlan = None
    input_grouping: Tuple[Expr, ...] = ()
    other_grouping: Tuple[Expr, ...] = ()
    udf: object = None


@dataclass(frozen=True)
class MapPartitions(QueryPlan):
    """mapInPandas / mapInArrow — iterator-of-batches UDF per partition
    (reference: pyspark_map_iter_udf.rs)."""
    input: QueryPlan = None
    udf: object = None
    is_barrier: bool = False


@dataclass(frozen=True)
class WithWatermark(QueryPlan):
    """Streaming watermark marker (event-time column + delay)."""

    input: QueryPlan = None
    column: str = ""
    delay_seconds: float = 0.0


@dataclass(frozen=True)
class LateralView(QueryPlan):
    input: QueryPlan
    generator: Expr = None
    table_alias: Optional[str] = None
    column_aliases: Tuple[str, ...] = ()
    outer: bool = False


# ---------------------------------------------------------------------------
# Binary / n-ary nodes
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Join(QueryPlan):
    left: QueryPlan
    right: QueryPlan
    join_type: str = "inner"  # inner|left|right|full|semi|anti|cross
    condition: Optional[Expr] = None
    using: Tuple[str, ...] = ()
    is_lateral: bool = False
    is_natural: bool = False  # resolver expands to USING over common columns


@dataclass(frozen=True)
class SetOperation(QueryPlan):
    left: QueryPlan
    right: QueryPlan
    op: str = "union"  # union|intersect|except
    all: bool = False
    by_name: bool = False


# ---------------------------------------------------------------------------
# Commands
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class CommandPlan(Plan):
    """Base for commands (DDL/DML/session)."""


@dataclass(frozen=True)
class CreateTable(CommandPlan):
    name: Tuple[str, ...]
    schema: Optional[Schema] = None
    format: Optional[str] = None
    location: Optional[str] = None
    query: Optional[QueryPlan] = None  # CTAS
    if_not_exists: bool = False
    replace: bool = False
    partition_by: Tuple[str, ...] = ()
    options: Tuple[Tuple[str, str], ...] = ()
    comment: Optional[str] = None


@dataclass(frozen=True)
class CreateView(CommandPlan):
    name: Tuple[str, ...]
    query: QueryPlan = None
    temporary: bool = True
    replace: bool = False
    columns: Tuple[str, ...] = ()


@dataclass(frozen=True)
class DropTable(CommandPlan):
    name: Tuple[str, ...]
    if_exists: bool = False
    purge: bool = False
    is_view: bool = False


@dataclass(frozen=True)
class InsertInto(CommandPlan):
    table: Tuple[str, ...]
    query: QueryPlan = None
    overwrite: bool = False
    columns: Tuple[str, ...] = ()
    partition_spec: Tuple[Tuple[str, Optional[str]], ...] = ()


@dataclass(frozen=True)
class WriteDataSource(CommandPlan):
    query: QueryPlan
    format: str = "parquet"
    path: Optional[str] = None
    mode: str = "error"  # append|overwrite|error|ignore
    partition_by: Tuple[str, ...] = ()
    options: Tuple[Tuple[str, str], ...] = ()
    table: Optional[Tuple[str, ...]] = None


@dataclass(frozen=True)
class Explain(CommandPlan):
    query: QueryPlan
    mode: str = "simple"  # simple|extended|codegen|cost|formatted|analyze
    format: str = "text"  # text | json (EXPLAIN [ANALYZE] FORMAT JSON)


@dataclass(frozen=True)
class SetVariable(CommandPlan):
    name: str = ""
    value: Optional[str] = None  # None → show


@dataclass(frozen=True)
class ResetVariable(CommandPlan):
    name: Optional[str] = None


@dataclass(frozen=True)
class ShowTables(CommandPlan):
    database: Optional[Tuple[str, ...]] = None
    pattern: Optional[str] = None


@dataclass(frozen=True)
class ShowDatabases(CommandPlan):
    pattern: Optional[str] = None


@dataclass(frozen=True)
class ShowColumns(CommandPlan):
    table: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ShowFunctions(CommandPlan):
    pattern: Optional[str] = None


@dataclass(frozen=True)
class DescribeTable(CommandPlan):
    table: Tuple[str, ...] = ()
    extended: bool = False


@dataclass(frozen=True)
class CreateDatabase(CommandPlan):
    name: Tuple[str, ...] = ()
    if_not_exists: bool = False
    comment: Optional[str] = None
    location: Optional[str] = None


@dataclass(frozen=True)
class DropDatabase(CommandPlan):
    name: Tuple[str, ...] = ()
    if_exists: bool = False
    cascade: bool = False


@dataclass(frozen=True)
class UseDatabase(CommandPlan):
    name: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CacheTable(CommandPlan):
    name: Tuple[str, ...] = ()
    query: Optional[QueryPlan] = None
    lazy: bool = False


@dataclass(frozen=True)
class UncacheTable(CommandPlan):
    name: Tuple[str, ...] = ()
    if_exists: bool = False


@dataclass(frozen=True)
class CacheMaterialized(CommandPlan):
    """CACHE MATERIALIZED [VIEW] name AS query — a continuously-
    maintained materialized view (exec/result_cache.py): base-table
    DML folds deltas into the cached fragment at marker cadence."""

    name: Tuple[str, ...] = ()
    query: Optional[QueryPlan] = None


@dataclass(frozen=True)
class UncacheMaterialized(CommandPlan):
    name: Tuple[str, ...] = ()
    if_exists: bool = False


@dataclass(frozen=True)
class ShowCatalogs(CommandPlan):
    pattern: Optional[str] = None


@dataclass(frozen=True)
class TruncateTable(CommandPlan):
    name: Tuple[str, ...] = ()


@dataclass(frozen=True)
class RefreshTable(CommandPlan):
    name: Tuple[str, ...] = ()


@dataclass(frozen=True)
class ClearCache(CommandPlan):
    pass


@dataclass(frozen=True)
class ShowCreateTable(CommandPlan):
    name: Tuple[str, ...] = ()


@dataclass(frozen=True)
class AnalyzeTable(CommandPlan):
    name: Tuple[str, ...] = ()
    columns: Tuple[str, ...] = ()
    noscan: bool = False


@dataclass(frozen=True)
class AlterTable(CommandPlan):
    """action: rename | add_columns | drop_columns | rename_column |
    set_properties | unset_properties | set_comment"""
    name: Tuple[str, ...] = ()
    action: str = "rename"
    new_name: Tuple[str, ...] = ()
    columns: Tuple[Tuple[str, "DataType"], ...] = ()
    column_names: Tuple[str, ...] = ()
    properties: Tuple[Tuple[str, Optional[str]], ...] = ()
    comment: Optional[str] = None


@dataclass(frozen=True)
class DescribeDatabase(CommandPlan):
    name: Tuple[str, ...] = ()
    extended: bool = False


@dataclass(frozen=True)
class ShowTblProperties(CommandPlan):
    name: Tuple[str, ...] = ()
    key: Optional[str] = None


@dataclass(frozen=True)
class ShowPartitions(CommandPlan):
    name: Tuple[str, ...] = ()


@dataclass(frozen=True)
class CommentOn(CommandPlan):
    kind: str = "table"  # table | database
    name: Tuple[str, ...] = ()
    comment: Optional[str] = None


@dataclass(frozen=True)
class Delete(CommandPlan):
    table: Tuple[str, ...] = ()
    condition: Optional[Expr] = None


@dataclass(frozen=True)
class Update(CommandPlan):
    table: Tuple[str, ...] = ()
    assignments: Tuple[Tuple[Tuple[str, ...], Expr], ...] = ()
    condition: Optional[Expr] = None


@dataclass(frozen=True)
class MergeAction:
    action: str = "update"  # update|delete|insert
    condition: Optional[Expr] = None
    assignments: Tuple[Tuple[Tuple[str, ...], Expr], ...] = ()


@dataclass(frozen=True)
class MergeInto(CommandPlan):
    target: Tuple[str, ...] = ()
    target_alias: Optional[str] = None
    source: QueryPlan = None
    condition: Expr = None
    matched_actions: Tuple[MergeAction, ...] = ()
    not_matched_actions: Tuple[MergeAction, ...] = ()
    not_matched_by_source_actions: Tuple[MergeAction, ...] = ()
