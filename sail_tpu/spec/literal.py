"""Literal values for the spec IR.

Mirrors the role of the reference's literal spec
(reference: crates/sail-common/src/spec/literal.rs), as a single tagged
dataclass instead of 30+ variants: the logical type carries the tag.
"""

from __future__ import annotations

import datetime
import decimal
from dataclasses import dataclass
from typing import Any, Optional

from .data_type import (
    BooleanType,
    DataType,
    DateType,
    DayTimeIntervalType,
    DecimalType,
    DoubleType,
    IntegerType,
    LongType,
    NullType,
    StringType,
    TimestampType,
    TimeType,
)


@dataclass(frozen=True)
class Literal:
    data_type: DataType
    value: Any  # None means NULL of data_type

    @property
    def is_null(self) -> bool:
        return self.value is None

    # -- constructors -------------------------------------------------------
    @staticmethod
    def null(dt: Optional[DataType] = None) -> "Literal":
        return Literal(dt or NullType(), None)

    @staticmethod
    def boolean(v: bool) -> "Literal":
        return Literal(BooleanType(), bool(v))

    @staticmethod
    def int32(v: int) -> "Literal":
        return Literal(IntegerType(), int(v))

    @staticmethod
    def int64(v: int) -> "Literal":
        return Literal(LongType(), int(v))

    @staticmethod
    def float64(v: float) -> "Literal":
        return Literal(DoubleType(), float(v))

    @staticmethod
    def string(v: str) -> "Literal":
        return Literal(StringType(), str(v))

    @staticmethod
    def decimal(v: decimal.Decimal, precision: int, scale: int) -> "Literal":
        return Literal(DecimalType(precision, scale), v)

    @staticmethod
    def date(v: datetime.date) -> "Literal":
        return Literal(DateType(), v)

    @staticmethod
    def timestamp(v: datetime.datetime, tz: Optional[str] = "UTC") -> "Literal":
        return Literal(TimestampType(tz), v)

    @staticmethod
    def interval_microseconds(us: int) -> "Literal":
        return Literal(DayTimeIntervalType(), int(us))

    # -- device value -------------------------------------------------------
    def physical_value(self):
        """The value as stored on device (epoch days/us, scaled decimal int)."""
        if self.value is None:
            return None
        if isinstance(self.data_type, DateType):
            return (self.value - datetime.date(1970, 1, 1)).days
        if isinstance(self.data_type, TimestampType):
            v = self.value
            if self.data_type.timezone is None:
                # timestamp_ntz stores the WALL time — no zone conversion
                if v.tzinfo is not None:
                    v = v.replace(tzinfo=None)
                return int(v.replace(
                    tzinfo=datetime.timezone.utc).timestamp() * 1_000_000)
            if v.tzinfo is None:
                # Spark semantics: naive timestamp literals are interpreted
                # in the session timezone (spark.sql.session.timeZone)
                from ..utils.tz import localize
                v = localize(v)
            return int(v.timestamp() * 1_000_000)
        if isinstance(self.data_type, TimeType):
            from .data_type import time_to_micros
            v = self.value
            if isinstance(v, datetime.time):
                return time_to_micros(v)
            return int(v)
        if isinstance(self.data_type, DecimalType):
            if self.data_type.physical_dtype == "int64":
                return int(
                    decimal.Decimal(self.value).scaleb(self.data_type.scale)
                    .to_integral_value(rounding=decimal.ROUND_HALF_UP)
                )
            return float(self.value)
        return self.value
