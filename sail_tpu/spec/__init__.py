"""The spec IR: the protocol-independent intermediate representation that all
front-ends (SQL, DataFrame API, Spark Connect) lower into, and that the plan
resolver consumes (reference role: crates/sail-common/src/spec/)."""

from .data_type import (  # noqa: F401
    ArrayType,
    BinaryType,
    BooleanType,
    ByteType,
    CalendarIntervalType,
    DataType,
    DateType,
    DayTimeIntervalType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    MapType,
    NullType,
    Schema,
    ShortType,
    StringType,
    StructField,
    StructType,
    TimestampType,
    TimeType,
    YearMonthIntervalType,
    common_type,
)
from .literal import Literal as LiteralValue  # noqa: F401
from . import expression  # noqa: F401
from . import plan  # noqa: F401
from .expression import col, lit  # noqa: F401
