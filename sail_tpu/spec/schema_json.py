"""Spark JSON schema ⇄ spec types.

Reference role: the schema (de)serialization used by Spark Connect's
json_to_ddl and the Delta metaData.schemaString field
(crates/sail-delta-lake/src/spec/, sail-spark-connect plan_analyzer).
"""

from __future__ import annotations

from . import data_type as dt


def schema_from_json(obj) -> dt.StructType:
    out = type_from_json(obj)
    if not isinstance(out, dt.StructType):
        raise ValueError("json schema must be a struct")
    return out


def type_from_json(t) -> dt.DataType:
    if isinstance(t, dict):
        kind = t.get("type")
        if kind == "struct":
            return dt.StructType(tuple(
                dt.StructField(f["name"], type_from_json(f["type"]),
                               bool(f.get("nullable", True)))
            for f in t.get("fields", ())))
        if kind == "array":
            return dt.ArrayType(type_from_json(t["elementType"]),
                                bool(t.get("containsNull", True)))
        if kind == "map":
            return dt.MapType(type_from_json(t["keyType"]),
                              type_from_json(t["valueType"]),
                              bool(t.get("valueContainsNull", True)))
        raise ValueError(f"unknown json type {t}")
    from ..sql.parser import parse_data_type
    return parse_data_type(str(t))


_SIMPLE_NAMES = {
    dt.NullType: "void",
    dt.BooleanType: "boolean",
    dt.ByteType: "byte",
    dt.ShortType: "short",
    dt.IntegerType: "integer",
    dt.LongType: "long",
    dt.FloatType: "float",
    dt.DoubleType: "double",
    dt.StringType: "string",
    dt.BinaryType: "binary",
    dt.DateType: "date",
}


def type_to_json(d: dt.DataType):
    if isinstance(d, dt.StructType):
        return {"type": "struct", "fields": [
            {"name": f.name, "type": type_to_json(f.data_type),
             "nullable": f.nullable, "metadata": {}}
            for f in d.fields]}
    if isinstance(d, dt.ArrayType):
        return {"type": "array", "elementType": type_to_json(d.element_type),
                "containsNull": d.contains_null}
    if isinstance(d, dt.MapType):
        return {"type": "map", "keyType": type_to_json(d.key_type),
                "valueType": type_to_json(d.value_type),
                "valueContainsNull": d.value_contains_null}
    if isinstance(d, dt.DecimalType):
        return f"decimal({d.precision},{d.scale})"
    if isinstance(d, dt.TimestampType):
        return "timestamp" if d.timezone is not None else "timestamp_ntz"
    for cls, name in _SIMPLE_NAMES.items():
        if isinstance(d, cls):
            return name
    raise ValueError(f"cannot serialize type {d!r} to Spark JSON")
