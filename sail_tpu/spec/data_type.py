"""Logical data types for the sail-tpu spec IR.

Mirrors the role of the reference's ``sail-common`` spec data types
(reference: crates/sail-common/src/spec/data_type.rs), re-designed for a
TPU-native engine: every logical type declares its *device representation*
(``physical_dtype``) — the fixed-width JAX dtype its values occupy in HBM —
or ``None`` when values stay host-side (variable-width data is
dictionary-encoded to int32 codes on device).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass(frozen=True)
class DataType:
    """Base class for all logical types."""

    def simple_string(self) -> str:
        return type(self).__name__.lower()

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def is_integer(self) -> bool:
        return False

    @property
    def is_floating(self) -> bool:
        return False

    @property
    def physical_dtype(self) -> Optional[str]:
        """JAX dtype name of the on-device representation, or None if host-only."""
        return None


@dataclass(frozen=True)
class NullType(DataType):
    def simple_string(self) -> str:
        return "void"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int8"


@dataclass(frozen=True)
class BooleanType(DataType):
    def simple_string(self) -> str:
        return "boolean"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "bool"


@dataclass(frozen=True)
class _IntegerType(DataType):
    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_integer(self) -> bool:
        return True


@dataclass(frozen=True)
class ByteType(_IntegerType):
    def simple_string(self) -> str:
        return "tinyint"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int8"


@dataclass(frozen=True)
class ShortType(_IntegerType):
    def simple_string(self) -> str:
        return "smallint"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int16"


@dataclass(frozen=True)
class IntegerType(_IntegerType):
    def simple_string(self) -> str:
        return "int"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int32"


@dataclass(frozen=True)
class LongType(_IntegerType):
    def simple_string(self) -> str:
        return "bigint"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int64"


@dataclass(frozen=True)
class FloatType(DataType):
    def simple_string(self) -> str:
        return "float"

    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_floating(self) -> bool:
        return True

    @property
    def physical_dtype(self) -> Optional[str]:
        return "float32"


@dataclass(frozen=True)
class DoubleType(DataType):
    def simple_string(self) -> str:
        return "double"

    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def is_floating(self) -> bool:
        return True

    @property
    def physical_dtype(self) -> Optional[str]:
        return "float64"


@dataclass(frozen=True)
class DecimalType(DataType):
    """Fixed-point decimal.

    Device representation: the *unscaled* int64 whenever the scale is small
    (exact arithmetic; values beyond ±2^63 unscaled are a v0 limitation —
    the Arrow boundary validates ingested values). KNOWN LIMITATION:
    device-side arithmetic (multiply, sum) on wide low-scale decimals can
    overflow int64 silently when true magnitudes approach 2^63/10^scale;
    int128 emulation (hi/lo int64 pairs, a Pallas kernel candidate) is the
    planned exact wide path. High-scale (>6) decimals degrade to float64.
    """

    precision: int = 10
    scale: int = 0

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    @property
    def is_numeric(self) -> bool:
        return True

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int64" if self.scale <= 6 else "float64"


@dataclass(frozen=True)
class StringType(DataType):
    """UTF-8 string. Device representation: int32 dictionary codes; the
    dictionary itself (Arrow StringArray) stays on host."""

    def simple_string(self) -> str:
        return "string"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int32"


@dataclass(frozen=True)
class BinaryType(DataType):
    def simple_string(self) -> str:
        return "binary"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int32"


@dataclass(frozen=True)
class DateType(DataType):
    """Days since UNIX epoch (Arrow date32)."""

    def simple_string(self) -> str:
        return "date"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int32"


@dataclass(frozen=True)
class TimeType(DataType):
    """Time of day, microsecond precision (Arrow time64[us])."""

    def simple_string(self) -> str:
        return "time"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int64"


def time_to_micros(v) -> int:
    """datetime.time → microseconds since midnight (single impl shared by
    the literal, host-interpreter and datetime-function paths)."""
    return ((v.hour * 60 + v.minute) * 60 + v.second) * 1_000_000 \
        + v.microsecond


@dataclass(frozen=True)
class TimestampType(DataType):
    """Microseconds since UNIX epoch; ``timezone=None`` means timestamp_ntz."""

    timezone: Optional[str] = "UTC"

    def simple_string(self) -> str:
        return "timestamp" if self.timezone else "timestamp_ntz"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int64"


@dataclass(frozen=True)
class DayTimeIntervalType(DataType):
    """Microsecond-resolution interval (Spark DayTimeIntervalType)."""

    start_field: int = 0  # DAY
    end_field: int = 3  # SECOND

    def simple_string(self) -> str:
        return "interval day to second"

    @property
    def is_numeric(self) -> bool:
        return False

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int64"


@dataclass(frozen=True)
class YearMonthIntervalType(DataType):
    start_field: int = 0  # YEAR
    end_field: int = 1  # MONTH

    def simple_string(self) -> str:
        return "interval year to month"

    @property
    def physical_dtype(self) -> Optional[str]:
        return "int32"


@dataclass(frozen=True)
class CalendarIntervalType(DataType):
    def simple_string(self) -> str:
        return "interval"


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True
    metadata: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class StructType(DataType):
    fields: Tuple[StructField, ...] = ()

    def simple_string(self) -> str:
        inner = ",".join(f"{f.name}:{f.data_type.simple_string()}" for f in self.fields)
        return f"struct<{inner}>"

    def field_names(self) -> Tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    def field(self, name: str) -> StructField:
        for f in self.fields:
            if f.name == name:
                return f
        raise KeyError(name)


@dataclass(frozen=True)
class ArrayType(DataType):
    element_type: DataType = field(default_factory=lambda: NullType())
    contains_null: bool = True

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"


@dataclass(frozen=True)
class MapType(DataType):
    key_type: DataType = field(default_factory=lambda: NullType())
    value_type: DataType = field(default_factory=lambda: NullType())
    value_contains_null: bool = True

    def simple_string(self) -> str:
        return f"map<{self.key_type.simple_string()},{self.value_type.simple_string()}>"


# Schema is just a struct at top level, as in Spark.
Schema = StructType


# ---------------------------------------------------------------------------
# Type lattice helpers (Spark's implicit cast / common-type rules, simplified)
# ---------------------------------------------------------------------------

_NUMERIC_ORDER = {
    "ByteType": 0,
    "ShortType": 1,
    "IntegerType": 2,
    "LongType": 3,
    "DecimalType": 4,
    "FloatType": 5,
    "DoubleType": 6,
}


def common_type(a: DataType, b: DataType) -> DataType:
    """Least common type for binary expressions (simplified Spark coercion)."""
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    an, bn = type(a).__name__, type(b).__name__
    if an in _NUMERIC_ORDER and bn in _NUMERIC_ORDER:
        # Decimal + float → double; otherwise wider wins.
        if {an, bn} & {"FloatType", "DoubleType"} and "DecimalType" in {an, bn}:
            return DoubleType()
        if an == "DecimalType" and bn == "DecimalType":
            assert isinstance(a, DecimalType) and isinstance(b, DecimalType)
            int_digits = max(a.precision - a.scale, b.precision - b.scale)
            scale = max(a.scale, b.scale)
            return DecimalType(min(int_digits + scale, 38), scale)
        if an == "DecimalType":
            assert isinstance(a, DecimalType)
            return a if _NUMERIC_ORDER[bn] < _NUMERIC_ORDER["DecimalType"] else b
        if bn == "DecimalType":
            assert isinstance(b, DecimalType)
            return b if _NUMERIC_ORDER[an] < _NUMERIC_ORDER["DecimalType"] else a
        return a if _NUMERIC_ORDER[an] >= _NUMERIC_ORDER[bn] else b
    if isinstance(a, ArrayType) and isinstance(b, ArrayType):
        return ArrayType(common_type(a.element_type, b.element_type),
                         a.contains_null or b.contains_null)
    if isinstance(a, MapType) and isinstance(b, MapType):
        return MapType(common_type(a.key_type, b.key_type),
                       common_type(a.value_type, b.value_type),
                       a.value_contains_null or b.value_contains_null)
    if isinstance(a, StructType) and isinstance(b, StructType) and \
            len(a.fields) == len(b.fields):
        return StructType(tuple(
            StructField(fa.name,
                        common_type(fa.data_type, fb.data_type),
                        fa.nullable or fb.nullable)
            for fa, fb in zip(a.fields, b.fields)))
    if isinstance(a, StringType) and b.is_numeric:
        return DoubleType()
    if isinstance(b, StringType) and a.is_numeric:
        return DoubleType()
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return b
    if isinstance(a, TimestampType) and isinstance(b, DateType):
        return a
    if isinstance(a, StringType) and isinstance(b, (DateType, TimestampType)):
        return b
    if isinstance(b, StringType) and isinstance(a, (DateType, TimestampType)):
        return a
    raise TypeError(f"no common type for {a.simple_string()} and {b.simple_string()}")


def replace(dt, **kwargs):
    return dataclasses.replace(dt, **kwargs)
