"""Unresolved expression IR.

Mirrors the role of the reference's expression spec
(reference: crates/sail-common/src/spec/expression.rs). Operators are
represented as ``Function`` nodes (e.g. ``+`` → ``Function("+", [l, r])``),
matching Spark Connect's unresolved-function convention; the resolver binds
them against the function registry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Tuple

from .data_type import DataType
from .literal import Literal as LiteralValue


@dataclass(frozen=True)
class Expr:
    """Base class for unresolved expressions."""


@dataclass(frozen=True)
class Literal(Expr):
    value: LiteralValue


@dataclass(frozen=True)
class Attribute(Expr):
    """Unresolved column reference; ``name`` may be multi-part (a.b.c)."""

    name: Tuple[str, ...]
    plan_id: Optional[int] = None

    def last(self) -> str:
        return self.name[-1]


@dataclass(frozen=True)
class Star(Expr):
    """``*`` or ``qualifier.*``"""

    target: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Function(Expr):
    name: str
    args: Tuple[Expr, ...] = ()
    is_distinct: bool = False
    filter: Optional[Expr] = None  # FILTER (WHERE ...) clause on aggregates
    ignore_nulls: Optional[bool] = None


@dataclass(frozen=True)
class Alias(Expr):
    child: Expr
    name: Tuple[str, ...]
    metadata: Tuple[Tuple[str, str], ...] = ()


@dataclass(frozen=True)
class Cast(Expr):
    child: Expr
    data_type: DataType
    try_: bool = False


@dataclass(frozen=True)
class SortOrder(Expr):
    child: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None → Spark default (first if asc)


@dataclass(frozen=True)
class CaseWhen(Expr):
    branches: Tuple[Tuple[Expr, Expr], ...]  # (condition, value)
    else_value: Optional[Expr] = None


@dataclass(frozen=True)
class InList(Expr):
    child: Expr
    values: Tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class Between(Expr):
    child: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass(frozen=True)
class Like(Expr):
    child: Expr
    pattern: Expr
    negated: bool = False
    case_insensitive: bool = False
    escape: Optional[str] = None


@dataclass(frozen=True)
class Exists(Expr):
    """EXISTS (subquery); ``plan`` is a spec QueryPlan (forward ref)."""

    plan: object
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    plan: object


@dataclass(frozen=True)
class InSubquery(Expr):
    child: Expr
    plan: object
    negated: bool = False


@dataclass(frozen=True)
class WindowFrame:
    """Window frame boundaries. ``None`` bound means UNBOUNDED."""

    frame_type: str = "rows"  # "rows" | "range"
    lower: Optional[int] = None  # negative = preceding
    upper: Optional[int] = 0  # 0 = current row


@dataclass(frozen=True)
class Window(Expr):
    function: Expr
    partition_by: Tuple[Expr, ...] = ()
    order_by: Tuple[SortOrder, ...] = ()
    frame: Optional[WindowFrame] = None


@dataclass(frozen=True)
class LambdaFunction(Expr):
    body: Expr
    arguments: Tuple[str, ...] = ()


@dataclass(frozen=True)
class LambdaVariable(Expr):
    name: str


@dataclass(frozen=True)
class Extract(Expr):
    """EXTRACT(field FROM source)."""

    field_name: str
    child: Expr


# -- convenience builders ---------------------------------------------------

def col(*parts: str) -> Attribute:
    return Attribute(tuple(parts))


def lit(v) -> Literal:
    import datetime
    import decimal as _dec

    if isinstance(v, LiteralValue):
        return Literal(v)
    if v is None:
        return Literal(LiteralValue.null())
    if isinstance(v, bool):
        return Literal(LiteralValue.boolean(v))
    if isinstance(v, int):
        return Literal(LiteralValue.int32(v) if -(2**31) <= v < 2**31 else LiteralValue.int64(v))
    if isinstance(v, float):
        return Literal(LiteralValue.float64(v))
    if isinstance(v, str):
        return Literal(LiteralValue.string(v))
    if isinstance(v, _dec.Decimal):
        sign, digits, exp = v.as_tuple()
        scale = max(0, -int(exp))
        precision = max(len(digits) + max(0, int(exp)), scale + 1)
        return Literal(LiteralValue.decimal(v, precision, scale))
    if isinstance(v, datetime.datetime):
        return Literal(LiteralValue.timestamp(v))
    if isinstance(v, datetime.date):
        return Literal(LiteralValue.date(v))
    raise TypeError(f"cannot convert {type(v)} to literal")
