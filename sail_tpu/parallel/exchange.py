"""Data exchange between partitions: the engine's "shuffle" as XLA
collectives.

Reference role: the five InputModes — Forward, Merge, Shuffle, Broadcast,
Rescale — that form the reference's complete exchange vocabulary
(crates/sail-execution/src/job_graph/mod.rs:134-151), plus the shuffle
write/read data plane (src/plan/shuffle_write.rs, Arrow Flight streams).
TPU-native redesign: partitioned batches live as [P, capacity] arrays
sharded over a mesh axis; exchanges are `shard_map`-wrapped collectives —
hash shuffle = local bucket sort + `all_to_all` over ICI, broadcast =
`all_gather` — instead of TCP streams.

Static-shape contract: each (source→target) bucket has a fixed capacity;
overload is detected (per-bucket counts exported) and the host re-runs
with a larger bucket factor. Uniform hash keys need factor ≈ 1+ε; the
default doubles.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.hash import hash64
from ..spec import data_type as dt
from .mesh import DATA_AXIS


def bucket_by_partition(part_id, sel, num_partitions: int, bucket_cap: int):
    """Scatter local rows into per-target buckets.

    Returns (perm int32[num_partitions * bucket_cap], valid mask, overflow
    scalar): ``perm[t * bucket_cap + k]`` = local row index of the k-th row
    destined for target t. Rows beyond a bucket's capacity are dropped and
    counted in ``overflow``.
    """
    n = part_id.shape[0]
    pid = jnp.where(sel, part_id, num_partitions)  # dead rows to a trash bucket
    order = jnp.argsort(pid, stable=True).astype(jnp.int32)
    sorted_pid = pid[order]
    # rank within bucket = position - first position of the bucket
    first = jnp.searchsorted(sorted_pid, jnp.arange(num_partitions + 1,
                                                    dtype=sorted_pid.dtype),
                             side="left").astype(jnp.int32)
    pos = jnp.arange(n, dtype=jnp.int32)
    rank = pos - first[jnp.clip(sorted_pid, 0, num_partitions)]
    counts = first[1:] - first[:-1]  # rows per real bucket
    overflow = jnp.sum(jnp.maximum(counts[:num_partitions] - bucket_cap, 0))
    slot = jnp.clip(sorted_pid, 0, num_partitions - 1) * bucket_cap + \
        jnp.clip(rank, 0, bucket_cap - 1)
    ok = (sorted_pid < num_partitions) & (rank < bucket_cap)
    total = num_partitions * bucket_cap
    target = jnp.where(ok, slot, total)  # out-of-range → dropped by scatter
    perm = jnp.zeros(total, dtype=jnp.int32).at[target].set(order, mode="drop")
    valid = jnp.zeros(total, dtype=jnp.bool_).at[target].set(True, mode="drop")
    return perm, valid, overflow


def shuffle_local(arrays: Sequence[jnp.ndarray], sel, part_id,
                  num_partitions: int, bucket_cap: int):
    """Local side of the hash shuffle (inside shard_map, one partition).

    ``arrays``: per-column data [n]; returns per-column [num_partitions,
    bucket_cap] send buffers + valid mask + overflow count.
    """
    perm, valid, overflow = bucket_by_partition(part_id, sel, num_partitions,
                                                bucket_cap)
    out = [a[perm].reshape(num_partitions, bucket_cap) for a in arrays]
    return out, valid.reshape(num_partitions, bucket_cap), overflow


def make_shuffle(mesh: Mesh, num_cols: int, has_validity: Sequence[bool],
                 bucket_cap: int):
    """Build a jitted all-to-all hash shuffle over the mesh.

    Input:  columns as [P, n] sharded arrays (+ validity where present),
            sel [P, n], part_id [P, n].
    Output: columns as [P, P*bucket_cap] sharded arrays, sel, overflow [P].
    """
    num_partitions = mesh.shape[DATA_AXIS]

    def local_fn(cols, validities, sel, part_id):
        arrays = list(cols) + [v for v in validities if v is not None]
        bufs, valid, overflow = shuffle_local(arrays, sel, part_id,
                                              num_partitions, bucket_cap)
        # all_to_all: axis 0 is the target-partition dim
        exchanged = [jax.lax.all_to_all(b, DATA_AXIS, 0, 0, tiled=True)
                     for b in bufs]
        valid_x = jax.lax.all_to_all(valid, DATA_AXIS, 0, 0, tiled=True)
        ncols = len(cols)
        out_cols = [e.reshape(-1) for e in exchanged[:ncols]]
        out_vals = []
        vi = ncols
        for hv in has_validity:
            if hv:
                out_vals.append(exchanged[vi].reshape(-1))
                vi += 1
            else:
                out_vals.append(None)
        return out_cols, out_vals, valid_x.reshape(-1), overflow

    spec = P(DATA_AXIS)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec, spec),
        out_specs=(spec, spec, spec, spec))
    def shuffled(cols, validities, sel, part_id):
        # inside: leading dim is the local shard (size 1 after sharding [P, n])
        cols_l = [c[0] for c in cols]
        vals_l = [None if v is None else v[0] for v in validities]
        sel_l = sel[0]
        pid_l = part_id[0]
        out_cols, out_vals, out_sel, overflow = local_fn(cols_l, vals_l, sel_l, pid_l)
        return (tuple(c[None] for c in out_cols),
                tuple(None if v is None else v[None] for v in out_vals),
                out_sel[None], overflow[None])

    return shuffled


# ---------------------------------------------------------------------------
# The five exchange modes (SPMD formulations)
# ---------------------------------------------------------------------------

def exchange_forward(arrays):
    """Forward: partition i feeds consumer i unchanged."""
    return arrays


def exchange_broadcast(mesh: Mesh, array, axis: str = DATA_AXIS):
    """Broadcast: every partition receives all rows (build side of
    broadcast hash joins). [P, n] → [P, P*n] replicated content."""
    spec = P(axis)

    @functools.partial(jax.shard_map, mesh=mesh, in_specs=(spec,),
                       out_specs=spec)
    def bc(a):
        gathered = jax.lax.all_gather(a[0], axis, tiled=True)
        return gathered[None]

    return bc(array)


def exchange_merge(mesh: Mesh, array, axis: str = DATA_AXIS):
    """Merge: all partitions concatenate into every shard (the driver/root
    reads shard 0). Same collective as broadcast; semantic difference is
    that downstream runs single-partition."""
    return exchange_broadcast(mesh, array, axis)


def hash_partition_ids(key_datas, key_types: Sequence[dt.DataType],
                       num_partitions: int):
    h = hash64(list(key_datas), list(key_types))
    return (h % jnp.uint64(num_partitions)).astype(jnp.int32)
