"""SPMD mesh executor: a whole multi-stage job graph as ONE jitted program.

Reference role: the distributed execution path — ShuffleWriteExec hash
repartitioning + the Arrow Flight stream data plane + per-stage task
execution (crates/sail-execution/src/plan/shuffle_write.rs:42-114,
src/stream_service/server.rs:22-70, SURVEY.md §2.5/§2.8). TPU-native
redesign: when every stage of a job graph is co-resident on one
jax.sharding.Mesh, the stages and their exchanges compile into a single
shard_map program — SHUFFLE edges lower to local bucket scatter +
``jax.lax.all_to_all`` and BROADCAST edges to ``jax.lax.all_gather``, both
riding ICI instead of a host TCP data plane. The gRPC cluster runtime
(exec/cluster.py) remains the elastic fallback for graphs that cannot
co-reside (dynamic worker sets, host-only operators).

Static-shape contract: every stage output has a bind-time capacity; hash
buckets and group tables export overflow counters, and the host re-runs
the program with scaled capacities when any overflow fires (the same
detect-and-rerun protocol as parallel/exchange.py). Joins compile as the
unique-probe (PK-FK) plan first; build-side duplicate keys raise a retry
flag and the next attempt recompiles with a many-to-many expanding join
at ``probe_cap * expand_mult`` static capacity.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from ..columnar import arrow_interop as ai
from ..columnar.batch import (Column, DeviceBatch, HostBatch,
                              bucket_capacity)
from ..ops import aggregate as aggk
from ..ops import join as joink
from ..ops.hash import hash64
from ..plan import nodes as pn
from ..plan import rex as rx
from ..plan.compiler import ExprCompiler, HostFallback
from ..metrics import record as _record_metric
from ..spec import data_type as dt
from ..exec import job_graph as jg
from .exchange import bucket_by_partition
from .mesh import DATA_AXIS, make_mesh, partition_rows

_MESH_AGGS = {"count", "sum", "min", "max", "first", "last",
              "bool_and", "bool_or"}
_DEFAULT_GROUPS = 4096


class MeshUnsupported(Exception):
    """Plan shape the SPMD compiler cannot express; caller falls back."""


# Cols are positional lists of (data, validity-or-None); a fragment maps an
# environment of stage outputs to its own (cols, sel, retry_flags,
# fatal_flags).
Cols = List[Tuple[jnp.ndarray, Optional[jnp.ndarray]]]


@dataclasses.dataclass
class _Frag:
    fn: Callable  # env -> (cols, sel, retry, fatal)
    types: List[dt.DataType]
    dicts: Dict[int, pa.Array]
    cap: int  # per-shard output capacity


@dataclasses.dataclass
class _LeafData:
    """Host-partitioned scan data for one leaf stage."""
    datas: List[np.ndarray]          # [P, cap] per column
    validities: List[Optional[np.ndarray]]
    sel: np.ndarray                  # [P, cap]
    types: List[dt.DataType]
    dicts: Dict[int, pa.Array]
    cap: int
    # device-placed flat buffers, memoized so capacity-retry attempts and
    # the initial prefetch-overlapped upload share one H2D transfer
    placed: Optional[List] = None


def _positional_name(i: int) -> str:
    return f"c{i}"


# Compiled SPMD programs, keyed by (structural graph key, leaf-dictionary
# identity) — same contract as the local executor's _OpCache: entries hold
# strong references to the dictionaries baked into their closures.
_PROGRAM_CACHE: Dict = {}
_PROGRAM_CACHE_MAX = 64
# program structure -> first attempt index known to succeed (skips the
# unique-join attempt for programs that need expanding joins)
_ATTEMPT_HINT: Dict = {}


def _leaf_layout(leaves: Dict[int, "_LeafData"]):
    """Static input layout: [(leaf_id, (has_validity per column, ...))]."""
    return [(lid, tuple(v is not None for v in leaves[lid].validities))
            for lid in sorted(leaves)]


def _make_rebuild(layout):
    """Flat shard_map args → {leaf_id: (cols, sel)}. Closes over the
    static layout only (not the leaf buffers), so cached programs don't
    retain host data."""

    def rebuild(args):
        env: Dict = {}
        it = iter(args)
        for lid, has_validity in layout:
            cols: Cols = []
            for hv in has_validity:
                d = next(it)[0]
                val = next(it)[0] if hv else None
                cols.append((d, val))
            sel = next(it)[0]
            env[lid] = (cols, sel)
        return env

    return rebuild


class MeshExecutor:
    """Compiles a JobGraph into one shard_map program over a device mesh."""

    def __init__(self, mesh=None, config: Optional[dict] = None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.config = config or {}
        self._subquery_cache: Dict[int, object] = {}
        self.last_exchanges = 0       # collective edges in the last program
        self.last_hlo: Optional[str] = None
        self._group_cap = int(self.config.get(
            "spark.sail.mesh.maxGroups", _DEFAULT_GROUPS))

    @property
    def nparts(self) -> int:
        return int(self.mesh.shape[DATA_AXIS])

    # ------------------------------------------------------------------
    # public entry
    # ------------------------------------------------------------------
    def execute(self, plan: pn.PlanNode) -> Optional[pa.Table]:
        """Run ``plan`` distributed over the mesh; None → not supported
        (caller should run the local / gRPC-cluster path)."""
        if self.nparts < 2:
            return None
        graph = jg.split_job(plan, self.nparts)
        if graph is None:
            return None
        try:
            return self._run_graph(graph)
        except MeshUnsupported:
            return None

    def _pre_eval_subqueries(self, graph: jg.JobGraph) -> None:
        """Uncorrelated scalar subqueries evaluate once on the host before
        the SPMD program compiles; their values bake into the compiled
        closures as literals (same contract as the local engine,
        exec/local.py _pre_eval_subqueries)."""
        from ..exec.local import LocalExecutor

        loc = LocalExecutor(self.config)
        loc._subquery_cache = self._subquery_cache
        for stage in graph.stages:
            loc._pre_eval_subqueries(stage.plan)

    # ------------------------------------------------------------------
    # graph orchestration
    # ------------------------------------------------------------------
    def _consumer_modes(self, graph: jg.JobGraph) -> Dict[int, jg.InputMode]:
        modes: Dict[int, jg.InputMode] = {}
        for stage in graph.stages:
            for si in stage.inputs:
                if si.stage_id in modes and modes[si.stage_id] != si.mode:
                    raise MeshUnsupported("stage consumed in two modes")
                modes[si.stage_id] = si.mode
        return modes

    def _run_graph(self, graph: jg.JobGraph) -> pa.Table:
        from ..exec.local import LocalExecutor

        self._pre_eval_subqueries(graph)
        P = self.nparts
        modes = self._consumer_modes(graph)
        worker_stages = [s for s in graph.stages if not s.on_driver]
        root = graph.root
        if not root.on_driver or len(root.inputs) != 1:
            raise MeshUnsupported("root stage shape")
        top_id = root.inputs[0].stage_id

        # host-side leaf data (shared across retries). No prefetch stage
        # here: program compilation keys on EVERY leaf's signature
        # (_program_cache_key), so leaf prep is a barrier with nothing to
        # overlap against. Device upload is instead deferred to
        # _place_leaf (memoized per leaf) so a plan that later declines
        # with MeshUnsupported never pays host→device transfers and
        # capacity retries reuse one upload
        leaves: Dict[int, _LeafData] = {}
        for stage in worker_stages:
            scan = _bottom_scan(stage.plan)
            if scan is not None:
                leaves[stage.stage_id] = self._prepare_leaf(scan, graph, P)

        # (groups_mult, bucket_mult, expand_mult): the first attempt
        # compiles unique-key (PK-FK) joins; a duplicate-build-key or
        # capacity overflow raises a retry flag and recompiles with
        # scaled group/bucket capacities and expanding joins. The winning
        # attempt index is remembered per program structure so repeat
        # executions skip the doomed earlier attempts entirely.
        attempts = [(1, 1, 1), (4, 2, 4), (16, 4, 16)]
        base_key, dict_objs = self._program_cache_key(worker_stages,
                                                      leaves, 1, 1, 1)
        start = _ATTEMPT_HINT.get(base_key, 0)
        for idx in range(start, len(attempts)):
            groups_mult, bucket_mult, expand_mult = attempts[idx]
            # attempt 0's key is base_key itself; later attempts differ
            # only in the multiplier fields — swap them in without
            # re-encoding every stage plan
            cache_key = base_key if idx == 0 else \
                base_key[:4] + (groups_mult, bucket_mult, expand_mult) + \
                base_key[7:]
            result = self._compile_and_run(
                graph, worker_stages, modes, leaves, top_id,
                groups_mult, bucket_mult, expand_mult,
                cache_key, dict_objs)
            if result is None:
                continue  # retryable overflow: scale capacities and redo
            if idx > 0:
                _ATTEMPT_HINT[base_key] = idx
                while len(_ATTEMPT_HINT) > _PROGRAM_CACHE_MAX:
                    _ATTEMPT_HINT.pop(next(iter(_ATTEMPT_HINT)))
            out_cols, out_sel, frag = result
            # leaf input buffers are dead once the program produced its
            # outputs — release the memoized uploads before the driver
            # fragment runs its own device compute, or they pin HBM
            # through _assemble + the root plan
            for ld in leaves.values():
                ld.placed = None
            table = self._assemble(out_cols, out_sel, frag)
            root_plan = jg.attach_stage_inputs(root.plan, {top_id: table})
            root_plan = _reattach_scans(root_plan, graph.scan_tables)
            return LocalExecutor(self.config).execute(root_plan)
        raise MeshUnsupported("capacity overflow after retries")

    def _program_cache_key(self, worker_stages, leaves, groups_mult,
                           bucket_mult, expand_mult):
        """Structural cache key + the dictionary objects baked into the
        compiled closures (same identity contract as local._OpCache).

        Stage plans key by ``plan/stages.py plan_fingerprint`` — the
        per-stage structural fingerprint shared with the local
        executor's operator cache — instead of JSON-serializing every
        fragment (which inlined whole memory tables into the key on
        each lookup). Memory-table sources ride ``dict_objs`` so the
        hit path verifies them by identity like dictionaries; an
        unhashable fingerprint (exotic literals) falls back to the
        serialized form."""
        from ..plan.stages import plan_fingerprint
        plan_keys = []
        source_objs: list = []
        for s in worker_stages:
            fp, sources = plan_fingerprint(s.plan)
            try:
                hash(fp)
            except TypeError:
                fp = jg.encode_fragment(s.plan)
                sources = ()
            plan_keys.append(fp)
            source_objs.extend(sources)
        plans = tuple(plan_keys)
        shapes = tuple((s.stage_id, s.shuffle_keys, s.num_partitions)
                       for s in worker_stages)
        leaf_sig = tuple(
            (lid, ld.cap, tuple(repr(t) for t in ld.types),
             tuple(sorted(ld.dicts)))
            for lid, ld in sorted(leaves.items()))
        dict_objs = tuple(d for _, ld in sorted(leaves.items())
                          for _, d in sorted(ld.dicts.items(),
                                             key=lambda kv: kv[0])) \
            + tuple(source_objs)
        # scalar-subquery values bake into the compiled closures as
        # literals: key them like local._op_key (rex-walk order)
        from ..exec.local import _node_rex
        sub_vals = []
        for s in worker_stages:
            for node in pn.walk_plan(s.plan):
                for r in _node_rex(node):
                    for sub in rx.walk(r):
                        if isinstance(sub, rx.RScalarSubquery):
                            v = self._subquery_cache.get(id(sub))
                            sub_vals.append(
                                repr(None if v is None else v.value))
        key = (plans, shapes, leaf_sig, self.nparts, groups_mult,
               bucket_mult, expand_mult, tuple(sub_vals),
               tuple(str(d) for d in self.mesh.devices.flat))
        return key, dict_objs

    def _compile_and_run(self, graph, worker_stages, modes, leaves, top_id,
                         groups_mult, bucket_mult, expand_mult,
                         cache_key=None, dict_objs=None):
        if cache_key is None:
            cache_key, dict_objs = self._program_cache_key(
                worker_stages, leaves, groups_mult, bucket_mult,
                expand_mult)
        ident = tuple(id(d) for d in dict_objs)
        hit = _PROGRAM_CACHE.get((cache_key, ident))
        if hit is not None and all(s is d for s, d in
                                   zip(hit[0], dict_objs)):
            _, jitted, stage_out, n_exchanges, hlo = hit
            self.last_exchanges = n_exchanges
            self.last_hlo = hlo
            return self._run_program(jitted, leaves, stage_out, top_id)
        return self._compile_fresh(cache_key, ident, dict_objs,
                                   worker_stages, modes, leaves, top_id,
                                   groups_mult, bucket_mult, expand_mult)

    def _compile_fresh(self, cache_key, ident, dict_objs, worker_stages,
                       modes, leaves, top_id, groups_mult, bucket_mult,
                       expand_mult):
        P = self.nparts
        mesh = self.mesh
        self._expand_mult = expand_mult

        # ---- bind-time fragment compilation (host) --------------------
        stage_frags: Dict[int, _Frag] = {}   # pre-exchange fragment
        stage_out: Dict[int, _Frag] = {}     # post-exchange (consumable)
        exchanges: List[Tuple[int, str, object]] = []
        # consumed-edge metadata for _compile_agg's keyless-merge check
        self._stage_modes = modes
        self._stage_shuffle_keys = {s.stage_id: s.shuffle_keys
                                    for s in worker_stages}
        for stage in worker_stages:
            frag = self._compile_node(
                stage.plan, stage_out, leaves.get(stage.stage_id),
                stage.stage_id, groups_mult)
            stage_frags[stage.stage_id] = frag
            mode = modes.get(stage.stage_id)
            if mode == jg.InputMode.SHUFFLE:
                if stage.shuffle_keys is None:
                    raise MeshUnsupported("shuffle stage without keys")
                bucket_cap = bucket_capacity(
                    max(8, -(-frag.cap * 2 * bucket_mult // P)))
                ex = self._bind_shuffle(frag, stage.shuffle_keys, P,
                                        bucket_cap)
                exchanges.append((stage.stage_id, "shuffle", ex))
                stage_out[stage.stage_id] = dataclasses.replace(
                    frag, cap=P * bucket_cap)
            elif mode == jg.InputMode.BROADCAST:
                exchanges.append((stage.stage_id, "broadcast", None))
                stage_out[stage.stage_id] = dataclasses.replace(
                    frag, cap=P * frag.cap)
            else:  # FORWARD / MERGE / None
                stage_out[stage.stage_id] = frag

        # ---- assemble the single SPMD program -------------------------
        exchange_of = {sid: (kind, ex) for sid, kind, ex in exchanges}
        layout = _leaf_layout(leaves)
        rebuild = _make_rebuild(layout)
        n_flat = sum(len(hvs) + sum(hvs) + 1 for _, hvs in layout)

        def program(*flat):
            env: Dict = {("leaf", lid): v
                         for lid, v in rebuild(flat).items()}
            retry: List[jnp.ndarray] = []
            fatal: List[jnp.ndarray] = []
            for stage in worker_stages:
                cols, sel, r, f = stage_frags[stage.stage_id].fn(env)
                retry.extend(r)
                fatal.extend(f)
                kind_ex = exchange_of.get(stage.stage_id)
                if kind_ex is not None:
                    kind, ex = kind_ex
                    if kind == "shuffle":
                        cols, sel, over = ex(cols, sel)
                        retry.append(over)
                    else:  # broadcast
                        cols = [(jax.lax.all_gather(d, DATA_AXIS, tiled=True),
                                 None if v is None else
                                 jax.lax.all_gather(v, DATA_AXIS, tiled=True))
                                for d, v in cols]
                        sel = jax.lax.all_gather(sel, DATA_AXIS, tiled=True)
                env[stage.stage_id] = (cols, sel)
            out_cols, out_sel = env[top_id]
            retry_total = sum((jnp.asarray(r).astype(jnp.int32).sum()
                               for r in retry), start=jnp.int32(0))
            fatal_total = sum((jnp.asarray(f).astype(jnp.int32).sum()
                               for f in fatal), start=jnp.int32(0))
            flat_out = []
            for d, v in out_cols:
                flat_out.append(d[None])
                flat_out.append(jnp.ones_like(out_sel)[None] if v is None
                                else v[None])
            return (tuple(flat_out), out_sel[None], retry_total[None],
                    fatal_total[None])

        from jax.sharding import PartitionSpec as Pspec
        spec = Pspec(DATA_AXIS)
        wrapped = jax.shard_map(
            program, mesh=mesh,
            in_specs=tuple(spec for _ in range(n_flat)),
            out_specs=(spec, spec, spec, spec))
        jitted = jax.jit(wrapped)
        # persistent AOT cache (exec/pcache.py): the whole SPMD program
        # keys by the structural graph key + dictionary CONTENT + leaf
        # avals, so a restarted process loads the stored executable
        # instead of re-tracing the multi-stage shard_map program.
        # Memory-table sources (identity-keyed) make the key process-
        # local — those programs stay jit-only.
        jitted = self._maybe_persistent(wrapped, cache_key,
                                        dict_objs) or jitted
        self.last_exchanges = len(exchanges)
        _record_metric("mesh.exchange_count", len(exchanges))
        self.last_hlo = None
        if self.config.get("spark.sail.mesh.captureHlo") == "true":
            flat_probe = self._flatten_leaf_arrays(leaves)
            self.last_hlo = jax.jit(wrapped).lower(
                *flat_probe).as_text()
        _PROGRAM_CACHE[(cache_key, ident)] = (
            dict_objs, jitted, dict(stage_out), len(exchanges),
            self.last_hlo)
        while len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
        return self._run_program(jitted, leaves, stage_out, top_id)

    def _maybe_persistent(self, wrapped, cache_key, dict_objs):
        """Swap the jitted SPMD program for a persistent-cache-aware
        wrapper when every baked host object is content-digestable
        (dictionary arrays only — memory-table sources are identity-
        keyed and cannot name a cross-process entry)."""
        from ..config import truthy_value
        from ..exec import pcache
        if not pcache.enabled():
            return None
        session = self.config.get("spark.sail.compileCache.enabled")
        if session is not None and not truthy_value(session):
            return None
        if any(not isinstance(d, pa.Array) for d in dict_objs):
            return None
        try:
            return pcache.wrap(wrapped, ("mesh", cache_key), dict_objs,
                               fused=True, site="mesh")
        except Exception:  # noqa: BLE001 — cache trouble: plain jit
            return None

    def _run_program(self, jitted, leaves, stage_out, top_id):
        flat_in = self._flatten_leaf_arrays(leaves)
        flat_out, out_sel, retry_tot, fatal_tot = jitted(*flat_in)
        retry_tot, fatal_tot = jax.device_get(
            (np.asarray(retry_tot), np.asarray(fatal_tot)))
        if int(np.max(fatal_tot)) > 0:
            raise MeshUnsupported("fatal flag raised in mesh program")
        if int(np.max(retry_tot)) > 0:
            return None
        top = stage_out[top_id]
        cols = []
        for i in range(len(top.types)):
            cols.append((flat_out[2 * i], flat_out[2 * i + 1]))
        return cols, out_sel, top

    # ------------------------------------------------------------------
    # leaf preparation
    # ------------------------------------------------------------------
    def _prepare_leaf(self, scan: pn.ScanExec, graph: jg.JobGraph,
                      P: int) -> _LeafData:
        from ..exec.local import LocalExecutor, _positional

        if scan.format == "__driver__":
            table = graph.scan_tables[scan.table_name]
            hb = _positional(ai.from_arrow(table))
        else:
            hb = LocalExecutor(self.config)._exec_ScanExec(scan)
        dev = hb.device
        host = jax.device_get(
            {"sel": dev.sel,
             **{f"d{i}": dev.columns[_positional_name(i)].data
                for i in range(len(dev.columns))},
             **{f"v{i}": dev.columns[_positional_name(i)].validity
                for i in range(len(dev.columns))
                if dev.columns[_positional_name(i)].validity is not None}})
        sel = np.asarray(host["sel"])
        n = int(sel.sum())  # from_arrow keeps live rows as a prefix
        from ..exec.local import _scan_cap_key
        cap = bucket_capacity(max(8, -(-n // P)),
                              key=("mesh-leaf", _scan_cap_key(scan), P))
        types: List[dt.DataType] = []
        datas: List[np.ndarray] = []
        validities: List[Optional[np.ndarray]] = []
        for i in range(len(dev.columns)):
            col = dev.columns[_positional_name(i)]
            types.append(col.dtype)
            datas.append(partition_rows(np.asarray(host[f"d{i}"])[:n], P, cap))
            if col.validity is not None:
                validities.append(
                    partition_rows(np.asarray(host[f"v{i}"])[:n], P, cap))
            else:
                validities.append(None)
        psel = partition_rows(np.ones(n, dtype=bool), P, cap)
        dicts = {i: hb.dicts[_positional_name(i)]
                 for i in range(len(dev.columns))
                 if _positional_name(i) in hb.dicts}
        return _LeafData(datas, validities, psel, types, dicts, cap)

    def _place_leaf(self, ld: _LeafData) -> List:
        """Device placement for one leaf's buffers, memoized on the leaf:
        repeat program runs (capacity retries) reuse the uploaded arrays
        instead of paying the host→device transfer again."""
        if ld.placed is None:
            from jax.sharding import NamedSharding, PartitionSpec as Pspec
            sharding = NamedSharding(self.mesh, Pspec(DATA_AXIS))
            flat: List = []
            for d, v in zip(ld.datas, ld.validities):
                flat.append(jax.device_put(d, sharding))
                if v is not None:
                    flat.append(jax.device_put(v, sharding))
            flat.append(jax.device_put(ld.sel, sharding))
            ld.placed = flat
        return ld.placed

    def _flatten_leaf_arrays(self, leaves: Dict[int, _LeafData]) -> List:
        flat: List = []
        for lid in sorted(leaves):
            flat.extend(self._place_leaf(leaves[lid]))
        return flat

    # ------------------------------------------------------------------
    # fragment compilation
    # ------------------------------------------------------------------
    def _compile_node(self, node: pn.PlanNode, producers: Dict[int, _Frag],
                      leaf: Optional[_LeafData], stage_id: int,
                      groups_mult: int) -> _Frag:
        if isinstance(node, pn.ScanExec):
            if leaf is None:
                raise MeshUnsupported("scan without prepared leaf data")

            def fn(env, _lid=stage_id):
                cols, sel = env[("leaf", _lid)]
                return cols, sel, [], []

            return _Frag(fn, leaf.types, dict(leaf.dicts), leaf.cap)
        if isinstance(node, jg.StageInputExec):
            prod = producers.get(node.stage_id)
            if prod is None:
                raise MeshUnsupported("stage input before producer")

            def fn(env, _sid=node.stage_id):
                cols, sel = env[_sid]
                return cols, sel, [], []

            return _Frag(fn, prod.types, dict(prod.dicts), prod.cap)
        if isinstance(node, pn.FilterExec):
            return self._compile_filter(node, producers, leaf, stage_id,
                                        groups_mult)
        if isinstance(node, pn.ProjectExec):
            return self._compile_project(node, producers, leaf, stage_id,
                                         groups_mult)
        if isinstance(node, pn.AggregateExec):
            return self._compile_agg(node, producers, leaf, stage_id,
                                     groups_mult)
        if isinstance(node, pn.JoinExec):
            return self._compile_join(node, producers, leaf, stage_id,
                                      groups_mult)
        raise MeshUnsupported(f"mesh fragment op {type(node).__name__}")

    def _expr_compiler(self, frag: _Frag) -> ExprCompiler:
        return ExprCompiler(frag.types, frag.dicts, self._subquery_cache)

    def _compile_rex(self, comp: ExprCompiler, r: rx.Rex):
        try:
            return comp.compile(r)
        except HostFallback as e:
            raise MeshUnsupported(f"host-only expression: {e}") from e

    def _compile_filter(self, node, producers, leaf, stage_id, gm) -> _Frag:
        child = self._compile_node(node.input, producers, leaf, stage_id, gm)
        c = self._compile_rex(self._expr_compiler(child), node.condition)

        def fn(env):
            cols, sel, r, f = child.fn(env)
            data, validity = c.fn(cols)
            keep = data.astype(jnp.bool_)
            if validity is not None:
                keep = keep & validity
            return cols, sel & keep, r, f

        return _Frag(fn, child.types, child.dicts, child.cap)

    def _compile_project(self, node, producers, leaf, stage_id, gm) -> _Frag:
        from ..columnar.batch import physical_jnp_dtype

        child = self._compile_node(node.input, producers, leaf, stage_id, gm)
        comp = self._expr_compiler(child)
        compiled = [self._compile_rex(comp, e) for _, e in node.exprs]
        types = [rx.rex_type(e) for _, e in node.exprs]
        jdts = [physical_jnp_dtype(t) for t in types]
        dicts = {i: c.dictionary for i, c in enumerate(compiled)
                 if c.dictionary is not None}

        def fn(env):
            cols, sel, r, f = child.fn(env)
            out: Cols = []
            for c, jdt in zip(compiled, jdts):
                data, validity = c.fn(cols)
                if data.ndim == 0:
                    data = jnp.broadcast_to(data[None], (sel.shape[0],))
                if data.dtype != jnp.dtype(jdt):
                    data = data.astype(jdt)
                if validity is not None and validity.ndim == 0:
                    validity = jnp.broadcast_to(validity[None],
                                                (sel.shape[0],))
                out.append((data, validity))
            return out, sel, r, f

        return _Frag(fn, types, dicts, child.cap)

    def _compile_agg(self, node: pn.AggregateExec, producers, leaf,
                     stage_id, gm) -> _Frag:
        from ..exec.local import _dict_order_ranks

        if any(a.distinct or a.filter is not None or
               a.fn not in _MESH_AGGS for a in node.aggs):
            raise MeshUnsupported("non-mergeable aggregate in mesh stage")
        child = self._compile_node(node.input, producers, leaf, stage_id, gm)
        in_types = child.types
        max_groups = min(child.cap,
                         bucket_capacity(self._group_cap * gm))
        # A keyless FINAL aggregate consumes the builder's empty-key
        # shuffle (every partial row routed to partition 0): its single
        # global row is valid on device 0 only — the other devices merge
        # zero partials and must emit nothing (else the driver-side MERGE
        # sees one duplicate row per device).
        merge_to_zero = False
        if not node.group_indices:
            inp = node.input
            while isinstance(inp, (pn.FilterExec, pn.ProjectExec)):
                inp = inp.input
            if isinstance(inp, jg.StageInputExec) and \
                    getattr(self, "_stage_modes", {}).get(
                        inp.stage_id) == jg.InputMode.SHUFFLE and \
                    not getattr(self, "_stage_shuffle_keys", {}).get(
                        inp.stage_id):
                merge_to_zero = True
        # min/max over dictionary codes must order by VALUE: remap through
        # order-preserving ranks and back (same design as the local engine)
        luts = {}
        for j, a in enumerate(node.aggs):
            if a.fn in ("min", "max") and a.arg is not None and \
                    a.arg in child.dicts and len(child.dicts[a.arg]) > 1:
                ranks = _dict_order_ranks(child.dicts[a.arg])
                inv = np.empty_like(ranks)
                inv[ranks] = np.arange(len(ranks), dtype=ranks.dtype)
                luts[j] = (jnp.asarray(ranks), jnp.asarray(inv))

        def run_one(ctx, a: pn.AggSpec, arg: Optional[Column]) -> Column:
            if a.fn == "count":
                return aggk.agg_count(ctx, arg)
            if a.fn == "sum":
                return aggk.agg_sum(ctx, arg, a.out_dtype)
            if a.fn in ("min", "max"):
                return aggk.agg_min_max(ctx, arg, is_min=a.fn == "min")
            if a.fn in ("first", "last"):
                return aggk.agg_first_last(ctx, arg,
                                           is_first=a.fn == "first",
                                           ignore_nulls=a.ignore_nulls)
            return aggk.agg_bool(ctx, arg, is_any=a.fn == "bool_or")

        def fn(env):
            cols, sel, r, f = child.fn(env)
            key_cols = [Column(cols[i][0], cols[i][1], in_types[i])
                        for i in node.group_indices]
            ctx, skeys = aggk.group_rows(key_cols, sel, max_groups)
            gkeys = aggk.group_key_output(ctx, skeys)
            out: Cols = [(g.data, g.validity) for g in gkeys]
            for j, a in enumerate(node.aggs):
                arg = None if a.arg is None else \
                    Column(cols[a.arg][0], cols[a.arg][1], in_types[a.arg])
                lut = luts.get(j)
                if lut is not None:
                    ranks_lut, inv_lut = lut
                    codes = jnp.clip(arg.data, 0, ranks_lut.shape[0] - 1)
                    col = run_one(ctx, a, Column(ranks_lut[codes],
                                                 arg.validity, arg.dtype))
                    col = Column(inv_lut[jnp.clip(col.data, 0,
                                                  inv_lut.shape[0] - 1)],
                                 col.validity, col.dtype)
                else:
                    col = run_one(ctx, a, arg)
                out.append((col.data, col.validity))
            r = r + [aggk.group_overflow(ctx)]
            osel = aggk.group_sel(ctx)
            if merge_to_zero:
                osel = osel & (jax.lax.axis_index(DATA_AXIS) == 0)
            return out, osel, r, f

        nk = len(node.group_indices)
        types = [in_types[i] for i in node.group_indices] + \
            [a.out_dtype for a in node.aggs]
        dicts: Dict[int, pa.Array] = {}
        for j, gi in enumerate(node.group_indices):
            if gi in child.dicts:
                dicts[j] = child.dicts[gi]
        for j, a in enumerate(node.aggs):
            if a.arg is not None and a.fn in ("min", "max", "first", "last") \
                    and a.arg in child.dicts:
                dicts[nk + j] = child.dicts[a.arg]
        return _Frag(fn, types, dicts, max_groups)

    def _compile_join(self, node: pn.JoinExec, producers, leaf, stage_id,
                      gm) -> _Frag:
        jt = node.join_type
        if jt not in ("inner", "left", "semi", "anti") or not node.left_keys:
            raise MeshUnsupported(f"mesh join type {jt}")
        if node.null_aware:
            raise MeshUnsupported("null-aware join in mesh stage")
        left = self._compile_node(node.left, producers, leaf, stage_id, gm)
        right = self._compile_node(node.right, producers, leaf, stage_id, gm)
        lcomp = self._expr_compiler(left)
        rcomp = self._expr_compiler(right)
        pairs = []
        for lk, rk in zip(node.left_keys, node.right_keys):
            lc = self._compile_rex(lcomp, lk)
            rc = self._compile_rex(rcomp, rk)
            ktype = rx.rex_type(lk)
            luts = None
            if lc.dictionary is not None or rc.dictionary is not None:
                merged, ra, rb = ai.unify_dictionaries(lc.dictionary,
                                                       rc.dictionary)
                luts = (jnp.asarray(ra), jnp.asarray(rb))
                ktype = dt.IntegerType()
            pairs.append((lc, rc, ktype, luts))
        n_left = len(left.types)
        residual_c = None
        if node.residual is not None:
            comb = ExprCompiler(
                left.types + right.types,
                {**left.dicts,
                 **{n_left + i: d for i, d in right.dicts.items()}},
                self._subquery_cache)
            residual_c = self._compile_rex(comb, node.residual)

        # expand_mult == 1: unique-key (PK-FK) fast path, output capacity
        # = probe capacity; duplicate build keys raise a retry flag.
        # expand_mult > 1: many-to-many expansion at static capacity
        # probe_cap * expand_mult; a true output count past the capacity
        # raises a retry flag (next attempt scales further). Semi/anti
        # need only the match BIT so they are duplicate-safe — except
        # with a residual, where each candidate row must be tested.
        em = int(getattr(self, "_expand_mult", 1))
        has_res = residual_c is not None
        expand = em > 1 and (jt in ("inner", "left") or has_res)
        exp_cap = bucket_capacity(left.cap * em)
        n_right = len(right.types)
        if jt in ("semi", "anti") or not expand:
            out_cap = left.cap
        elif jt == "left" and has_res:
            # surviving expanded rows + unmatched-probe fallback rows
            out_cap = exp_cap + left.cap
        else:
            out_cap = exp_cap

        def fn(env):
            lcols, lsel, lr, lf = left.fn(env)
            rcols, rsel, rr, rf = right.fn(env)
            retry = lr + rr
            fatal = lf + rf
            lkeys, rkeys = [], []
            for lc, rc, ktype, luts in pairs:
                ld, lv = lc.fn(lcols)
                rd, rv = rc.fn(rcols)
                if luts is not None:
                    ld = luts[0][ld]
                    rd = luts[1][rd]
                lkeys.append(Column(ld, lv, ktype))
                rkeys.append(Column(rd, rv, ktype))
            bt = joink.build_side(rkeys, rsel)
            if not bt.exact:
                retry = retry + [joink.hash_ambiguous(bt, rkeys)]
            ranges = joink.probe_ranges(
                bt, lkeys, lsel,
                build_key_cols=rkeys if not bt.exact else None)
            probe = DeviceBatch(
                {_positional_name(i): Column(d, v, left.types[i])
                 for i, (d, v) in enumerate(lcols)}, lsel)
            payload = DeviceBatch(
                {_positional_name(n_left + i): Column(d, v, right.types[i])
                 for i, (d, v) in enumerate(rcols)}, rsel)
            all_names = [_positional_name(n_left + i)
                         for i in range(n_right)]
            probe_cols: Cols = [(d, v) for d, v in lcols]

            def res_mask(cols_full, base):
                data, validity = residual_c.fn(cols_full)
                keep = data.astype(jnp.bool_)
                if validity is not None:
                    keep = keep & validity
                return base & keep

            def batch_cols(b, ncols) -> Cols:
                return [(b.columns[_positional_name(i)].data,
                         b.columns[_positional_name(i)].validity)
                        for i in range(ncols)]

            if not expand:
                if jt in ("inner", "left") or has_res:
                    retry = retry + [joink.has_duplicate_build_keys(bt)]
                if not has_res:
                    names = all_names if jt not in ("semi", "anti") else []
                    out = joink.join_unique(bt, ranges, probe, payload, jt,
                                            names)
                    ncols = n_left if jt in ("semi", "anti") else \
                        n_left + n_right
                    return (batch_cols(out, ncols), out.sel, retry, fatal)
                # residual on the ≤1-match path: gather the candidate
                # build row for every probe row, then test it
                combined = joink.join_unique(bt, ranges, probe, payload,
                                             "left", all_names)
                cols_full = batch_cols(combined, n_left + n_right)
                m = res_mask(cols_full, ranges.cnt > 0)
                if jt == "inner":
                    return cols_full, combined.sel & m, retry, fatal
                if jt == "left":
                    cols = [(d, (m if v is None else v & m) if i >= n_left
                             else v)
                            for i, (d, v) in enumerate(cols_full)]
                    return cols, combined.sel, retry, fatal
                if jt == "semi":
                    return probe_cols, lsel & m, retry, fatal
                return probe_cols, lsel & ~m, retry, fatal  # anti

            # expanding path
            if not has_res:
                total = joink.join_output_count(ranges, lsel, jt)
                retry = retry + [total > out_cap]
                res = joink.join_expand(bt, ranges, probe, payload, jt,
                                        all_names, out_cap)
                return (batch_cols(res.batch, n_left + n_right),
                        res.batch.sel, retry, fatal)
            # residual: expand every candidate pair as inner, test, then
            # recover the outer/semi/anti semantics from the match bits
            total = joink.join_output_count(ranges, lsel, "inner")
            retry = retry + [total > exp_cap]
            res = joink.join_expand(bt, ranges, probe, payload, "inner",
                                    all_names, exp_cap)
            cols_full = batch_cols(res.batch, n_left + n_right)
            ok = res_mask(cols_full, res.batch.sel)
            if jt == "inner":
                return cols_full, ok, retry, fatal
            matched_probe = jnp.zeros(probe.capacity, dtype=jnp.bool_) \
                .at[res.probe_index].max(ok, mode="drop")
            if jt == "semi":
                return probe_cols, lsel & matched_probe, retry, fatal
            if jt == "anti":
                return probe_cols, lsel & ~matched_probe, retry, fatal
            # left: surviving expanded rows + unmatched probe rows with
            # null build columns (same shape as local._join_expand)
            unmatched = lsel & ~matched_probe
            cols: Cols = []
            for i in range(n_left):
                ed, ev = cols_full[i]
                pd_, pv = lcols[i]
                data = jnp.concatenate([ed, pd_])
                validity = None
                if ev is not None or pv is not None:
                    ev_ = ev if ev is not None else \
                        jnp.ones(exp_cap, dtype=jnp.bool_)
                    pv_ = pv if pv is not None else \
                        jnp.ones(probe.capacity, dtype=jnp.bool_)
                    validity = jnp.concatenate([ev_, pv_])
                cols.append((data, validity))
            for i in range(n_right):
                ed, ev = cols_full[n_left + i]
                ev_ = ev if ev is not None else \
                    jnp.ones(exp_cap, dtype=jnp.bool_)
                cols.append((
                    jnp.concatenate(
                        [ed, jnp.zeros(probe.capacity, dtype=ed.dtype)]),
                    jnp.concatenate(
                        [ev_, jnp.zeros(probe.capacity, dtype=jnp.bool_)])))
            sel = jnp.concatenate([ok, unmatched])
            return cols, sel, retry, fatal

        if jt in ("semi", "anti"):
            types, dicts = list(left.types), dict(left.dicts)
        else:
            types = list(left.types) + list(right.types)
            dicts = {**left.dicts,
                     **{n_left + i: d for i, d in right.dicts.items()}}
        return _Frag(fn, types, dicts, out_cap)

    # ------------------------------------------------------------------
    # exchanges
    # ------------------------------------------------------------------
    def _bind_shuffle(self, frag: _Frag, keys: Tuple[int, ...], P: int,
                      bucket_cap: int):
        # Dictionary-encoded keys must hash by VALUE, not code: the two
        # sides of a shuffle join carry independent per-leaf dictionaries,
        # so equal strings can have different codes. A bind-time LUT maps
        # each code to a deterministic hash of its string value — equal
        # values hash identically on every producer stage.
        key_types: List[dt.DataType] = []
        value_luts: Dict[int, jnp.ndarray] = {}
        for i in keys:
            if i in frag.dicts:
                value_luts[i] = jnp.asarray(
                    _dict_value_hashes(frag.dicts[i]))
                key_types.append(dt.LongType())
            else:
                key_types.append(frag.types[i])

        def exchange(cols: Cols, sel):
            # normalize NULL slots to 0 before hashing: the backing data of
            # an invalid slot is arbitrary (e.g. join_unique gathers from a
            # clipped build row), and equal keys — including NULL ≡ NULL —
            # must land on the same partition
            kd = []
            for i in keys:
                d, v = cols[i]
                lut = value_luts.get(i)
                if lut is not None:
                    d = lut[jnp.clip(d, 0, lut.shape[0] - 1)]
                if v is not None:
                    d = jnp.where(v, d, jnp.zeros_like(d))
                kd.append(d)
            if kd:
                pid = (hash64(kd, key_types)
                       % jnp.uint64(P)).astype(jnp.int32)
            else:
                # keyless shuffle (global aggregate): every partial row
                # merges on partition 0
                pid = jnp.zeros(sel.shape[0], dtype=jnp.int32)
            perm, valid, overflow = bucket_by_partition(pid, sel, P,
                                                        bucket_cap)

            def xchg(a):
                buf = a[perm].reshape(P, bucket_cap)
                return jax.lax.all_to_all(buf, DATA_AXIS, 0, 0,
                                          tiled=True).reshape(-1)

            out: Cols = []
            for d, v in cols:
                out.append((xchg(d), None if v is None else xchg(v)))
            out_sel = jax.lax.all_to_all(
                valid.reshape(P, bucket_cap), DATA_AXIS, 0, 0,
                tiled=True).reshape(-1)
            return out, out_sel, overflow

        return exchange

    # ------------------------------------------------------------------
    # output assembly
    # ------------------------------------------------------------------
    def _assemble(self, out_cols, out_sel, frag: _Frag) -> pa.Table:
        """One batched device fetch, then build arrow directly from the
        host buffers (no device re-upload)."""
        host = jax.device_get({"sel": out_sel,
                               **{f"d{i}": d for i, (d, v)
                                  in enumerate(out_cols)},
                               **{f"v{i}": v for i, (d, v)
                                  in enumerate(out_cols)}})
        idx = np.nonzero(np.asarray(host["sel"]).reshape(-1))[0]
        arrays = []
        names = []
        for i, t in enumerate(frag.types):
            data = np.asarray(host[f"d{i}"]).reshape(-1)[idx]
            validity = np.asarray(host[f"v{i}"]).reshape(-1)[idx]
            arrays.append(ai.column_values_to_arrow(
                data, validity, t, frag.dicts.get(i)))
            names.append(_positional_name(i))
        return pa.Table.from_arrays(arrays, names=names)


def _dict_value_hashes(dictionary: pa.Array) -> np.ndarray:
    """Deterministic int64 hash per dictionary VALUE (side-independent —
    both producers of a shuffle join compute the same hash for the same
    string regardless of code assignment)."""
    import pandas as pd

    vals = dictionary.cast(pa.string()).to_pylist()
    arr = np.array(["\0NULL" if v is None else v for v in vals],
                   dtype=object)
    return pd.util.hash_array(arr).view(np.int64)


def _bottom_scan(plan: pn.PlanNode) -> Optional[pn.ScanExec]:
    """The unique ScanExec leaf of a stage plan (joins reference upstream
    stages via StageInputExec, so ≤1 scan per stage in supported shapes)."""
    scans = [n for n in pn.walk_plan(plan) if isinstance(n, pn.ScanExec)]
    if len(scans) > 1:
        raise MeshUnsupported("multiple scans in one stage")
    return scans[0] if scans else None


def _reattach_scans(plan: pn.PlanNode, scan_tables) -> pn.PlanNode:
    import dataclasses as dc

    def repl(p):
        if isinstance(p, pn.ScanExec) and p.format == "__driver__":
            return dc.replace(p, source=scan_tables[p.table_name],
                              format="memory", table_name="")
        if isinstance(p, pn.JoinExec):
            return dc.replace(p, left=repl(p.left), right=repl(p.right))
        if isinstance(p, pn.UnionExec):
            return dc.replace(p, inputs=tuple(repl(c) for c in p.inputs))
        if hasattr(p, "input") and p.input is not None:
            return dc.replace(p, input=repl(p.input))
        return p

    return repl(plan)
