"""Distributed relational operators over a device mesh.

Reference role: the distributed execution of stages — partial aggregation,
hash-shuffled final aggregation, broadcast joins — that the reference runs
as tasks exchanging Arrow Flight streams (SURVEY.md §2.5). Here a whole
multi-stage pipeline is ONE jitted SPMD program: per-shard relational
kernels (the same sort/segment primitives as the local engine) composed
with `all_to_all` / `all_gather` collectives inside `jax.shard_map`.

Used by the multichip dry-run and (in later rounds) the distributed
executor's stage compiler.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..columnar.batch import Column
from ..ops import aggregate as aggk
from ..ops import join as joink
from ..ops.hash import hash64
from ..spec import data_type as dt
from .mesh import DATA_AXIS
from .exchange import bucket_by_partition


def partition_arrays(arrays: Sequence[np.ndarray], n: int, num_partitions: int,
                     cap_per_part: Optional[int] = None):
    """Host-side: split n rows contiguously into [P, cap] (shared layout
    helper: parallel.mesh.partition_rows)."""
    from .mesh import partition_rows
    per = -(-n // num_partitions)
    cap = cap_per_part or max(8, per)
    out = [partition_rows(a, num_partitions, cap) for a in arrays]
    sel = partition_rows(np.ones(n, dtype=bool), num_partitions, cap)
    return out, sel


def _local_partial_agg(key_data, key_type: dt.DataType, vals, sel, max_groups):
    """Per-shard partial aggregation: returns (group key, partial sums,
    partial counts, group sel)."""
    kcol = Column(key_data, None, key_type)
    ctx, skeys = aggk.group_rows([kcol], sel, max_groups)
    gkey = aggk.group_key_output(ctx, skeys)[0]
    sums = [aggk.agg_sum(ctx, Column(v, None, dt.DoubleType()), dt.DoubleType()).data
            for v in vals]
    cnt = aggk.agg_count(ctx, None).data
    return (gkey.data, sums, cnt, aggk.group_sel(ctx),
            aggk.group_overflow(ctx))


def make_distributed_agg(mesh: Mesh, key_type: dt.DataType, n_vals: int,
                         local_groups: int, bucket_cap: int):
    """Two-phase distributed GROUP BY SUM/COUNT as one SPMD program:

      local partial agg → hash all_to_all of partial rows → final agg

    Inputs (sharded [P, n]): key, vals..., sel.
    Outputs (sharded [P, local_groups]): key, sums..., count, group_sel,
    plus a per-shard overflow count [P] covering BOTH loss modes: partial
    groups dropped because a target bucket exceeded ``bucket_cap``, and
    group-table truncation when a shard saw more than ``local_groups``
    distinct keys (locally or after the exchange). Callers MUST host-check
    ``overflow.max() == 0`` and re-run with larger capacities otherwise
    (same detect-and-rerun contract as ``make_shuffle``).
    """
    nparts = mesh.shape[DATA_AXIS]
    spec = P(DATA_AXIS)

    def step(key, vals, sel):
        k, v, s = key[0], [x[0] for x in vals], sel[0]
        gkey, sums, cnt, gsel, l_over = _local_partial_agg(
            k, key_type, v, s, local_groups)
        # shuffle partial groups by key hash so equal keys co-locate
        pid = (hash64([gkey], [key_type]) % jnp.uint64(nparts)).astype(jnp.int32)
        arrays = [gkey] + sums + [cnt]
        perm, valid, overflow = bucket_by_partition(pid, gsel, nparts,
                                                    bucket_cap)
        bufs = [a[perm].reshape(nparts, bucket_cap) for a in arrays]
        valid2 = valid.reshape(nparts, bucket_cap)
        exch = [jax.lax.all_to_all(b, DATA_AXIS, 0, 0, tiled=True) for b in bufs]
        vex = jax.lax.all_to_all(valid2, DATA_AXIS, 0, 0, tiled=True)
        rkey = exch[0].reshape(-1)
        rsums = [e.reshape(-1) for e in exch[1: 1 + n_vals]]
        rcnt = exch[1 + n_vals].reshape(-1)
        rsel = vex.reshape(-1)
        # final aggregation of partials
        kcol = Column(rkey, None, key_type)
        ctx, skeys = aggk.group_rows([kcol], rsel, local_groups)
        fkey = aggk.group_key_output(ctx, skeys)[0].data
        fsums = [aggk.agg_sum(ctx, Column(x, None, dt.DoubleType()),
                              dt.DoubleType()).data for x in rsums]
        fcnt = aggk.agg_sum(ctx, Column(rcnt, None, dt.LongType()),
                            dt.LongType()).data
        fsel = aggk.group_sel(ctx)
        total_overflow = (overflow.astype(jnp.int32)
                          + l_over.astype(jnp.int32)
                          + aggk.group_overflow(ctx).astype(jnp.int32))
        return (fkey[None], tuple(f[None] for f in fsums), fcnt[None],
                fsel[None], total_overflow[None])

    wrapped = jax.shard_map(step, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=(spec, spec, spec, spec, spec))
    return jax.jit(wrapped)


def make_broadcast_join(mesh: Mesh, probe_key_type: dt.DataType,
                        n_payload: int):
    """Broadcast hash join as one SPMD program: the (small) build side is
    all_gathered to every shard; each shard sort-probes locally.

    Inputs: probe key + payload cols [P, n] sharded, probe sel;
            build key + payload [P, m] sharded, build sel.
    Output: probe cols ++ gathered build payload (validity = match), and an
            output sel — all sharded, inner-join semantics, unique build.
    """
    spec = P(DATA_AXIS)

    def step(pkey, ppayload, psel, bkey, bpayload, bsel):
        pk, ps = pkey[0], psel[0]
        bk = jax.lax.all_gather(bkey[0], DATA_AXIS, tiled=True)
        bs = jax.lax.all_gather(bsel[0], DATA_AXIS, tiled=True)
        bp = [jax.lax.all_gather(x[0], DATA_AXIS, tiled=True) for x in bpayload]
        bt = joink.build_side([Column(bk, None, probe_key_type)], bs)
        ranges = joink.probe_ranges(bt, [Column(pk, None, probe_key_type)], ps)
        matched = ranges.cnt > 0
        cap = bk.shape[0]
        bidx = bt.perm[jnp.clip(ranges.lo, 0, cap - 1)]
        out_payload = tuple(x[bidx][None] for x in bp)
        out_sel = (ps & matched)[None]
        return (pk[None], tuple(x[0][None] for x in ppayload), out_payload,
                out_sel)

    wrapped = jax.shard_map(step, mesh=mesh,
                            in_specs=(spec, spec, spec, spec, spec, spec),
                            out_specs=(spec, spec, spec, spec))
    return jax.jit(wrapped)
