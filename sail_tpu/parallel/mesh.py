"""Device mesh management.

Reference role: the cluster topology side of sail-execution's worker pool
(SURVEY.md §2.5/§2.8) — but TPU-native: parallelism is expressed as a
jax.sharding.Mesh over chips, with XLA collectives riding ICI. The default
layout is a 1-D "data" axis (partition parallelism — every relational
operator is data-parallel over row partitions); a second "expert"/pipeline
axis slots in for multi-stage scheduling in later rounds.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (DATA_AXIS,))


def partitioned_spec() -> P:
    """Rows sharded over the data axis (leading partition dim)."""
    return P(DATA_AXIS)


def replicated_spec() -> P:
    return P()


def shard_batch_arrays(mesh: Mesh, arrays):
    """Place [P, ...] arrays with the partition dim sharded over the mesh."""
    sharding = NamedSharding(mesh, partitioned_spec())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), arrays)


def partition_rows(arr: np.ndarray, n_parts: int, cap: int) -> np.ndarray:
    """Split [n] rows contiguously into [n_parts, cap] (zero-padded) —
    the host-side layout contract for sharded batches (live rows are a
    per-partition prefix)."""
    n = arr.shape[0]
    per = -(-n // n_parts) if n else 0
    out = np.zeros((n_parts, cap), dtype=arr.dtype)
    for p in range(n_parts):
        chunk = arr[p * per: (p + 1) * per]
        out[p, : len(chunk)] = chunk
    return out
