"""MCP (Model Context Protocol) server for Spark SQL over stdio.

Reference role: crates/sail-cli/src/spark/mcp_server.rs:39-86 +
src/python/spark_mcp_server.py — the reference launches a fastmcp server
over an in-process Spark Connect server. No MCP SDK ships in this image,
so this implements the protocol surface directly: JSON-RPC 2.0 over
stdin/stdout with ``initialize``, ``tools/list`` and ``tools/call``
(2024-11-05 protocol revision). The tool surface mirrors the reference's:
query execution, view registration per format, and catalog inspection.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional

PROTOCOL_VERSION = "2024-11-05"


def _tool(name: str, description: str, props: Dict[str, dict],
          required: List[str]) -> dict:
    return {
        "name": name,
        "description": description,
        "inputSchema": {"type": "object", "properties": props,
                        "required": required},
    }


TOOLS = [
    _tool("execute_query",
          "Execute a Spark SQL query and return the result rows as JSON.",
          {"query": {"type": "string", "description": "The SQL text."},
           "limit": {"type": "integer",
                     "description": "Maximum rows to return (default 100)."}},
          ["query"]),
    _tool("list_views", "List registered views/tables.", {}, []),
    _tool("describe_view",
          "Describe a view's columns (name, type, nullable).",
          {"name": {"type": "string"}}, ["name"]),
    _tool("create_parquet_view",
          "Register a Parquet file or directory as a named view.",
          {"name": {"type": "string"}, "path": {"type": "string"}},
          ["name", "path"]),
    _tool("create_csv_view",
          "Register a CSV file as a named view.",
          {"name": {"type": "string"}, "path": {"type": "string"},
           "header": {"type": "boolean"}},
          ["name", "path"]),
    _tool("create_json_view",
          "Register a JSON-lines file as a named view.",
          {"name": {"type": "string"}, "path": {"type": "string"}},
          ["name", "path"]),
    _tool("list_local_directories",
          "List directories under a local filesystem path "
          "(non-recursive).",
          {"path": {"type": "string"}}, ["path"]),
]


class McpSparkServer:
    """Protocol handler; transport-agnostic (serve() drives stdio)."""

    def __init__(self, spark=None):
        self._spark = spark

    @property
    def spark(self):
        if self._spark is None:
            from . import SparkSession
            self._spark = SparkSession.builder.getOrCreate()
        return self._spark

    # -- JSON-RPC dispatch ----------------------------------------------
    def handle(self, msg: dict) -> Optional[dict]:
        method = msg.get("method", "")
        msg_id = msg.get("id")
        try:
            if method == "initialize":
                result = {
                    "protocolVersion": PROTOCOL_VERSION,
                    "capabilities": {"tools": {}},
                    "serverInfo": {"name": "sail-tpu MCP server for "
                                           "Spark SQL",
                                   "version": "0.1"},
                }
            elif method in ("notifications/initialized", "initialized"):
                return None  # notification: no response
            elif method == "tools/list":
                result = {"tools": TOOLS}
            elif method == "tools/call":
                result = self._call_tool(msg.get("params", {}))
            elif method == "ping":
                result = {}
            else:
                return self._error(msg_id, -32601,
                                   f"method not found: {method}")
        except Exception as e:  # noqa: BLE001 — surfaced as a tool error
            return self._error(msg_id, -32000, f"{type(e).__name__}: {e}")
        if msg_id is None:
            return None
        return {"jsonrpc": "2.0", "id": msg_id, "result": result}

    @staticmethod
    def _error(msg_id, code, message) -> Optional[dict]:
        if msg_id is None:
            return None
        return {"jsonrpc": "2.0", "id": msg_id,
                "error": {"code": code, "message": message}}

    # -- tools -----------------------------------------------------------
    def _call_tool(self, params: dict) -> dict:
        name = params.get("name", "")
        args = params.get("arguments") or {}
        fn = getattr(self, f"_tool_{name}", None)
        if fn is None:
            raise ValueError(f"unknown tool {name!r}")
        try:
            text = fn(**args)
            return {"content": [{"type": "text", "text": text}],
                    "isError": False}
        except Exception as e:  # noqa: BLE001 — tool errors are results
            return {"content": [{"type": "text",
                                 "text": f"{type(e).__name__}: {e}"}],
                    "isError": True}

    def _tool_execute_query(self, query: str, limit: int = 100) -> str:
        table = self.spark.sql(query).toArrow()
        if table.num_rows > limit:
            table = table.slice(0, limit)
        return json.dumps(table.to_pylist(), default=str)

    def _tool_list_views(self) -> str:
        cm = self.spark.catalog_manager
        names = sorted(cm.temp_views)
        try:
            names += [e.name[-1] for e in cm.list_tables()
                      if e.name and e.name[-1] not in names
                      and e.view_plan is None]
        except Exception:  # noqa: BLE001 — provider without listing
            pass
        return json.dumps(sorted(set(names)))

    def _tool_describe_view(self, name: str) -> str:
        df = self.spark.sql(f"SELECT * FROM {name} LIMIT 0")
        out = [{"name": f.name, "dataType": f.data_type.simple_string(),
                "nullable": f.nullable}
               for f in df.schema.fields]
        return json.dumps(out)

    def _register(self, name: str, path: str, fmt: str, **options) -> str:
        reader = self.spark.read.format(fmt)
        for k, v in options.items():
            reader = reader.option(k, str(v).lower())
        reader.load(path).createOrReplaceTempView(name)
        return json.dumps({"view": name, "path": path, "format": fmt})

    def _tool_create_parquet_view(self, name: str, path: str) -> str:
        return self._register(name, path, "parquet")

    def _tool_create_csv_view(self, name: str, path: str,
                              header: bool = True) -> str:
        return self._register(name, path, "csv", header=header)

    def _tool_create_json_view(self, name: str, path: str) -> str:
        return self._register(name, path, "json")

    @staticmethod
    def _tool_list_local_directories(path: str) -> str:
        out = sorted(d for d in os.listdir(path)
                     if os.path.isdir(os.path.join(path, d)))
        return json.dumps(out)

    # -- stdio transport -------------------------------------------------
    def serve(self, stdin=None, stdout=None):
        """Line-delimited JSON-RPC over stdio (the MCP stdio transport)."""
        stdin = stdin if stdin is not None else sys.stdin
        stdout = stdout if stdout is not None else sys.stdout
        for line in stdin:
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            resp = self.handle(msg)
            if resp is not None:
                stdout.write(json.dumps(resp) + "\n")
                stdout.flush()


def main(argv=None):
    McpSparkServer().serve()


if __name__ == "__main__":
    main()
