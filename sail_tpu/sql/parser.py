"""Spark SQL parser: tokens → spec IR.

From-scratch recursive-descent parser with Pratt operator precedence for the
Spark SQL dialect (reference role: crates/sail-sql-parser +
crates/sail-sql-analyzer; unlike the reference we lower straight to the spec
IR — Python dataclasses make a separate AST layer redundant).

Coverage (grown per round): full SELECT queries (CTEs, set ops, all join
types, lateral/exists/in subqueries, group by / rollup / cube / grouping
sets, having, qualify-less windows, order/limit/offset/distribute/sort by),
literals (typed, intervals, numerics with suffixes), CASE/CAST/EXTRACT/
SUBSTRING/TRIM/POSITION special forms, lambdas, and the common commands
(CREATE/DROP/INSERT/SHOW/DESCRIBE/USE/SET/EXPLAIN/CACHE/VALUES/
DELETE/UPDATE/MERGE).
"""

from __future__ import annotations

import datetime
import decimal
import functools
import re
from typing import List, Optional, Tuple

from ..spec import expression as ex
from ..spec import plan as pl
from ..spec import data_type as dt
from ..spec.literal import Literal as LV
from .lexer import SqlSyntaxError, Token, tokenize

# Words that terminate an expression / cannot start a primary expression.
_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "INTERSECT", "EXCEPT", "MINUS", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "CROSS", "ON", "USING", "AS", "WHEN", "THEN", "ELSE", "END", "AND", "OR",
    "NOT", "BETWEEN", "IN", "LIKE", "RLIKE", "ILIKE", "IS", "CASE", "BY",
    "ASC", "DESC", "NULLS", "FIRST", "LAST", "SELECT", "DISTINCT", "ALL",
    "SEMI", "ANTI", "LATERAL", "NATURAL", "DIV", "THEN", "OVER",
    "PARTITION", "ROWS", "RANGE", "PRECEDING", "FOLLOWING", "CURRENT",
    "UNBOUNDED", "ESCAPE", "SORT", "DISTRIBUTE", "CLUSTER", "SET", "MATCHED",
}

_JOIN_TYPES = {
    "INNER": "inner", "LEFT": "left", "RIGHT": "right", "FULL": "full",
    "CROSS": "cross", "SEMI": "semi", "ANTI": "anti",
}


class Parser:
    def __init__(self, text: str):
        self.text = text
        self.tokens = tokenize(text)
        self.i = 0

    # ------------------------------------------------------------------
    # token helpers
    # ------------------------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        j = min(self.i + ahead, len(self.tokens) - 1)
        return self.tokens[j]

    def advance(self) -> Token:
        t = self.tokens[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def error(self, message: str) -> SqlSyntaxError:
        return SqlSyntaxError(message, self.text, self.peek().pos)

    def tok_desc(self, ahead: int = 0) -> str:
        t = self.peek(ahead)
        return "end of input" if t.kind == "eof" else repr(t.value)

    def at_kw(self, *words: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "ident" and t.upper in words

    def accept_kw(self, *words: str) -> Optional[str]:
        if self.at_kw(*words):
            return self.advance().upper
        return None

    def expect_kw(self, *words: str) -> str:
        got = self.accept_kw(*words)
        if got is None:
            raise self.error(f"expected {' or '.join(words)}, got {self.tok_desc()}")
        return got

    def at_op(self, *ops: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "op" and t.value in ops

    def accept_op(self, *ops: str) -> Optional[str]:
        if self.at_op(*ops):
            return self.advance().value
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise self.error(f"expected {op!r}, got {self.tok_desc()}")

    def parse_identifier(self) -> str:
        t = self.peek()
        if t.kind in ("ident", "quoted_ident"):
            self.advance()
            return t.value
        raise self.error(f"expected identifier, got {t.value!r}")

    def parse_qualified_name(self) -> Tuple[str, ...]:
        parts = [self.parse_identifier()]
        while self.at_op(".") and self.peek(1).kind in ("ident", "quoted_ident"):
            self.advance()
            parts.append(self.parse_identifier())
        return tuple(parts)

    def parse_ident_list(self) -> Tuple[str, ...]:
        """'(' ident (',' ident)* ')'  — the '(' must already be consumed or
        pending; callers use paren_ident_list for the common parenthesized
        form."""
        names = [self.parse_identifier()]
        while self.accept_op(","):
            names.append(self.parse_identifier())
        return tuple(names)

    def paren_ident_list(self) -> Tuple[str, ...]:
        self.expect_op("(")
        names = self.parse_ident_list()
        self.expect_op(")")
        return names

    def parse_optional_alias(self) -> Optional[str]:
        """Consume 'AS ident' or a bare non-reserved identifier, if present."""
        if self.accept_kw("AS"):
            return self.parse_identifier()
        t = self.peek()
        if t.kind in ("ident", "quoted_ident") and t.upper not in _RESERVED_STOP:
            return self.parse_identifier()
        return None

    # ------------------------------------------------------------------
    # entry points
    # ------------------------------------------------------------------
    def parse_statements(self) -> List[pl.Plan]:
        out = []
        while self.peek().kind != "eof":
            out.append(self.parse_statement())
            while self.accept_op(";"):
                pass
        return out

    def parse_statement(self) -> pl.Plan:
        if self.at_kw("SELECT", "WITH", "VALUES") or self.at_op("("):
            return self.parse_query()
        if self.at_kw("CREATE"):
            return self.parse_create()
        if self.at_kw("DROP"):
            return self.parse_drop()
        if self.at_kw("INSERT"):
            return self.parse_insert()
        if self.at_kw("SHOW"):
            return self.parse_show()
        if self.at_kw("DESCRIBE", "DESC"):
            return self.parse_describe()
        if self.at_kw("USE"):
            self.advance()
            self.accept_kw("DATABASE", "SCHEMA", "NAMESPACE")
            return pl.UseDatabase(self.parse_qualified_name())
        if self.at_kw("SET"):
            return self.parse_set()
        if self.at_kw("RESET"):
            self.advance()
            name = None
            if self.peek().kind == "ident":
                name = ".".join(self.parse_qualified_name())
            return pl.ResetVariable(name)
        if self.at_kw("EXPLAIN"):
            self.advance()
            mode = "simple"
            m = self.accept_kw("EXTENDED", "CODEGEN", "COST", "FORMATTED", "ANALYZE")
            if m:
                mode = m.lower()
            fmt = "text"
            if self.accept_kw("FORMAT"):
                fmt = self.expect_kw("JSON", "TEXT").lower()
            return pl.Explain(self.parse_statement(), mode, fmt)
        if self.at_kw("CACHE"):
            self.advance()
            if self.accept_kw("MATERIALIZED"):
                self.accept_kw("VIEW")
                name = self.parse_qualified_name()
                self.expect_kw("AS")
                return pl.CacheMaterialized(name, self.parse_query())
            lazy = self.accept_kw("LAZY") is not None
            self.expect_kw("TABLE")
            name = self.parse_qualified_name()
            query = None
            if self.accept_kw("AS"):
                query = self.parse_query()
            return pl.CacheTable(name, query, lazy)
        if self.at_kw("UNCACHE"):
            self.advance()
            if self.accept_kw("MATERIALIZED"):
                self.accept_kw("VIEW")
                if_exists = self._accept_if_exists()
                return pl.UncacheMaterialized(
                    self.parse_qualified_name(), if_exists)
            self.expect_kw("TABLE")
            if_exists = self._accept_if_exists()
            return pl.UncacheTable(self.parse_qualified_name(), if_exists)
        if self.at_kw("DELETE"):
            return self.parse_delete()
        if self.at_kw("UPDATE"):
            return self.parse_update()
        if self.at_kw("MERGE"):
            return self.parse_merge()
        if self.at_kw("TRUNCATE"):
            self.advance()
            self.accept_kw("TABLE")
            return pl.TruncateTable(self.parse_qualified_name())
        if self.at_kw("REFRESH"):
            self.advance()
            self.accept_kw("TABLE")
            return pl.RefreshTable(self.parse_qualified_name())
        if self.at_kw("CLEAR"):
            self.advance()
            self.expect_kw("CACHE")
            return pl.ClearCache()
        if self.at_kw("ANALYZE"):
            self.advance()
            self.expect_kw("TABLE")
            name = self.parse_qualified_name()
            self.expect_kw("COMPUTE")
            self.expect_kw("STATISTICS")
            cols: Tuple[str, ...] = ()
            noscan = False
            if self.accept_kw("NOSCAN"):
                noscan = True
            elif self.accept_kw("FOR"):
                if self.accept_kw("ALL"):
                    self.expect_kw("COLUMNS")
                    cols = ("*",)
                else:
                    self.expect_kw("COLUMNS")
                    cols = tuple(self.parse_ident_list())
            return pl.AnalyzeTable(name, cols, noscan)
        if self.at_kw("ALTER"):
            return self.parse_alter()
        if self.at_kw("COMMENT"):
            self.advance()
            self.expect_kw("ON")
            kind = "database" if self.accept_kw(
                "DATABASE", "SCHEMA", "NAMESPACE") else \
                (self.expect_kw("TABLE") and "table")
            name = self.parse_qualified_name()
            self.expect_kw("IS")
            if self.accept_kw("NULL"):
                comment = None
            else:
                comment = self.advance().value
            return pl.CommentOn(kind, name, comment)
        if self.at_kw("TABLE"):
            self.advance()
            return pl.ReadNamedTable(self.parse_qualified_name())
        raise self.error(f"unsupported statement start {self.tok_desc()}")

    def parse_alter(self) -> pl.Plan:
        self.expect_kw("ALTER")
        self.expect_kw("TABLE")
        name = self.parse_qualified_name()
        if self.accept_kw("RENAME"):
            if self.accept_kw("TO"):
                return pl.AlterTable(name, "rename",
                                     new_name=self.parse_qualified_name())
            self.expect_kw("COLUMN")
            old = self.parse_identifier()
            self.expect_kw("TO")
            new = self.parse_identifier()
            return pl.AlterTable(name, "rename_column",
                                 column_names=(old, new))
        if self.accept_kw("ADD"):
            self.expect_kw("COLUMNS", "COLUMN")
            cols = []
            wrapped = self.accept_op("(")
            while True:
                cname = self.parse_identifier()
                ctype = self.parse_data_type()
                self.accept_kw("COMMENT") and self.advance()
                cols.append((cname, ctype))
                if not self.accept_op(","):
                    break
            if wrapped:
                self.expect_op(")")
            return pl.AlterTable(name, "add_columns", columns=tuple(cols))
        if self.accept_kw("DROP"):
            self.expect_kw("COLUMNS", "COLUMN")
            wrapped = self.accept_op("(")
            names = tuple(self.parse_ident_list())
            if wrapped:
                self.expect_op(")")
            return pl.AlterTable(name, "drop_columns", column_names=names)
        if self.accept_kw("SET"):
            self.expect_kw("TBLPROPERTIES")
            self.expect_op("(")
            props = []
            while True:
                k = self.advance().value
                self.expect_op("=")
                v = self.advance().value
                props.append((str(k), str(v)))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return pl.AlterTable(name, "set_properties",
                                 properties=tuple(props))
        if self.accept_kw("UNSET"):
            self.expect_kw("TBLPROPERTIES")
            self.expect_op("(")
            keys = []
            while True:
                keys.append((str(self.advance().value), None))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return pl.AlterTable(name, "unset_properties",
                                 properties=tuple(keys))
        raise self.error("unsupported ALTER TABLE action")

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def parse_query(self) -> pl.QueryPlan:
        ctes: Tuple[Tuple[str, pl.QueryPlan], ...] = ()
        recursive = False
        if self.accept_kw("WITH"):
            recursive = self.accept_kw("RECURSIVE") is not None
            items = []
            while True:
                name = self.parse_identifier()
                cols: Tuple[str, ...] = ()
                if self.at_op("("):
                    cols = self.paren_ident_list()
                self.expect_kw("AS")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                if cols:
                    q = pl.SubqueryAlias(q, name, columns=cols)
                items.append((name, q))
                if not self.accept_op(","):
                    break
            ctes = tuple(items)
        body = self.parse_set_expr()
        body = self.parse_query_tail(body)
        if ctes:
            body = pl.WithCtes(body, ctes, recursive)
        return body

    def parse_query_tail(self, body: pl.QueryPlan) -> pl.QueryPlan:
        """ORDER BY / SORT BY / DISTRIBUTE BY / CLUSTER BY / LIMIT / OFFSET."""
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            body = pl.Sort(body, tuple(self.parse_sort_items()), is_global=True)
        elif self.accept_kw("CLUSTER"):
            self.expect_kw("BY")
            exprs = self.parse_expr_list()
            body = pl.Repartition(body, None, tuple(exprs))
            body = pl.Sort(body, tuple(ex.SortOrder(e) for e in exprs), is_global=False)
        else:
            if self.accept_kw("DISTRIBUTE"):
                self.expect_kw("BY")
                body = pl.Repartition(body, None, tuple(self.parse_expr_list()))
            if self.accept_kw("SORT"):
                self.expect_kw("BY")
                body = pl.Sort(body, tuple(self.parse_sort_items()), is_global=False)
        offset = 0
        limit = None
        if self.accept_kw("OFFSET"):
            offset = self._parse_int_value()
            self.accept_kw("ROWS", "ROW")
        if self.accept_kw("LIMIT"):
            if not self.accept_kw("ALL"):
                limit = self._parse_int_value()
        if self.accept_kw("OFFSET"):
            offset = self._parse_int_value()
            self.accept_kw("ROWS", "ROW")
        if limit is not None or offset:
            body = pl.Limit(body, limit, offset)
        return body

    def _parse_int_value(self) -> int:
        t = self.peek()
        if t.kind == "number":
            self.advance()
            return int(re.sub(r"[LlSsYy]$", "", t.value))
        raise self.error("expected integer")

    def parse_sort_items(self) -> List[ex.SortOrder]:
        items = []
        while True:
            e = self.parse_expr()
            asc = True
            if self.accept_kw("DESC"):
                asc = False
            elif self.accept_kw("ASC"):
                asc = True
            nulls_first = None
            if self.accept_kw("NULLS"):
                nulls_first = self.expect_kw("FIRST", "LAST") == "FIRST"
            items.append(ex.SortOrder(e, asc, nulls_first))
            if not self.accept_op(","):
                break
        return items

    def parse_set_expr(self) -> pl.QueryPlan:
        left = self.parse_set_term()
        while True:
            if self.at_kw("UNION", "EXCEPT", "MINUS"):
                op_word = self.advance().upper
                op = "union" if op_word == "UNION" else "except"
                all_ = self.accept_kw("ALL") is not None
                if not all_:
                    self.accept_kw("DISTINCT")
                right = self.parse_set_term()
                left = pl.SetOperation(left, right, op, all_)
            else:
                break
        return left

    def parse_set_term(self) -> pl.QueryPlan:
        left = self.parse_set_primary()
        while self.at_kw("INTERSECT"):
            self.advance()
            all_ = self.accept_kw("ALL") is not None
            if not all_:
                self.accept_kw("DISTINCT")
            right = self.parse_set_primary()
            left = pl.SetOperation(left, right, "intersect", all_)
        return left

    def parse_set_primary(self) -> pl.QueryPlan:
        if self.accept_op("("):
            q = self.parse_query()
            self.expect_op(")")
            return q
        if self.at_kw("VALUES"):
            return self.parse_values()
        if self.at_kw("SELECT"):
            return self.parse_select()
        raise self.error(f"expected SELECT, VALUES or (, got {self.tok_desc()}")

    def parse_values(self) -> pl.QueryPlan:
        self.expect_kw("VALUES")
        rows = []
        while True:
            if self.accept_op("("):
                row = tuple(self.parse_expr_list())
                self.expect_op(")")
            else:
                row = (self.parse_expr(),)
            rows.append(row)
            if not self.accept_op(","):
                break
        q: pl.QueryPlan = pl.Values(tuple(rows))
        alias = self.parse_optional_alias()
        if alias is not None:
            cols: Tuple[str, ...] = ()
            if self.at_op("("):
                cols = self.paren_ident_list()
            q = pl.SubqueryAlias(q, alias, columns=cols)
        return q

    def parse_select(self) -> pl.QueryPlan:
        self.expect_kw("SELECT")
        distinct = False
        if self.accept_kw("DISTINCT"):
            distinct = True
        else:
            self.accept_kw("ALL")
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())
        source: pl.QueryPlan = pl.OneRow()
        if self.accept_kw("FROM"):
            source = self.parse_from()
        if self.accept_kw("WHERE"):
            source = pl.Filter(source, self.parse_expr())
        group: Tuple[ex.Expr, ...] = ()
        grouping_sets = None
        rollup = cube = False
        has_group = False
        if self.accept_kw("GROUP"):
            has_group = True
            self.expect_kw("BY")
            if self.accept_kw("ROLLUP"):
                rollup = True
                self.expect_op("(")
                group = tuple(self.parse_expr_list())
                self.expect_op(")")
            elif self.accept_kw("CUBE"):
                cube = True
                self.expect_op("(")
                group = tuple(self.parse_expr_list())
                self.expect_op(")")
            elif self.accept_kw("GROUPING"):
                self.expect_kw("SETS")
                self.expect_op("(")
                sets = []
                while True:
                    self.expect_op("(")
                    if self.at_op(")"):
                        sets.append(())
                    else:
                        sets.append(tuple(self.parse_expr_list()))
                    self.expect_op(")")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                grouping_sets = tuple(sets)
            else:
                group = tuple(self.parse_expr_list())
                if self.accept_kw("WITH"):
                    w = self.expect_kw("ROLLUP", "CUBE")
                    rollup = w == "ROLLUP"
                    cube = w == "CUBE"
        having = None
        if self.accept_kw("HAVING"):
            having = self.parse_expr()
        if has_group or having is not None:
            plan: pl.QueryPlan = pl.Aggregate(
                source, group, tuple(items), having, grouping_sets, rollup, cube)
        else:
            plan = pl.Project(source, tuple(items))
        if distinct:
            plan = pl.Deduplicate(plan)
        return plan

    def parse_select_item(self) -> ex.Expr:
        if self.at_op("*"):
            self.advance()
            return ex.Star()
        # qualifier.* star
        save = self.i
        if self.peek().kind in ("ident", "quoted_ident"):
            parts = []
            try:
                parts = list(self.parse_qualified_name())
            except SqlSyntaxError:
                self.i = save
                parts = []
            if parts and self.at_op(".") and self.at_op("*", ahead=1):
                self.advance()
                self.advance()
                return ex.Star(tuple(parts))
            self.i = save
        e = self.parse_expr()
        if self.accept_kw("AS"):
            if self.at_op("("):
                return ex.Alias(e, self.paren_ident_list())
            return ex.Alias(e, (self.parse_identifier(),))
        t = self.peek()
        if t.kind in ("ident", "quoted_ident") and t.upper not in _RESERVED_STOP:
            return ex.Alias(e, (self.parse_identifier(),))
        return e

    # ------------------------------------------------------------------
    # FROM clause
    # ------------------------------------------------------------------
    def parse_from(self) -> pl.QueryPlan:
        left = self.parse_joined_relation()
        while self.accept_op(","):
            right = self.parse_joined_relation()
            left = pl.Join(left, right, "cross")
        while self.at_kw("LATERAL") and self.at_kw("VIEW", ahead=1):
            left = self.parse_lateral_view(left)
        return left

    def parse_lateral_view(self, input_plan: pl.QueryPlan) -> pl.QueryPlan:
        self.expect_kw("LATERAL")
        self.expect_kw("VIEW")
        outer = self.accept_kw("OUTER") is not None
        gen = self.parse_expr()
        table_alias = None
        if self.peek().kind in ("ident", "quoted_ident") and not self.at_kw("AS"):
            table_alias = self.parse_identifier()
        col_aliases: Tuple[str, ...] = ()
        if self.accept_kw("AS"):
            col_aliases = self.parse_ident_list()
        return pl.LateralView(input_plan, gen, table_alias, col_aliases, outer)

    def parse_joined_relation(self) -> pl.QueryPlan:
        left = self.parse_relation_primary()
        while True:
            natural = False
            save = self.i
            if self.accept_kw("NATURAL"):
                natural = True
            jt = None
            if self.at_kw("JOIN"):
                jt = "inner"
                self.advance()
            elif self.at_kw("INNER", "LEFT", "RIGHT", "FULL", "CROSS", "SEMI", "ANTI"):
                word = self.advance().upper
                jt = _JOIN_TYPES[word]
                if word in ("LEFT", "RIGHT", "FULL"):
                    self.accept_kw("OUTER")
                    if word == "LEFT" and self.at_kw("SEMI"):
                        self.advance()
                        jt = "semi"
                    elif word == "LEFT" and self.at_kw("ANTI"):
                        self.advance()
                        jt = "anti"
                self.expect_kw("JOIN")
            else:
                self.i = save
                break
            lateral = self.accept_kw("LATERAL") is not None
            right = self.parse_relation_primary()
            condition = None
            using: Tuple[str, ...] = ()
            if self.accept_kw("ON"):
                condition = self.parse_expr()
            elif self.accept_kw("USING"):
                using = self.paren_ident_list()
            left = pl.Join(left, right, jt, condition, using, lateral,
                           is_natural=(natural and condition is None and not using))
        return left

    def parse_relation_primary(self) -> pl.QueryPlan:
        if self.accept_op("("):
            inner = self.parse_query() if self.at_kw("SELECT", "WITH", "VALUES") \
                else self.parse_from()
            self.expect_op(")")
            return self._maybe_alias(inner)
        if self.at_kw("VALUES"):
            return self.parse_values()
        if self.at_kw("LATERAL"):
            self.advance()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return self._maybe_alias(pl.SubqueryAlias(q, "__lateral__"))
        # table-valued function: name(args)
        if self.peek().kind == "ident" and self.at_op("(", ahead=1):
            name = self.parse_identifier()
            self.expect_op("(")
            args = [] if self.at_op(")") else self.parse_expr_list()
            self.expect_op(")")
            return self._maybe_alias(pl.ReadUdtf(name.lower(), tuple(args)))
        name = self.parse_qualified_name()
        temporal = None
        options: Tuple[Tuple[str, str], ...] = ()
        # time travel: FOR (VERSION|TIMESTAMP) AS OF <value>
        if self.at_kw("FOR") and self.at_kw("VERSION", "TIMESTAMP", ahead=1):
            self.advance()
            kind = self.advance().upper
            self.expect_kw("AS")
            self.expect_kw("OF")
            v = self.advance().value
            temporal = f"{kind.lower()}:{v}"
        elif self.at_kw("VERSION", "TIMESTAMP") and self.at_kw("AS", ahead=1):
            kind = self.advance().upper
            self.expect_kw("AS")
            self.expect_kw("OF")
            v = self.advance().value
            temporal = f"{kind.lower()}:{v}"
        return self._maybe_alias(pl.ReadNamedTable(name, temporal, options))

    def _maybe_alias(self, plan: pl.QueryPlan) -> pl.QueryPlan:
        alias = self.parse_optional_alias()
        if alias is None:
            return plan
        cols: Tuple[str, ...] = ()
        if self.at_op("("):
            cols = self.paren_ident_list()
        return pl.SubqueryAlias(plan, alias, columns=cols)

    # ------------------------------------------------------------------
    # expressions (Pratt)
    # ------------------------------------------------------------------
    def parse_expr_list(self) -> List[ex.Expr]:
        out = [self.parse_expr()]
        while self.accept_op(","):
            out.append(self.parse_expr())
        return out

    def parse_expr(self) -> ex.Expr:
        return self.parse_or()

    def parse_or(self) -> ex.Expr:
        left = self.parse_and()
        while self.accept_kw("OR"):
            left = ex.Function("or", (left, self.parse_and()))
        return left

    def parse_and(self) -> ex.Expr:
        left = self.parse_not()
        while self.accept_kw("AND"):
            left = ex.Function("and", (left, self.parse_not()))
        return left

    def parse_not(self) -> ex.Expr:
        if self.accept_kw("NOT") or self.accept_op("!"):
            return ex.Function("not", (self.parse_not(),))
        return self.parse_predicate()

    def parse_predicate(self) -> ex.Expr:
        left = self.parse_bitor()
        while True:
            if self.at_op("=", "==", "<>", "!=", "<", ">", "<=", ">=", "<=>"):
                op = self.advance().value
                right = self.parse_bitor()
                name = {"=": "==", "==": "==", "<>": "!=", "!=": "!=", "<": "<",
                        ">": ">", "<=": "<=", ">=": ">=", "<=>": "<=>"}[op]
                left = ex.Function(name, (left, right))
                continue
            negated = False
            save = self.i
            if self.accept_kw("NOT"):
                negated = True
            if self.accept_kw("BETWEEN"):
                low = self.parse_bitor()
                self.expect_kw("AND")
                high = self.parse_bitor()
                left = ex.Between(left, low, high, negated)
                continue
            if self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT", "WITH"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ex.InSubquery(left, q, negated)
                else:
                    vals = tuple(self.parse_expr_list())
                    self.expect_op(")")
                    left = ex.InList(left, vals, negated)
                continue
            if self.at_kw("LIKE", "ILIKE", "RLIKE", "REGEXP"):
                word = self.advance().upper
                pattern = self.parse_bitor()
                if word in ("LIKE", "ILIKE"):
                    ci = word == "ILIKE"
                    e: ex.Expr = ex.Like(left, pattern, negated,
                                         case_insensitive=ci)
                    if self.accept_kw("ESCAPE"):
                        esc = self.parse_primary()
                        esc_s = esc.value.value if isinstance(esc, ex.Literal) else None
                        e = ex.Like(left, pattern, negated,
                                    case_insensitive=ci, escape=esc_s)
                else:
                    e = ex.Function("rlike", (left, pattern))
                    if negated:
                        e = ex.Function("not", (e,))
                left = e
                continue
            if negated:
                self.i = save
                break
            if self.accept_kw("IS"):
                is_not = self.accept_kw("NOT") is not None
                if self.accept_kw("NULL"):
                    e = ex.Function("isnull", (left,))
                elif self.accept_kw("TRUE"):
                    e = ex.Function("==", (left, ex.lit(True)))
                elif self.accept_kw("FALSE"):
                    e = ex.Function("==", (left, ex.lit(False)))
                elif self.accept_kw("DISTINCT"):
                    self.expect_kw("FROM")
                    right = self.parse_bitor()
                    e = ex.Function("not", (ex.Function("<=>", (left, right)),))
                elif self.accept_kw("UNKNOWN"):
                    e = ex.Function("isnull", (left,))
                else:
                    raise self.error("expected NULL/TRUE/FALSE/DISTINCT after IS")
                if is_not:
                    e = ex.Function("not", (e,))
                left = e
                continue
            break
        return left

    def parse_bitor(self) -> ex.Expr:
        left = self.parse_bitxor()
        while self.at_op("|") and not self.at_op("||"):
            self.advance()
            left = ex.Function("|", (left, self.parse_bitxor()))
        return left

    def parse_bitxor(self) -> ex.Expr:
        left = self.parse_bitand()
        while self.accept_op("^"):
            left = ex.Function("^", (left, self.parse_bitand()))
        return left

    def parse_bitand(self) -> ex.Expr:
        left = self.parse_shift()
        while self.accept_op("&"):
            left = ex.Function("&", (left, self.parse_shift()))
        return left

    def parse_shift(self) -> ex.Expr:
        left = self.parse_concat()
        while self.at_op("<<", ">>", ">>>"):
            op = self.advance().value
            fn = {"<<": "shiftleft", ">>": "shiftright",
                  ">>>": "shiftrightunsigned"}[op]
            left = ex.Function(fn, (left, self.parse_concat()))
        return left

    def parse_concat(self) -> ex.Expr:
        left = self.parse_add()
        while self.accept_op("||"):
            left = ex.Function("concat", (left, self.parse_add()))
        return left

    def parse_add(self) -> ex.Expr:
        left = self.parse_mul()
        while self.at_op("+", "-"):
            op = self.advance().value
            left = ex.Function(op, (left, self.parse_mul()))
        return left

    def parse_mul(self) -> ex.Expr:
        left = self.parse_unary()
        while True:
            if self.at_op("*", "/", "%"):
                op = self.advance().value
                left = ex.Function(op, (left, self.parse_unary()))
            elif self.at_kw("DIV"):
                self.advance()
                left = ex.Function("div", (left, self.parse_unary()))
            else:
                break
        return left

    def parse_unary(self) -> ex.Expr:
        if self.accept_op("-"):
            child = self.parse_unary()
            if isinstance(child, ex.Literal) and child.value.data_type.is_numeric \
                    and not isinstance(child.value.value, bool):
                v = child.value
                neg = -v.value
                # re-narrow: '2147483648' lexes as bigint but -2147483648
                # is an int literal (Spark parses the sign with the digits)
                if isinstance(v.data_type, dt.LongType) and \
                        isinstance(neg, int) and -(2**31) <= neg < 2**31:
                    return ex.Literal(LV.int32(neg))
                return ex.Literal(LV(v.data_type, neg))
            return ex.Function("negative", (child,))
        if self.accept_op("+"):
            return self.parse_unary()
        if self.accept_op("~"):
            return ex.Function("~", (self.parse_unary(),))
        return self.parse_postfix()

    def parse_postfix(self) -> ex.Expr:
        e = self.parse_primary()
        while True:
            if self.at_op(".") and self.peek(1).kind in ("ident", "quoted_ident"):
                self.advance()
                field = self.parse_identifier()
                if isinstance(e, ex.Attribute):
                    e = ex.Attribute(e.name + (field,), e.plan_id)
                else:
                    e = ex.Function("getfield", (e, ex.lit(field)))
                continue
            if self.accept_op("["):
                idx = self.parse_expr()
                self.expect_op("]")
                e = ex.Function("getitem", (e, idx))
                continue
            if self.accept_op("::"):
                e = ex.Cast(e, self.parse_data_type())
                continue
            if self.at_kw("COLLATE"):
                self.advance()
                e = ex.Function("collate", (e, ex.lit(self.parse_identifier())))
                continue
            break
        return e

    # ------------------------------------------------------------------
    # primary expressions
    # ------------------------------------------------------------------
    def parse_primary(self) -> ex.Expr:
        t = self.peek()
        if t.kind == "number":
            self.advance()
            try:
                return _number_literal(t.value)
            except (ValueError, ArithmeticError) as e:
                self.i -= 1
                raise self.error(str(e)) from e
        if t.kind == "string":
            # adjacent string literals concatenate
            parts = [self.advance().value]
            while self.peek().kind == "string":
                parts.append(self.advance().value)
            return ex.lit("".join(parts))
        if t.kind == "op":
            if self.accept_op("("):
                if self.at_kw("SELECT", "WITH"):
                    q = self.parse_query()
                    self.expect_op(")")
                    return ex.ScalarSubquery(q)
                items = self.parse_expr_list()
                self.expect_op(")")
                if len(items) == 1:
                    # lambda with parenthesized params: (x, y) -> body
                    if self.at_op("->"):
                        return self._parse_lambda_from(items)
                    return items[0]
                if self.at_op("->"):
                    return self._parse_lambda_from(items)
                return ex.Function("struct", tuple(items))
            if self.accept_op("*"):
                return ex.Star()
            if self.accept_op("?"):
                return ex.Attribute(("?",))
        if t.kind == "quoted_ident":
            return ex.Attribute(self.parse_qualified_name())
        if t.kind != "ident":
            raise self.error(f"unexpected token {self.tok_desc()}")
        word = t.upper
        # keyword-led constructs
        if word == "CASE":
            return self.parse_case()
        if word in ("CAST", "TRY_CAST"):
            self.advance()
            self.expect_op("(")
            child = self.parse_expr()
            self.expect_kw("AS")
            target = self.parse_data_type()
            self.expect_op(")")
            return ex.Cast(child, target, try_=(word == "TRY_CAST"))
        if word == "EXISTS" and self.at_op("(", ahead=1) and (
                self.at_kw("SELECT", "VALUES", "WITH", "FROM", ahead=2)
                or self.at_op("(", ahead=2)):
            self.advance()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return ex.Exists(q)
        if word == "EXTRACT" and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            field = self.parse_identifier()
            self.expect_kw("FROM")
            child = self.parse_expr()
            self.expect_op(")")
            return ex.Extract(field.lower(), child)
        if word in ("SUBSTRING", "SUBSTR") and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            child = self.parse_expr()
            if self.accept_kw("FROM"):
                start = self.parse_expr()
                length = None
                if self.accept_kw("FOR"):
                    length = self.parse_expr()
                self.expect_op(")")
                args = (child, start) if length is None else (child, start, length)
                return ex.Function("substring", args)
            self.expect_op(",")
            args2 = [child] + self.parse_expr_list()
            self.expect_op(")")
            return ex.Function("substring", tuple(args2))
        if word == "OVERLAY" and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            child = self.parse_bitor()
            if self.accept_kw("PLACING"):
                repl = self.parse_bitor()
                self.expect_kw("FROM")
                pos = self.parse_bitor()
                length = None
                if self.accept_kw("FOR"):
                    length = self.parse_bitor()
                self.expect_op(")")
                args = (child, repl, pos) if length is None else \
                    (child, repl, pos, length)
                return ex.Function("overlay", args)
            self.expect_op(",")
            rest0 = self.parse_expr_list()
            self.expect_op(")")
            return ex.Function("overlay", tuple([child] + rest0))
        if word == "POSITION" and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            sub = self.parse_bitor()
            if self.accept_kw("IN"):
                s = self.parse_bitor()
                self.expect_op(")")
                return ex.Function("position", (sub, s))
            self.expect_op(",")
            rest = self.parse_expr_list()
            self.expect_op(")")
            return ex.Function("position", tuple([sub] + rest))
        if word == "TRIM" and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            side = self.accept_kw("BOTH", "LEADING", "TRAILING")
            chars = None
            if not self.at_kw("FROM"):
                chars = self.parse_expr()
            if self.accept_kw("FROM"):
                src = self.parse_expr()
            else:
                src, chars = chars, None
            self.expect_op(")")
            fn = {"LEADING": "ltrim", "TRAILING": "rtrim", "BOTH": "trim",
                  None: "trim"}[side]
            args3 = (src,) if chars is None else (src, chars)
            return ex.Function(fn, args3)
        if word == "INTERVAL":
            return self.parse_interval()
        if word in ("DATE", "TIMESTAMP", "TIMESTAMP_NTZ", "TIME") and self.peek(1).kind == "string":
            self.advance()
            s = self.advance().value
            if word == "DATE":
                return ex.Literal(LV.date(datetime.date.fromisoformat(s.strip())))
            if word == "TIME":
                h, m, sec = (s.strip().split(":") + ["0", "0"])[:3]
                micros = int(round((float(sec) % 60) * 1_000_000))
                v_t = datetime.time(int(h), int(m), micros // 1_000_000,
                                    micros % 1_000_000)
                return ex.Literal(LV(dt.TimeType(), v_t))
            tz = "UTC" if word == "TIMESTAMP" else None
            v = datetime.datetime.fromisoformat(s.strip())
            return ex.Literal(LV.timestamp(v, tz))
        if word == "X" and self.peek(1).kind == "string":
            self.advance()
            hexs = self.advance().value.strip()
            if len(hexs) % 2:
                hexs = "0" + hexs
            return ex.Literal(LV(dt.BinaryType(), bytes.fromhex(hexs)))
        if word in ("TRUE", "FALSE"):
            self.advance()
            return ex.lit(word == "TRUE")
        if word == "NULL":
            self.advance()
            return ex.Literal(LV.null())
        if word in ("CURRENT_DATE", "CURRENT_TIMESTAMP", "CURRENT_USER", "CURRENT_CATALOG",
                    "CURRENT_SCHEMA", "CURRENT_DATABASE", "NOW",
                    "CURRENT_TIME") and not self.at_op("(", ahead=1):
            self.advance()
            return ex.Function(word.lower())
        if word in ("ARRAY", "MAP", "STRUCT") and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            args4 = [] if self.at_op(")") else self.parse_expr_list()
            self.expect_op(")")
            return ex.Function(word.lower(), tuple(args4))
        if word in ("FIRST", "LAST", "ANY_VALUE") and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            child = self.parse_expr()
            ignore_nulls = None
            if self.accept_op(","):
                flag = self.parse_expr()
                if isinstance(flag, ex.Literal):
                    ignore_nulls = bool(flag.value.value)
            if self.accept_kw("IGNORE"):
                self.expect_kw("NULLS")
                ignore_nulls = True
            elif self.accept_kw("RESPECT"):
                self.expect_kw("NULLS")
                ignore_nulls = False
            self.expect_op(")")
            f = ex.Function(word.lower(), (child,), ignore_nulls=ignore_nulls)
            return self._maybe_window(f)
        if word == "POSITION" and self.at_op("(", ahead=1):
            # POSITION(sub IN str) special form (plain calls also accepted)
            mark = self.i
            self.advance()
            self.expect_op("(")
            sub = self.parse_expr()
            if self.accept_kw("IN"):
                s = self.parse_expr()
                self.expect_op(")")
                return ex.Function("locate", (sub, s))
            self.i = mark
        # LIKE-family names in call position are functions, not predicates
        if word in ("LIKE", "ILIKE") and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            argsl = self.parse_expr_list()
            self.expect_op(")")
            esc = None
            if len(argsl) > 2 and isinstance(argsl[2], ex.Literal):
                esc = argsl[2].value.value
            return ex.Like(argsl[0], argsl[1], case_insensitive=(word == "ILIKE"),
                           escape=esc)
        if word == "RLIKE" and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            argsr = self.parse_expr_list()
            self.expect_op(")")
            return ex.Function("rlike", tuple(argsr))
        # function call or column reference; LEFT/RIGHT are join keywords
        # only after a relation — in expression position they're functions
        if self.at_op("(", ahead=1) and (word not in _RESERVED_STOP or
                                         word in ("LEFT", "RIGHT")):
            name = self.parse_identifier()
            return self.parse_function_call(name)
        # lambda: ident -> expr
        if self.at_op("->", ahead=1):
            name = self.parse_identifier()
            self.advance()
            body = self.parse_expr()
            return ex.LambdaFunction(body, (name,))
        if word in _RESERVED_STOP and word not in (
                "FIRST", "LAST", "CURRENT", "LEFT", "RIGHT") \
                and not self.at_op(".", ahead=1):
            raise self.error(f"unexpected keyword {t.value!r}")
        name_parts = self.parse_qualified_name()
        return ex.Attribute(name_parts)

    def _parse_lambda_from(self, items: List[ex.Expr]) -> ex.Expr:
        names = []
        for it in items:
            if isinstance(it, ex.Attribute) and len(it.name) == 1:
                names.append(it.name[0])
            else:
                raise self.error("invalid lambda parameter list")
        self.expect_op("->")
        body = self.parse_expr()
        return ex.LambdaFunction(body, tuple(names))

    _FN_ALIASES = {"std": "stddev", "random": "rand"}

    def parse_function_call(self, name: str) -> ex.Expr:
        name = self._FN_ALIASES.get(name.lower(), name)
        self.expect_op("(")
        distinct = False
        if self.accept_kw("DISTINCT"):
            distinct = True
        else:
            self.accept_kw("ALL")
        if self.at_op("*"):
            self.advance()
            args: Tuple[ex.Expr, ...] = ()
            if name.lower() == "count":
                args = (ex.Star(),)
            self.expect_op(")")
            f = ex.Function(name.lower(), args, distinct)
            return self._maybe_window(self._maybe_filter(f))
        args = () if self.at_op(")") else tuple(self.parse_call_args())
        ignore_nulls = None
        if self.accept_kw("IGNORE"):
            self.expect_kw("NULLS")
            ignore_nulls = True
        elif self.accept_kw("RESPECT"):
            self.expect_kw("NULLS")
            ignore_nulls = False
        self.expect_op(")")
        if self.at_kw("WITHIN") and self.at_op("(", ahead=2):
            return self._parse_within_group(name.lower(), args, distinct)
        if name.lower() == "collation" and len(args) == 1:
            a = args[0]
            if isinstance(a, ex.Function) and a.name == "collate" \
                    and len(a.args) == 2 and isinstance(a.args[1], ex.Literal):
                return ex.lit(
                    "SYSTEM.BUILTIN." + str(a.args[1].value.value).upper())
            return ex.lit("SYSTEM.BUILTIN.UTF8_BINARY")
        f = ex.Function(name.lower(), args, distinct, ignore_nulls=ignore_nulls)
        return self._maybe_window(self._maybe_filter(f))

    def parse_call_args(self) -> List[ex.Expr]:
        """Function-call arguments; named arguments (name => expr) are
        accepted and passed positionally (Spark resolves them by name; the
        corpus uses declaration order)."""
        out = []
        while True:
            if self.peek().kind == "ident" and self.at_op("=>", ahead=1):
                self.advance()
                self.advance()
            out.append(self.parse_expr())
            if not self.accept_op(","):
                break
        return out

    def _parse_within_group(self, name: str, args, distinct) -> ex.Expr:
        """fn(args) WITHIN GROUP (ORDER BY items) — ordered-set aggregates
        (listagg / string_agg / mode / percentile_cont / percentile_disc)."""
        self.expect_kw("WITHIN")
        self.expect_kw("GROUP")
        self.expect_op("(")
        self.expect_kw("ORDER")
        self.expect_kw("BY")
        items = self.parse_sort_items()
        self.expect_op(")")
        order = items[0]
        desc = ex.lit(not order.ascending)
        if name == "percentile_cont":
            p = args[0] if args else ex.lit(0.5)
            if not order.ascending:
                # valid for the continuous (interpolating) percentile only
                p = ex.Function("-", (ex.lit(1.0), p))
            f = ex.Function(name, (order.child, p))
        elif name == "percentile_disc":
            p = args[0] if args else ex.lit(0.5)
            f = ex.Function(name, (order.child, p, desc))
        elif name == "mode":
            f = ex.Function("__mode_ordered", (order.child, desc))
        elif name in ("listagg", "string_agg"):
            delim = args[1] if len(args) > 1 else ex.lit(None)
            f = ex.Function("__listagg_ordered",
                            (args[0], delim, order.child, desc), distinct)
        else:
            f = ex.Function(name, args, distinct)
        return self._maybe_window(self._maybe_filter(f))

    def _maybe_filter(self, f: ex.Function) -> ex.Function:
        if self.at_kw("FILTER") and self.at_op("(", ahead=1):
            self.advance()
            self.expect_op("(")
            self.expect_kw("WHERE")
            cond = self.parse_expr()
            self.expect_op(")")
            return ex.Function(f.name, f.args, f.is_distinct, cond, f.ignore_nulls)
        return f

    def _maybe_window(self, f: ex.Expr) -> ex.Expr:
        if not self.at_kw("OVER"):
            return f
        self.advance()
        self.expect_op("(")
        partition: Tuple[ex.Expr, ...] = ()
        order: Tuple[ex.SortOrder, ...] = ()
        frame = None
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition = tuple(self.parse_expr_list())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            order = tuple(self.parse_sort_items())
        if self.at_kw("ROWS", "RANGE"):
            frame_type = self.advance().upper.lower()
            lower, upper = self._parse_frame_bounds()
            frame = ex.WindowFrame(frame_type, lower, upper)
        self.expect_op(")")
        return ex.Window(f, partition, order, frame)

    def _parse_frame_bounds(self):
        def bound() -> Optional[int]:
            if self.accept_kw("UNBOUNDED"):
                self.expect_kw("PRECEDING", "FOLLOWING")
                return None
            if self.accept_kw("CURRENT"):
                self.expect_kw("ROW")
                return 0
            v = self._parse_int_value()
            w = self.expect_kw("PRECEDING", "FOLLOWING")
            return -v if w == "PRECEDING" else v

        if self.accept_kw("BETWEEN"):
            lo = bound()
            self.expect_kw("AND")
            hi = bound()
            return lo, hi
        lo = bound()
        return lo, 0

    def parse_case(self) -> ex.Expr:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expr()
        branches = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            val = self.parse_expr()
            if operand is not None:
                cond = ex.Function("==", (operand, cond))
            branches.append((cond, val))
        else_value = None
        if self.accept_kw("ELSE"):
            else_value = self.parse_expr()
        self.expect_kw("END")
        return ex.CaseWhen(tuple(branches), else_value)

    _INTERVAL_UNITS = {
        "YEAR": 12, "YEARS": 12, "MONTH": 1, "MONTHS": 1,
        "WEEK": 7 * 86_400_000_000, "WEEKS": 7 * 86_400_000_000,
        "DAY": 86_400_000_000, "DAYS": 86_400_000_000,
        "HOUR": 3_600_000_000, "HOURS": 3_600_000_000,
        "MINUTE": 60_000_000, "MINUTES": 60_000_000,
        "SECOND": 1_000_000, "SECONDS": 1_000_000,
        "MILLISECOND": 1_000, "MILLISECONDS": 1_000,
        "MICROSECOND": 1, "MICROSECONDS": 1,
    }

    def parse_interval(self) -> ex.Expr:
        self.expect_kw("INTERVAL")
        start = self.i
        try:
            return self._parse_interval_body()
        except (ValueError, ArithmeticError, IndexError, KeyError) as e:
            self.i = start
            raise self.error(f"invalid interval literal: {e}") from e

    def _parse_interval_body(self) -> ex.Expr:
        total_months = 0
        total_us = 0
        any_month = any_time = False
        while True:
            t = self.peek()
            sign = 1
            if t.kind == "op" and t.value in ("-", "+") \
                    and self.peek(1).kind == "string":
                sign = -1 if t.value == "-" else 1
                self.advance()
                t = self.peek()
            if t.kind == "string":
                raw = self.advance().value.strip()
                if self.at_kw(*self._INTERVAL_UNITS):
                    unit = self.advance().upper
                    if self.at_kw("TO"):
                        self.advance()
                        unit2 = self.advance().upper
                        m, us, im, it = _parse_interval_range(raw, unit, unit2)
                    else:
                        value = decimal.Decimal(raw)
                        m, us, im, it = _apply_unit(value, unit)
                else:
                    m, us, im, it = _parse_interval_string(raw)
                total_months += sign * m
                total_us += sign * us
                any_month |= im
                any_time |= it
            elif t.kind == "number":
                value = decimal.Decimal(self.advance().value)
                unit = self.expect_kw(*self._INTERVAL_UNITS)
                m, us, im, it = _apply_unit(value, unit)
                total_months += m
                total_us += us
                any_month |= im
                any_time |= it
            else:
                break
            if not (self.peek().kind in ("string", "number")
                    or self.at_kw(*self._INTERVAL_UNITS)):
                break
        if any_month and any_time:
            return ex.Literal(LV(dt.CalendarIntervalType(), (total_months, total_us)))
        if any_month:
            return ex.Literal(LV(dt.YearMonthIntervalType(), total_months))
        return ex.Literal(LV.interval_microseconds(total_us))

    # ------------------------------------------------------------------
    # data types
    # ------------------------------------------------------------------
    def parse_data_type(self) -> dt.DataType:
        name = self.parse_identifier().upper()
        if name in ("INT", "INTEGER"):
            return dt.IntegerType()
        if name in ("BIGINT", "LONG"):
            return dt.LongType()
        if name in ("SMALLINT", "SHORT"):
            return dt.ShortType()
        if name in ("TINYINT", "BYTE"):
            return dt.ByteType()
        if name in ("DOUBLE",):
            return dt.DoubleType()
        if name in ("FLOAT", "REAL"):
            return dt.FloatType()
        if name in ("STRING", "TEXT"):
            return dt.StringType()
        if name in ("VARCHAR", "CHAR", "CHARACTER"):
            if self.accept_op("("):
                self._parse_int_value()
                self.expect_op(")")
            return dt.StringType()
        if name in ("BOOLEAN", "BOOL"):
            return dt.BooleanType()
        if name in ("BINARY", "BYTES"):
            return dt.BinaryType()
        if name == "DATE":
            return dt.DateType()
        if name == "TIME":
            return dt.TimeType()
        if name == "TIMESTAMP":
            return dt.TimestampType("UTC")
        if name == "TIMESTAMP_NTZ":
            return dt.TimestampType(None)
        if name in ("DECIMAL", "DEC", "NUMERIC"):
            p, s = 10, 0
            if self.accept_op("("):
                p = self._parse_int_value()
                if self.accept_op(","):
                    s = self._parse_int_value()
                self.expect_op(")")
            return dt.DecimalType(p, s)
        if name == "VOID":
            return dt.NullType()
        if name == "ARRAY":
            self.expect_op("<")
            el = self.parse_data_type()
            self._expect_close_angle()
            return dt.ArrayType(el)
        if name == "MAP":
            self.expect_op("<")
            k = self.parse_data_type()
            self.expect_op(",")
            v = self.parse_data_type()
            self._expect_close_angle()
            return dt.MapType(k, v)
        if name == "STRUCT":
            self.expect_op("<")
            fields = []
            if not self.at_op(">"):
                while True:
                    fname = self.parse_identifier()
                    self.accept_op(":")
                    ftype = self.parse_data_type()
                    fields.append(dt.StructField(fname, ftype))
                    if not self.accept_op(","):
                        break
            self._expect_close_angle()
            return dt.StructType(tuple(fields))
        if name == "INTERVAL":
            if self.at_kw("YEAR", "MONTH"):
                self.advance()
                if self.accept_kw("TO"):
                    self.advance()
                return dt.YearMonthIntervalType()
            if self.at_kw("DAY", "HOUR", "MINUTE", "SECOND"):
                self.advance()
                if self.accept_kw("TO"):
                    self.advance()
            return dt.DayTimeIntervalType()
        raise self.error(f"unknown type {name!r}")

    def _expect_close_angle(self):
        if self.accept_op(">"):
            return
        if self.at_op(">>"):
            # split >> into two > for nested generics
            t = self.advance()
            self.tokens.insert(self.i, Token("op", ">", t.pos + 1))
            return
        if self.at_op(">>>"):
            t = self.advance()
            self.tokens.insert(self.i, Token("op", ">>", t.pos + 1))
            return
        raise self.error("expected '>'")

    # ------------------------------------------------------------------
    # commands
    # ------------------------------------------------------------------
    def _accept_if_exists(self) -> bool:
        if self.at_kw("IF") and self.at_kw("EXISTS", ahead=1):
            self.advance()
            self.advance()
            return True
        return False

    def _accept_if_not_exists(self) -> bool:
        if self.at_kw("IF") and self.at_kw("NOT", ahead=1) and self.at_kw("EXISTS", ahead=2):
            self.advance()
            self.advance()
            self.advance()
            return True
        return False

    def parse_create(self) -> pl.Plan:
        self.expect_kw("CREATE")
        replace = False
        if self.accept_kw("OR"):
            self.expect_kw("REPLACE")
            replace = True
        temporary = self.accept_kw("TEMPORARY", "TEMP") is not None
        self.accept_kw("GLOBAL")
        kind = self.expect_kw("TABLE", "VIEW", "DATABASE", "SCHEMA", "FUNCTION")
        if kind in ("DATABASE", "SCHEMA"):
            if_not_exists = self._accept_if_not_exists()
            name = self.parse_qualified_name()
            comment = location = None
            while True:
                if self.accept_kw("COMMENT"):
                    comment = self.advance().value
                elif self.accept_kw("LOCATION"):
                    location = self.advance().value
                else:
                    break
            return pl.CreateDatabase(name, if_not_exists, comment, location)
        if kind == "VIEW":
            if_not_exists = self._accept_if_not_exists()
            name = self.parse_qualified_name()
            cols: Tuple[str, ...] = ()
            if self.at_op("("):
                cols = self.paren_ident_list()
            self.expect_kw("AS")
            query = self.parse_query()
            return pl.CreateView(name, query, temporary, replace, cols)
        # TABLE
        if_not_exists = self._accept_if_not_exists()
        name = self.parse_qualified_name()
        schema = None
        if self.at_op("("):
            self.advance()
            fields = []
            while True:
                fname = self.parse_identifier()
                ftype = self.parse_data_type()
                nullable = True
                if self.accept_kw("NOT"):
                    self.expect_kw("NULL")
                    nullable = False
                if self.accept_kw("COMMENT"):
                    self.advance()
                fields.append(dt.StructField(fname, ftype, nullable))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            schema = dt.StructType(tuple(fields))
        fmt = None
        location = None
        partition_by: Tuple[str, ...] = ()
        options: Tuple[Tuple[str, str], ...] = ()
        comment = None
        while True:
            if self.accept_kw("USING", "STORED"):
                self.accept_kw("AS")
                fmt = self.parse_identifier().lower()
            elif self.accept_kw("LOCATION"):
                location = self.advance().value
            elif self.accept_kw("COMMENT"):
                comment = self.advance().value
            elif self.accept_kw("PARTITIONED"):
                self.expect_kw("BY")
                partition_by = self.paren_ident_list()
            elif self.accept_kw("TBLPROPERTIES", "OPTIONS"):
                self.expect_op("(")
                opts = []
                while True:
                    k = self.advance().value
                    self.expect_op("=")
                    v = self.advance().value
                    opts.append((k, v))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                options = tuple(opts)
            else:
                break
        query = None
        if self.accept_kw("AS"):
            query = self.parse_query()
        return pl.CreateTable(name, schema, fmt, location, query, if_not_exists,
                              replace, partition_by, options, comment)

    def parse_drop(self) -> pl.Plan:
        self.expect_kw("DROP")
        kind = self.expect_kw("TABLE", "VIEW", "DATABASE", "SCHEMA")
        if_exists = self._accept_if_exists()
        name = self.parse_qualified_name()
        if kind in ("DATABASE", "SCHEMA"):
            cascade = self.accept_kw("CASCADE") is not None
            self.accept_kw("RESTRICT")
            return pl.DropDatabase(name, if_exists, cascade)
        purge = self.accept_kw("PURGE") is not None
        return pl.DropTable(name, if_exists, purge, is_view=(kind == "VIEW"))

    def parse_insert(self) -> pl.Plan:
        self.expect_kw("INSERT")
        overwrite = False
        if self.accept_kw("OVERWRITE"):
            overwrite = True
            self.accept_kw("TABLE")
        else:
            self.expect_kw("INTO")
            self.accept_kw("TABLE")
        name = self.parse_qualified_name()
        partition_spec: Tuple[Tuple[str, Optional[str]], ...] = ()
        if self.accept_kw("PARTITION"):
            self.expect_op("(")
            ps = []
            while True:
                k = self.parse_identifier()
                v = None
                if self.accept_op("="):
                    v = self.advance().value
                ps.append((k, v))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            partition_spec = tuple(ps)
        columns: Tuple[str, ...] = ()
        if self.at_op("(") and not self.at_kw("SELECT", ahead=1) and not self.at_kw("WITH", ahead=1):
            columns = self.paren_ident_list()
        query = self.parse_query()
        return pl.InsertInto(name, query, overwrite, columns, partition_spec)

    def parse_show(self) -> pl.Plan:
        self.expect_kw("SHOW")
        kind = self.expect_kw("TABLES", "DATABASES", "SCHEMAS", "COLUMNS",
                              "FUNCTIONS", "VIEWS", "CATALOGS", "CREATE",
                              "TBLPROPERTIES", "PARTITIONS")
        if kind == "CATALOGS":
            pattern = None
            if self.accept_kw("LIKE"):
                pattern = self.advance().value
            return pl.ShowCatalogs(pattern)
        if kind == "CREATE":
            self.expect_kw("TABLE")
            return pl.ShowCreateTable(self.parse_qualified_name())
        if kind == "TBLPROPERTIES":
            name = self.parse_qualified_name()
            key = None
            if self.accept_op("("):
                key = str(self.advance().value)
                self.expect_op(")")
            return pl.ShowTblProperties(name, key)
        if kind == "PARTITIONS":
            return pl.ShowPartitions(self.parse_qualified_name())
        if kind in ("DATABASES", "SCHEMAS"):
            pattern = None
            if self.accept_kw("LIKE"):
                pattern = self.advance().value
            return pl.ShowDatabases(pattern)
        if kind in ("TABLES", "VIEWS"):
            db = None
            if self.accept_kw("IN", "FROM"):
                db = self.parse_qualified_name()
            pattern = None
            if self.accept_kw("LIKE"):
                pattern = self.advance().value
            elif self.peek().kind == "string":
                pattern = self.advance().value
            return pl.ShowTables(db, pattern)
        if kind == "COLUMNS":
            self.expect_kw("IN", "FROM")
            return pl.ShowColumns(self.parse_qualified_name())
        pattern = None
        if self.accept_kw("LIKE"):
            pattern = self.advance().value
        return pl.ShowFunctions(pattern)

    def parse_describe(self) -> pl.Plan:
        self.expect_kw("DESCRIBE", "DESC")
        if self.accept_kw("QUERY"):
            return pl.Explain(self.parse_query(), "simple")
        if self.accept_kw("DATABASE", "SCHEMA", "NAMESPACE"):
            extended = self.accept_kw("EXTENDED") is not None
            return pl.DescribeDatabase(self.parse_qualified_name(),
                                       extended)
        self.accept_kw("TABLE")
        extended = self.accept_kw("EXTENDED", "FORMATTED") is not None
        return pl.DescribeTable(self.parse_qualified_name(), extended)

    def parse_set(self) -> pl.Plan:
        self.expect_kw("SET")
        if self.peek().kind == "eof" or self.at_op(";"):
            return pl.SetVariable("", None)
        # collect key tokens until '=' (keys may contain dots)
        parts = []
        while not self.at_op("=") and self.peek().kind != "eof" and not self.at_op(";"):
            parts.append(self.advance().value)
        key = "".join(parts)
        value = None
        if self.accept_op("="):
            vparts = []
            while self.peek().kind != "eof" and not self.at_op(";"):
                vparts.append(self.advance().value)
            value = " ".join(vparts)
        return pl.SetVariable(key, value)

    def parse_delete(self) -> pl.Plan:
        self.expect_kw("DELETE")
        self.expect_kw("FROM")
        name = self.parse_qualified_name()
        self.parse_optional_alias()
        cond = None
        if self.accept_kw("WHERE"):
            cond = self.parse_expr()
        return pl.Delete(name, cond)

    def parse_update(self) -> pl.Plan:
        self.expect_kw("UPDATE")
        name = self.parse_qualified_name()
        self.parse_optional_alias()
        self.expect_kw("SET")
        assignments = []
        while True:
            target = self.parse_qualified_name()
            self.expect_op("=")
            value = self.parse_expr()
            assignments.append((target, value))
            if not self.accept_op(","):
                break
        cond = None
        if self.accept_kw("WHERE"):
            cond = self.parse_expr()
        return pl.Update(name, tuple(assignments), cond)

    def parse_merge(self) -> pl.Plan:
        self.expect_kw("MERGE")
        self.expect_kw("INTO")
        target = self.parse_qualified_name()
        target_alias = self.parse_optional_alias()
        self.expect_kw("USING")
        source = self.parse_relation_primary()
        self.expect_kw("ON")
        condition = self.parse_expr()
        matched, not_matched, not_matched_by_source = [], [], []
        while self.at_kw("WHEN"):
            self.advance()
            negated = self.accept_kw("NOT") is not None
            self.expect_kw("MATCHED")
            by_source = False
            if self.accept_kw("BY"):
                w = self.expect_kw("TARGET", "SOURCE")
                by_source = w == "SOURCE"
            cond = None
            if self.accept_kw("AND"):
                cond = self.parse_expr()
            self.expect_kw("THEN")
            if self.accept_kw("DELETE"):
                action = pl.MergeAction("delete", cond)
            elif self.accept_kw("UPDATE"):
                self.expect_kw("SET")
                if self.at_op("*"):
                    self.advance()
                    action = pl.MergeAction("update_star", cond)
                else:
                    assigns = []
                    while True:
                        tgt = self.parse_qualified_name()
                        self.expect_op("=")
                        assigns.append((tgt, self.parse_expr()))
                        if not self.accept_op(","):
                            break
                    action = pl.MergeAction("update", cond, tuple(assigns))
            else:
                self.expect_kw("INSERT")
                if self.at_op("*"):
                    self.advance()
                    action = pl.MergeAction("insert_star", cond)
                else:
                    cols: Tuple[str, ...] = ()
                    if self.at_op("("):
                        cols = self.paren_ident_list()
                    self.expect_kw("VALUES")
                    self.expect_op("(")
                    vals = self.parse_expr_list()
                    self.expect_op(")")
                    if cols and len(cols) != len(vals):
                        raise self.error(
                            f"INSERT column list has {len(cols)} columns but "
                            f"{len(vals)} values were supplied")
                    if cols:
                        assigns = tuple(((c,), v) for c, v in zip(cols, vals))
                    else:
                        # positional insert: empty target means "by position"
                        assigns = tuple(((), v) for v in vals)
                    action = pl.MergeAction("insert", cond, assigns)
            if negated and by_source:
                not_matched_by_source.append(action)
            elif negated:
                not_matched.append(action)
            else:
                matched.append(action)
        return pl.MergeInto(target, target_alias, source, condition,
                            tuple(matched), tuple(not_matched),
                            tuple(not_matched_by_source))


def _number_literal(raw: str) -> ex.Literal:
    suffix = ""
    body = raw
    if raw[-2:].upper() == "BD":
        suffix, body = "BD", raw[:-2]
    elif raw[-1].upper() in "LSYDF" and not raw[-1].isdigit():
        suffix, body = raw[-1].upper(), raw[:-1]
    if suffix == "BD" or ("." in body or "e" in body.lower()) and suffix not in ("D", "F"):
        if suffix == "BD" or ("e" not in body.lower()):
            d = decimal.Decimal(body)
            sign, digits, exp = d.as_tuple()
            scale = max(0, -int(exp))
            precision = max(len(digits) + max(0, int(exp)), scale + 1)
            if precision > 38 or scale > 38:
                raise ValueError(
                    f"decimal literal {raw!r} exceeds maximum precision 38")
            return ex.Literal(LV(dt.DecimalType(precision, scale), d))
        return ex.Literal(LV.float64(float(body)))
    if suffix == "D":
        return ex.Literal(LV.float64(float(body)))
    if suffix == "F":
        return ex.Literal(LV(dt.FloatType(), float(body)))
    v = int(body) if "." not in body and "e" not in body.lower() else int(float(body))
    if suffix == "L":
        return ex.Literal(LV.int64(v))
    if suffix == "S":
        return ex.Literal(LV(dt.ShortType(), v))
    if suffix == "Y":
        return ex.Literal(LV(dt.ByteType(), v))
    if -(2**31) <= v < 2**31:
        return ex.Literal(LV.int32(v))
    return ex.Literal(LV.int64(v))


def _apply_unit(value: decimal.Decimal, unit: str):
    unit = unit.upper()
    if unit in ("YEAR", "YEARS", "MONTH", "MONTHS"):
        if value != int(value):
            raise ValueError(f"fractional {unit.lower()} interval {value} is not allowed")
        months = int(value) * (12 if unit.startswith("YEAR") else 1)
        return months, 0, True, False
    scale = Parser._INTERVAL_UNITS[unit]
    return 0, int(value * scale), False, True


def _parse_interval_range(raw: str, unit: str, unit2: str):
    unit, unit2 = unit.upper(), unit2.upper()
    if unit == "YEAR" and unit2 == "MONTH":
        m = re.fullmatch(r"([+-]?)(\d+)-(\d+)", raw.strip())
        if not m:
            raise ValueError(f"bad YEAR TO MONTH interval {raw!r}")
        sign = -1 if m.group(1) == "-" else 1
        return sign * (int(m.group(2)) * 12 + int(m.group(3))), 0, True, False
    m = re.fullmatch(r"([+-]?)(?:(\d+) )?(\d+)(?::(\d+))?(?::(\d+(?:\.\d+)?))?",
                     raw.strip())
    if not m:
        raise ValueError(f"bad interval {raw!r}")
    sign = -1 if m.group(1) == "-" else 1
    parts = [p for p in m.groups()[1:] if p is not None]
    units_order = ["DAY", "HOUR", "MINUTE", "SECOND"]
    start = units_order.index(unit)
    us = 0
    for offset, p in enumerate(parts):
        u = units_order[start + offset]
        us += int(decimal.Decimal(p) * Parser._INTERVAL_UNITS[u])
    return 0, sign * us, False, True


def _parse_interval_string(raw: str):
    """Multi-unit string form: '1 year 2 months 3 days'."""
    total_months = 0
    total_us = 0
    any_month = any_time = False
    toks = raw.replace(",", " ").split()
    i = 0
    while i < len(toks):
        value = decimal.Decimal(toks[i])
        unit = toks[i + 1].upper()
        m, us, im, it = _apply_unit(value, unit)
        total_months += m
        total_us += us
        any_month |= im
        any_time |= it
        i += 2
    return total_months, total_us, any_month, any_time


def parse_sql(text: str) -> List[pl.Plan]:
    return Parser(text).parse_statements()


@functools.lru_cache(maxsize=256)
def parse_one(text: str) -> pl.Plan:
    """Parse one statement. Results are memoized: spec plans are frozen
    dataclasses (pure text → IR), so repeated queries — dashboards,
    benchmark steady state, prepared-statement-style workloads — skip the
    lexer/parser entirely (the reference caches at the DataFusion logical
    layer instead; here parse is the analogous pure prefix)."""
    stmts = parse_sql(text)
    if len(stmts) != 1:
        raise ValueError(f"expected one statement, got {len(stmts)}")
    return stmts[0]


def parse_expression(text: str) -> ex.Expr:
    p = Parser(text)
    e = p.parse_expr()
    if p.peek().kind != "eof":
        raise p.error("trailing input after expression")
    return e


def parse_data_type(text: str) -> dt.DataType:
    p = Parser(text)
    t = p.parse_data_type()
    if p.peek().kind != "eof":
        raise p.error("trailing input after data type")
    return t
