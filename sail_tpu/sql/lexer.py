"""Spark SQL lexer.

From-scratch tokenizer for the Spark SQL dialect (reference role:
crates/sail-sql-parser/src/lexer — chumsky combinators there; a direct
scanning lexer here). Handles: identifiers (plain + backquoted), string
literals ('..' and ".." with '' escapes and \\ escapes), numeric literals
(int/decimal/scientific + typed suffixes L/S/Y/BD/D/F), operators,
comments (-- and /* */), and parameter markers.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Iterator, List, Optional


class SqlSyntaxError(ValueError):
    def __init__(self, message: str, text: str = "", pos: int = 0):
        line = text.count("\n", 0, pos) + 1
        col = pos - (text.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"{message} (line {line}, col {col})")
        self.pos = pos


@dataclass(frozen=True)
class Token:
    kind: str  # ident | quoted_ident | string | number | op | param | eof
    value: str
    pos: int

    @property
    def upper(self) -> str:
        return self.value.upper()


_OPERATORS = [
    "<=>", ">>>", "<<", ">>", "||", "->", "=>", "::", "<=", ">=", "<>", "!=", "==",
    "(", ")", "[", "]", ",", ".", ";", "+", "-", "*", "/", "%", "=", "<",
    ">", "!", "~", "&", "|", "^", "?", ":", "@",
]

_NUMBER_RE = re.compile(
    r"(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?(?:BD|bd|[LlSsYyDdFf])?")
_IDENT_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")
_WS_RE = re.compile(r"\s+")


def tokenize(text: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        m = _WS_RE.match(text, i)
        if m:
            i = m.end()
            continue
        if text.startswith("--", i):
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                raise SqlSyntaxError("unterminated block comment", text, i)
            i = j + 2
            continue
        if c in "'\"":
            val, i2 = _scan_string(text, i, c)
            tokens.append(Token("string", val, i))
            i = i2
            continue
        if c in "rR" and i + 1 < n and text[i + 1] in "'\"":
            # raw string literal: r'...' — backslashes are literal
            val, i2 = _scan_raw_string(text, i + 1, text[i + 1])
            tokens.append(Token("string", val, i))
            i = i2
            continue
        if c == "`":
            j = i + 1
            buf = []
            while j < n:
                if text[j] == "`":
                    if j + 1 < n and text[j + 1] == "`":
                        buf.append("`")
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            else:
                raise SqlSyntaxError("unterminated quoted identifier", text, i)
            tokens.append(Token("quoted_ident", "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and text[i + 1].isdigit()):
            # '.' only starts a number when not directly after an identifier
            # or ')' (qualified names like a.b vs literals like .5)
            prev = tokens[-1] if tokens else None
            if not (c == "." and prev is not None
                    and (prev.kind in ("ident", "quoted_ident")
                         or prev.value == ")") and prev.pos + len(prev.value) == i):
                m = _NUMBER_RE.match(text, i)
                if m:
                    tokens.append(Token("number", m.group(0), i))
                    i = m.end()
                    continue
        m = _IDENT_RE.match(text, i)
        if m:
            tokens.append(Token("ident", m.group(0), i))
            i = m.end()
            continue
        for op in _OPERATORS:
            if text.startswith(op, i):
                tokens.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise SqlSyntaxError(f"unexpected character {c!r}", text, i)
    tokens.append(Token("eof", "", n))
    return tokens


def _scan_raw_string(text: str, i: int, quote: str):
    """Raw string starting at the quote char ``text[i]``; no escapes except
    doubled quotes."""
    j = i + 1
    buf = []
    n = len(text)
    while j < n:
        c = text[j]
        if c == quote:
            if j + 1 < n and text[j + 1] == quote:
                buf.append(quote)
                j += 2
                continue
            return "".join(buf), j + 1
        buf.append(c)
        j += 1
    raise SqlSyntaxError("unterminated string literal", text, i)


def _scan_string(text: str, i: int, quote: str):
    j = i + 1
    buf = []
    n = len(text)
    while j < n:
        c = text[j]
        if c == "\\" and j + 1 < n:
            esc = text[j + 1]
            mapping = {"n": "\n", "t": "\t", "r": "\r", "0": "\0", "b": "\b",
                       "'": "'", '"': '"', "\\": "\\", "%": "\\%", "_": "\\_"}
            buf.append(mapping.get(esc, esc))
            j += 2
            continue
        if c == quote:
            if j + 1 < n and text[j + 1] == quote:
                buf.append(quote)
                j += 2
                continue
            return "".join(buf), j + 1
        buf.append(c)
        j += 1
    raise SqlSyntaxError("unterminated string literal", text, i)
