"""Spark SQL front-end: lexer + recursive-descent parser lowering to the
spec IR (reference role: sail-sql-parser + sail-sql-analyzer)."""

from .parser import parse_data_type, parse_expression, parse_one, parse_sql  # noqa: F401
from .lexer import SqlSyntaxError  # noqa: F401
