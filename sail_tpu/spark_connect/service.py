"""Spark Connect gRPC service.

Reference role: crates/sail-spark-connect/src/server.rs:119-487 (the 11
SparkConnectService RPCs), src/executor.rs (reattachable result buffering),
src/service/plan_analyzer.rs (AnalyzePlan operations), and
src/config_manager.rs (Config). Served via grpc generic method handlers on
the vendored `spark.connect` protos so stock Spark Connect clients attach.
"""

from __future__ import annotations

import threading
import uuid
from concurrent import futures
from typing import Dict, List

import grpc

from . import convert  # noqa: F401  (ensures gen/ is importable first)

from spark.connect import base_pb2 as bpb
from spark.connect import commands_pb2 as cpb
from spark.connect import relations_pb2 as rpb

from ..spec import plan as sp
from .convert import (
    ConvertError,
    data_type_to_proto,
    relation_from_proto,
    schema_from_string,
)

_SERVICE = "spark.connect.SparkConnectService"
_SPARK_VERSION = "4.0.0"


def _ipc_chunks(table, chunk_rows: int = 65536) -> List[bytes]:
    import pyarrow as pa

    out = []
    n = max(table.num_rows, 0)
    for start in range(0, max(n, 1), chunk_rows):
        chunk = table.slice(start, chunk_rows)
        sink = pa.BufferOutputStream()
        with pa.ipc.new_stream(sink, table.schema) as w:
            w.write_table(chunk)
        out.append((chunk.num_rows, sink.getvalue().to_pybytes()))
        if n == 0:
            break
    return out


class _Operation:
    """A buffered operation for reattachable execution (reference:
    crates/sail-spark-connect/src/executor.rs:30-97)."""

    def __init__(self, operation_id: str):
        self.operation_id = operation_id
        self.responses: List[bpb.ExecutePlanResponse] = []
        self.complete = False
        self.released_until = -1  # highest response index released


class SparkConnectServer:
    """gRPC server speaking the Spark Connect protocol."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 session_timeout_s: float = 3600.0):
        from ..server import SessionManager

        self.sessions = SessionManager(session_timeout_s)
        self.server_side_session_ids: Dict[str, str] = {}
        self._operations: Dict[str, _Operation] = {}
        self._lock = threading.Lock()
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host

    def start(self):
        self._server.start()
        return self

    def stop(self, grace: float = 1.0):
        self._server.stop(grace=grace)

    def wait(self):
        self._server.wait_for_termination()

    # ------------------------------------------------------------------
    # session helpers
    # ------------------------------------------------------------------
    def _session(self, session_id: str):
        session = self.sessions.get_or_create(session_id)
        with self._lock:
            if session_id not in self.server_side_session_ids:
                self.server_side_session_ids[session_id] = uuid.uuid4().hex
        return session

    def _server_session_id(self, session_id: str) -> str:
        return self.server_side_session_ids.get(session_id, "")

    @staticmethod
    def _abort(context, e: Exception):
        from ..exec.admission import DeadlineExceeded, ResourceExhausted
        if isinstance(e, ResourceExhausted):
            # typed, retryable load shed: the client backs off and
            # resubmits (nothing executed — no partial side effects)
            code = grpc.StatusCode.RESOURCE_EXHAUSTED
        elif isinstance(e, DeadlineExceeded):
            code = grpc.StatusCode.DEADLINE_EXCEEDED
        elif isinstance(e, (ConvertError, ValueError,
                            NotImplementedError)):
            code = grpc.StatusCode.INVALID_ARGUMENT
        else:
            code = grpc.StatusCode.INTERNAL
        context.abort(code, f"{type(e).__name__}: {e}")

    # ------------------------------------------------------------------
    # ExecutePlan
    # ------------------------------------------------------------------
    def _execute_plan(self, request: bpb.ExecutePlanRequest, context):
        from .. import tracing as tr
        parent = tr.extract_context(context.invocation_metadata())
        with tr.span("spark_connect:execute_plan",
                     {"session_id": request.session_id}, parent=parent):
            yield from self._execute_plan_traced(request, context)

    def _execute_plan_traced(self, request: bpb.ExecutePlanRequest, context):
        session = self._session(request.session_id)
        op_id = request.operation_id or str(uuid.uuid4())
        reattachable = any(
            o.HasField("reattach_options") and o.reattach_options.reattachable
            for o in request.request_options)
        op = _Operation(op_id)

        def mk(**kwargs):
            resp = bpb.ExecutePlanResponse(
                session_id=request.session_id,
                server_side_session_id=self._server_session_id(
                    request.session_id),
                operation_id=op_id,
                response_id=str(uuid.uuid4()), **kwargs)
            return resp

        try:
            which = request.plan.WhichOneof("op_type")
            if which == "root":
                table = session._execute_query(
                    relation_from_proto(request.plan.root))
                for rows, blob in _ipc_chunks(table):
                    op.responses.append(mk(
                        arrow_batch=bpb.ExecutePlanResponse.ArrowBatch(
                            row_count=rows, data=blob)))
            elif which == "command":
                for resp_kwargs in self._run_command(
                        session, request.plan.command):
                    op.responses.append(mk(**resp_kwargs))
            else:
                raise ConvertError(f"unsupported plan op_type: {which}")
        except Exception as e:  # noqa: BLE001 — errors cross the wire
            self._abort(context, e)
            return
        op.responses.append(mk(
            result_complete=bpb.ExecutePlanResponse.ResultComplete()))
        op.complete = True
        if reattachable:
            with self._lock:
                self._operations[(request.session_id, op_id)] = op
        for r in op.responses:
            yield r

    # ------------------------------------------------------------------
    # Commands (reference: src/service/plan_executor.rs:162-616)
    # ------------------------------------------------------------------
    def _run_command(self, session, command: cpb.Command):
        import pyarrow as pa

        which = command.WhichOneof("command_type")
        if which == "sql_command":
            sql = command.sql_command
            query = None
            if sql.HasField("input"):
                # Spark 4 wraps the SQL relation; older clients send `sql`
                rel = sql.input
                if rel.WhichOneof("rel_type") == "sql":
                    query = rel.sql.query
                else:
                    # non-SQL relation: execute eagerly, return the rows
                    table = session._execute_query(relation_from_proto(rel))
                    sink = pa.BufferOutputStream()
                    with pa.ipc.new_stream(sink, table.schema) as w:
                        w.write_table(table)
                    out = rpb.Relation()
                    out.local_relation.data = sink.getvalue().to_pybytes()
                    yield {"sql_command_result":
                           bpb.ExecutePlanResponse.SqlCommandResult(relation=out)}
                    return
            else:
                query = sql.sql
            from ..sql import parse_one
            plan = parse_one(query)
            if isinstance(plan, sp.CommandPlan):
                table = session._execute_command(plan)
                sink = pa.BufferOutputStream()
                with pa.ipc.new_stream(sink, table.schema) as w:
                    w.write_table(table)
                rel = rpb.Relation()
                rel.local_relation.data = sink.getvalue().to_pybytes()
                yield {"sql_command_result":
                       bpb.ExecutePlanResponse.SqlCommandResult(relation=rel)}
            else:
                # a query: hand the relation back for lazy execution
                rel = rpb.Relation()
                rel.sql.query = query
                yield {"sql_command_result":
                       bpb.ExecutePlanResponse.SqlCommandResult(relation=rel)}
            return
        if which == "create_dataframe_view":
            v = command.create_dataframe_view
            plan = relation_from_proto(v.input)
            session.catalog_manager.register_temp_view(
                v.name, plan, replace=v.replace)
            return
        if which == "write_operation":
            w = command.write_operation
            self._write_v1(session, w)
            return
        if which == "write_operation_v2":
            w2 = command.write_operation_v2
            self._write_v2(session, w2)
            return
        if which == "register_function":
            # cloudpickled UDF registration for SQL use (reference:
            # plan_executor.rs handle_register_user_defined_function)
            from .wire_udf import udf_from_proto
            cif = command.register_function
            session.udf.register(cif.function_name, udf_from_proto(cif))
            return
        if which == "register_data_source":
            # cloudpickled user DataSource class (reference:
            # formats/python/mod.rs registration path)
            import cloudpickle
            from .wire_udf import _install_pyspark_shim
            _install_pyspark_shim()
            rds = command.register_data_source
            obj = cloudpickle.loads(rds.python_data_source.command)
            cls = obj if isinstance(obj, type) else next(
                (x for x in obj if isinstance(x, type)), None)
            if cls is None:
                raise ValueError("data source payload contains no class")
            session.dataSource.register(cls, name=rds.name or None)
            return
        if which == "register_table_function":
            # cloudpickled UDTF handler class for SQL FROM-position use
            # (reference: plan_executor.rs register_user_defined_table_
            # function + pyspark_udtf.rs)
            from .wire_udf import udtf_from_proto
            tf = command.register_table_function
            handler, rt = udtf_from_proto(tf)
            session.udf.register_udtf(tf.function_name, handler, rt)
            return
        raise NotImplementedError(f"command {which} not supported yet")

    _SAVE_MODES = {
        cpb.WriteOperation.SAVE_MODE_APPEND: "append",
        cpb.WriteOperation.SAVE_MODE_OVERWRITE: "overwrite",
        cpb.WriteOperation.SAVE_MODE_ERROR_IF_EXISTS: "error",
        cpb.WriteOperation.SAVE_MODE_IGNORE: "ignore",
    }

    def _write_v1(self, session, w: cpb.WriteOperation):
        plan = relation_from_proto(w.input)
        fmt = w.source if w.HasField("source") else "parquet"
        mode = self._SAVE_MODES.get(w.mode, "error")
        save_type = w.WhichOneof("save_type")
        if save_type == "path":
            cmd = sp.WriteDataSource(
                plan, fmt, w.path, mode, tuple(w.partitioning_columns),
                tuple(sorted(w.options.items())))
        elif save_type == "table":
            name = tuple(w.table.table_name.split("."))
            if w.table.save_method == \
                    cpb.WriteOperation.SaveTable.TABLE_SAVE_METHOD_INSERT_INTO:
                cmd = sp.InsertInto(name, plan, overwrite=(mode == "overwrite"))
            else:
                cmd = sp.WriteDataSource(
                    plan, fmt, None, mode, tuple(w.partitioning_columns),
                    tuple(sorted(w.options.items())), name)
        else:
            raise ConvertError("write operation requires a path or table")
        session._execute_command(cmd)

    def _write_v2(self, session, w: cpb.WriteOperationV2):
        plan = relation_from_proto(w.input)
        name = tuple(w.table_name.split("."))
        mode_map = {
            cpb.WriteOperationV2.MODE_CREATE: "error",
            cpb.WriteOperationV2.MODE_OVERWRITE: "overwrite",
            cpb.WriteOperationV2.MODE_APPEND: "append",
            cpb.WriteOperationV2.MODE_REPLACE: "overwrite",
            cpb.WriteOperationV2.MODE_CREATE_OR_REPLACE: "overwrite",
        }
        mode = mode_map.get(w.mode, "error")
        fmt = w.provider if w.HasField("provider") else "parquet"
        session._execute_command(sp.WriteDataSource(
            plan, fmt, None, mode, (),
            tuple(sorted(w.options.items())), name))

    # ------------------------------------------------------------------
    # AnalyzePlan (reference: src/service/plan_analyzer.rs)
    # ------------------------------------------------------------------
    def _analyze_plan(self, request: bpb.AnalyzePlanRequest, context):
        session = self._session(request.session_id)
        resp = bpb.AnalyzePlanResponse(
            session_id=request.session_id,
            server_side_session_id=self._server_session_id(
                request.session_id))
        which = request.WhichOneof("analyze")
        try:
            if which == "schema":
                node = session._resolve(
                    relation_from_proto(request.schema.plan.root))
                from ..spec import data_type as dt
                st = dt.StructType(tuple(
                    dt.StructField(f.name, f.dtype, f.nullable)
                    for f in node.schema))
                resp.schema.schema.CopyFrom(data_type_to_proto(st))
            elif which == "explain":
                from ..plan.nodes import explain
                node = session._resolve(
                    relation_from_proto(request.explain.plan.root))
                resp.explain.explain_string = explain(node)
            elif which == "tree_string":
                from ..plan.nodes import explain
                node = session._resolve(
                    relation_from_proto(request.tree_string.plan.root))
                resp.tree_string.tree_string = explain(node)
            elif which == "is_local":
                resp.is_local.is_local = True
            elif which == "is_streaming":
                resp.is_streaming.is_streaming = False
            elif which == "input_files":
                plan = relation_from_proto(request.input_files.plan.root)
                resp.input_files.files.extend(_input_files(plan))
            elif which == "spark_version":
                resp.spark_version.version = _SPARK_VERSION
            elif which == "ddl_parse":
                st = schema_from_string(request.ddl_parse.ddl_string)
                resp.ddl_parse.parsed.CopyFrom(data_type_to_proto(st))
            elif which == "same_semantics":
                a = relation_from_proto(request.same_semantics.target_plan.root)
                b = relation_from_proto(request.same_semantics.other_plan.root)
                resp.same_semantics.result = (a == b)
            elif which == "semantic_hash":
                plan = relation_from_proto(request.semantic_hash.plan.root)
                resp.semantic_hash.result = hash(plan) & 0x7FFFFFFF
            elif which == "persist":
                resp.persist.SetInParent()  # no-op, as in the reference
            elif which == "unpersist":
                resp.unpersist.SetInParent()
            elif which == "get_storage_level":
                resp.get_storage_level.storage_level.use_memory = True
            elif which == "json_to_ddl":
                import json as _json
                from ..spec.schema_json import schema_from_json
                st = schema_from_json(_json.loads(
                    request.json_to_ddl.json_string))
                resp.json_to_ddl.ddl_string = ", ".join(
                    f"{f.name} {f.data_type.simple_string()}"
                    for f in st.fields)
            else:
                raise NotImplementedError(f"analyze op {which}")
        except Exception as e:  # noqa: BLE001
            self._abort(context, e)
        return resp

    # ------------------------------------------------------------------
    # Config (reference: src/config_manager.rs)
    # ------------------------------------------------------------------
    def _config(self, request: bpb.ConfigRequest, context):
        session = self._session(request.session_id)
        resp = bpb.ConfigResponse(
            session_id=request.session_id,
            server_side_session_id=self._server_session_id(
                request.session_id))
        op = request.operation
        which = op.WhichOneof("op_type")
        conf = session.conf
        if which == "set":
            for kv in op.set.pairs:
                conf.set(kv.key, kv.value)
        elif which == "get":
            for k in op.get.keys:
                v = conf.get(k)
                resp.pairs.add(key=k, value=v if v is not None else "")
        elif which == "get_with_default":
            for kv in op.get_with_default.pairs:
                v = conf.get(kv.key)
                pair = resp.pairs.add(key=kv.key)
                pair.value = v if v is not None else kv.value
        elif which == "get_option":
            for k in op.get_option.keys:
                v = conf.get(k)
                pair = resp.pairs.add(key=k)
                if v is not None:
                    pair.value = v
        elif which == "get_all":
            prefix = op.get_all.prefix if op.get_all.HasField("prefix") else ""
            for k, v in sorted(conf.items()):
                if k.startswith(prefix):
                    resp.pairs.add(key=k, value=v)
        elif which == "unset":
            for k in op.unset.keys:
                conf.reset(k)
        elif which == "is_modifiable":
            for k in op.is_modifiable.keys:
                resp.pairs.add(key=k, value="true")
        return resp

    # ------------------------------------------------------------------
    # Reattach / release / session lifecycle
    # ------------------------------------------------------------------
    def _reattach_execute(self, request: bpb.ReattachExecuteRequest, context):
        key = (request.session_id, request.operation_id)
        with self._lock:
            op = self._operations.get(key)
        if op is None:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          f"unknown operation {request.operation_id}")
            return
        start = 0
        if request.HasField("last_response_id") and request.last_response_id:
            for i, r in enumerate(op.responses):
                if r.response_id == request.last_response_id:
                    start = i + 1
                    break
        for r in op.responses[start:]:
            yield r

    def _release_execute(self, request: bpb.ReleaseExecuteRequest, context):
        key = (request.session_id, request.operation_id)
        if request.WhichOneof("release") == "release_all":
            with self._lock:
                self._operations.pop(key, None)
        return bpb.ReleaseExecuteResponse(
            session_id=request.session_id,
            server_side_session_id=self._server_session_id(
                request.session_id),
            operation_id=request.operation_id)

    def _release_session(self, request: bpb.ReleaseSessionRequest, context):
        self.sessions.release(request.session_id)
        with self._lock:
            self.server_side_session_ids.pop(request.session_id, None)
            for key in [k for k in self._operations
                        if k[0] == request.session_id]:
                del self._operations[key]
        return bpb.ReleaseSessionResponse(session_id=request.session_id)

    def _interrupt(self, request: bpb.InterruptRequest, context):
        return bpb.InterruptResponse(
            session_id=request.session_id,
            server_side_session_id=self._server_session_id(
                request.session_id))

    def _fetch_error_details(self, request, context):
        return bpb.FetchErrorDetailsResponse(
            session_id=request.session_id,
            server_side_session_id=self._server_session_id(
                request.session_id))

    def _add_artifacts(self, request_iterator, context):
        # Reference parity: artifacts are unsupported (reference returns a
        # todo error — src/service/artifact_manager.rs:12-24); drain and ack.
        names = []
        for req in request_iterator:
            if req.HasField("batch"):
                names.extend(a.name for a in req.batch.artifacts)
        resp = bpb.AddArtifactsResponse()
        for n in names:
            resp.artifacts.add(name=n, successful=False)
        return resp

    def _artifact_status(self, request, context):
        out = bpb.ArtifactStatusesResponse()
        for name in request.names:
            out.statuses[name].exists = False
        return out

    def _clone_session(self, request, context):
        context.abort(grpc.StatusCode.UNIMPLEMENTED,
                      "clone_session is not implemented")

    # ------------------------------------------------------------------
    # handler table
    # ------------------------------------------------------------------
    def _handlers(self):
        def u(fn, req_cls):
            return grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())

        def us(fn, req_cls):
            return grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())

        def su(fn, req_cls):
            return grpc.stream_unary_rpc_method_handler(
                fn, request_deserializer=req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString())

        return grpc.method_handlers_generic_handler(_SERVICE, {
            "ExecutePlan": us(self._execute_plan, bpb.ExecutePlanRequest),
            "AnalyzePlan": u(self._analyze_plan, bpb.AnalyzePlanRequest),
            "Config": u(self._config, bpb.ConfigRequest),
            "AddArtifacts": su(self._add_artifacts, bpb.AddArtifactsRequest),
            "ArtifactStatus": u(self._artifact_status,
                                bpb.ArtifactStatusesRequest),
            "Interrupt": u(self._interrupt, bpb.InterruptRequest),
            "ReattachExecute": us(self._reattach_execute,
                                  bpb.ReattachExecuteRequest),
            "ReleaseExecute": u(self._release_execute,
                                bpb.ReleaseExecuteRequest),
            "ReleaseSession": u(self._release_session,
                                bpb.ReleaseSessionRequest),
            "FetchErrorDetails": u(self._fetch_error_details,
                                   bpb.FetchErrorDetailsRequest),
            "CloneSession": u(self._clone_session, bpb.CloneSessionRequest),
        })


def _input_files(plan: sp.QueryPlan) -> List[str]:
    files: List[str] = []

    def walk(p):
        if isinstance(p, sp.ReadDataSource):
            files.extend(p.paths)
        for f in getattr(p, "__dataclass_fields__", {}):
            v = getattr(p, f)
            if isinstance(v, sp.QueryPlan):
                walk(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, sp.QueryPlan):
                        walk(x)

    walk(plan)
    return files
