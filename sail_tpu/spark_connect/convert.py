"""spark.connect proto → spec IR converters.

Reference role: crates/sail-spark-connect/src/proto/{plan,expression,
literal,data_type}.rs — the TryFrom impls mapping the Spark Connect
protocol onto the engine's unresolved spec IR.
"""

from __future__ import annotations

import datetime
import decimal
from typing import Optional, Tuple

from spark.connect import expressions_pb2 as epb
from spark.connect import relations_pb2 as rpb
from spark.connect import types_pb2 as tpb

from ..spec import data_type as dt
from ..spec import expression as ex
from ..spec import plan as sp
from ..spec.literal import Literal as LV


class ConvertError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Data types
# ---------------------------------------------------------------------------

def data_type_from_proto(t: tpb.DataType) -> dt.DataType:
    kind = t.WhichOneof("kind")
    if kind is None or kind == "null":
        return dt.NullType()
    if kind == "binary":
        return dt.BinaryType()
    if kind == "boolean":
        return dt.BooleanType()
    if kind == "byte":
        return dt.ByteType()
    if kind == "short":
        return dt.ShortType()
    if kind == "integer":
        return dt.IntegerType()
    if kind == "long":
        return dt.LongType()
    if kind == "float":
        return dt.FloatType()
    if kind == "double":
        return dt.DoubleType()
    if kind == "decimal":
        d = t.decimal
        return dt.DecimalType(d.precision if d.HasField("precision") else 10,
                              d.scale if d.HasField("scale") else 0)
    if kind in ("string", "char", "var_char"):
        return dt.StringType()
    if kind == "date":
        return dt.DateType()
    if kind == "timestamp":
        return dt.TimestampType("UTC")
    if kind == "timestamp_ntz":
        return dt.TimestampType(None)
    if kind == "calendar_interval":
        return dt.CalendarIntervalType()
    if kind == "year_month_interval":
        return dt.YearMonthIntervalType()
    if kind == "day_time_interval":
        return dt.DayTimeIntervalType()
    if kind == "array":
        return dt.ArrayType(data_type_from_proto(t.array.element_type),
                            t.array.contains_null)
    if kind == "map":
        return dt.MapType(data_type_from_proto(t.map.key_type),
                          data_type_from_proto(t.map.value_type),
                          t.map.value_contains_null)
    if kind == "struct":
        return dt.StructType(tuple(
            dt.StructField(f.name, data_type_from_proto(f.data_type),
                           f.nullable)
            for f in t.struct.fields))
    if kind == "unparsed":
        from ..sql.parser import parse_data_type
        return parse_data_type(t.unparsed.data_type_string)
    raise ConvertError(f"unsupported data type kind: {kind}")


def data_type_to_proto(d: dt.DataType) -> tpb.DataType:
    t = tpb.DataType()
    if isinstance(d, dt.NullType):
        t.null.SetInParent()
    elif isinstance(d, dt.BinaryType):
        t.binary.SetInParent()
    elif isinstance(d, dt.BooleanType):
        t.boolean.SetInParent()
    elif isinstance(d, dt.ByteType):
        t.byte.SetInParent()
    elif isinstance(d, dt.ShortType):
        t.short.SetInParent()
    elif isinstance(d, dt.IntegerType):
        t.integer.SetInParent()
    elif isinstance(d, dt.LongType):
        t.long.SetInParent()
    elif isinstance(d, dt.FloatType):
        t.float.SetInParent()
    elif isinstance(d, dt.DoubleType):
        t.double.SetInParent()
    elif isinstance(d, dt.DecimalType):
        t.decimal.precision = d.precision
        t.decimal.scale = d.scale
    elif isinstance(d, dt.StringType):
        t.string.SetInParent()
    elif isinstance(d, dt.DateType):
        t.date.SetInParent()
    elif isinstance(d, dt.TimestampType):
        if d.timezone is None:
            t.timestamp_ntz.SetInParent()
        else:
            t.timestamp.SetInParent()
    elif isinstance(d, dt.CalendarIntervalType):
        t.calendar_interval.SetInParent()
    elif isinstance(d, dt.YearMonthIntervalType):
        t.year_month_interval.SetInParent()
    elif isinstance(d, dt.DayTimeIntervalType):
        t.day_time_interval.SetInParent()
    elif isinstance(d, dt.ArrayType):
        t.array.element_type.CopyFrom(data_type_to_proto(d.element_type))
        t.array.contains_null = d.contains_null
    elif isinstance(d, dt.MapType):
        t.map.key_type.CopyFrom(data_type_to_proto(d.key_type))
        t.map.value_type.CopyFrom(data_type_to_proto(d.value_type))
        t.map.value_contains_null = d.value_contains_null
    elif isinstance(d, dt.StructType):
        for f in d.fields:
            pf = t.struct.fields.add()
            pf.name = f.name
            pf.data_type.CopyFrom(data_type_to_proto(f.data_type))
            pf.nullable = f.nullable
    else:
        raise ConvertError(f"cannot encode data type {d!r}")
    return t


def schema_from_string(s: str) -> dt.StructType:
    """DDL-formatted ("a INT, b STRING") or type-string schema."""
    from ..sql.parser import parse_data_type
    text = s.strip()
    parsed = None
    try:
        parsed = parse_data_type(text if text.lower().startswith("struct")
                                 else f"struct<{text}>")
    except Exception:
        parsed = parse_data_type(text)
    if not isinstance(parsed, dt.StructType):
        raise ConvertError(f"schema string is not a struct: {s!r}")
    return parsed


# ---------------------------------------------------------------------------
# Literals
# ---------------------------------------------------------------------------

_EPOCH_D = datetime.date(1970, 1, 1)
_EPOCH_TS = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def literal_value_from_proto(l: epb.Expression.Literal) -> LV:
    kind = l.WhichOneof("literal_type")
    if kind is None or kind == "null":
        d = data_type_from_proto(l.null) if l.HasField("null") else dt.NullType()
        return LV.null(d)
    if kind == "boolean":
        return LV.boolean(l.boolean)
    if kind == "byte":
        return LV(dt.ByteType(), int(l.byte))
    if kind == "short":
        return LV(dt.ShortType(), int(l.short))
    if kind == "integer":
        return LV.int32(l.integer)
    if kind == "long":
        return LV.int64(l.long)
    if kind == "float":
        return LV(dt.FloatType(), float(l.float))
    if kind == "double":
        return LV.float64(l.double)
    if kind == "decimal":
        v = decimal.Decimal(l.decimal.value)
        precision = l.decimal.precision if l.decimal.HasField("precision") \
            else max(1, len(v.as_tuple().digits))
        scale = l.decimal.scale if l.decimal.HasField("scale") \
            else max(0, -v.as_tuple().exponent)
        return LV.decimal(v, precision, scale)
    if kind == "string":
        return LV.string(l.string)
    if kind == "binary":
        return LV(dt.BinaryType(), bytes(l.binary))
    if kind == "date":
        return LV.date(_EPOCH_D + datetime.timedelta(days=l.date))
    if kind == "timestamp":
        return LV.timestamp(
            _EPOCH_TS + datetime.timedelta(microseconds=l.timestamp))
    if kind == "timestamp_ntz":
        v = (_EPOCH_TS + datetime.timedelta(microseconds=l.timestamp_ntz))
        return LV(dt.TimestampType(None), v.replace(tzinfo=None))
    if kind == "day_time_interval":
        return LV.interval_microseconds(l.day_time_interval)
    if kind == "year_month_interval":
        return LV(dt.YearMonthIntervalType(), int(l.year_month_interval))
    if kind == "array":
        elems = [literal_value_from_proto(e) for e in l.array.elements]
        et = data_type_from_proto(l.array.element_type) if \
            l.array.HasField("element_type") else (
                elems[0].data_type if elems else dt.NullType())
        return LV(dt.ArrayType(et), tuple(e.value for e in elems))
    if kind == "struct":
        vals = [literal_value_from_proto(e) for e in l.struct.elements]
        st = data_type_from_proto(l.struct.struct_type) if \
            l.struct.HasField("struct_type") else dt.StructType(tuple(
                dt.StructField(f"_{i+1}", v.data_type)
                for i, v in enumerate(vals)))
        return LV(st, tuple(v.value for v in vals))
    raise ConvertError(f"unsupported literal kind: {kind}")


# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------

def sort_order_from_proto(s: epb.Expression.SortOrder) -> ex.SortOrder:
    asc = s.direction != epb.Expression.SortOrder.SORT_DIRECTION_DESCENDING
    if s.null_ordering == epb.Expression.SortOrder.SORT_NULLS_FIRST:
        nf: Optional[bool] = True
    elif s.null_ordering == epb.Expression.SortOrder.SORT_NULLS_LAST:
        nf = False
    else:
        nf = None
    return ex.SortOrder(expr_from_proto(s.child), asc, nf)


def _window_frame_bound(b) -> Optional[int]:
    which = b.WhichOneof("boundary")
    if which == "current_row":
        return 0
    if which == "unbounded":
        return None
    lit = b.value.literal
    k = lit.WhichOneof("literal_type")
    if k in ("integer", "long", "byte", "short"):
        return int(getattr(lit, k))
    raise ConvertError("window frame boundary must be an integer literal")


def expr_from_proto(e: epb.Expression) -> ex.Expr:
    kind = e.WhichOneof("expr_type")
    if kind == "literal":
        return ex.Literal(literal_value_from_proto(e.literal))
    if kind == "unresolved_attribute":
        ua = e.unresolved_attribute
        parts = tuple(_split_attribute(ua.unparsed_identifier))
        plan_id = ua.plan_id if ua.HasField("plan_id") else None
        return ex.Attribute(parts, plan_id)
    if kind == "unresolved_function":
        f = e.unresolved_function
        args = tuple(expr_from_proto(a) for a in f.arguments)
        name = f.function_name.lower()
        if name == "when":
            # CASE WHEN: args alternate cond, value [, else]
            branches = []
            i = 0
            while i + 1 < len(args):
                branches.append((args[i], args[i + 1]))
                i += 2
            else_v = args[i] if i < len(args) else None
            return ex.CaseWhen(tuple(branches), else_v)
        if name == "in":
            return ex.InList(args[0], args[1:])
        return ex.Function(name, args, f.is_distinct)
    if kind == "expression_string":
        from ..sql.parser import parse_expression
        return parse_expression(e.expression_string.expression)
    if kind == "unresolved_star":
        us = e.unresolved_star
        target = ()
        if us.HasField("unparsed_target") and us.unparsed_target:
            t = us.unparsed_target
            target = tuple(_split_attribute(t[:-2] if t.endswith(".*") else t))
        return ex.Star(target)
    if kind == "alias":
        a = e.alias
        return ex.Alias(expr_from_proto(a.expr), tuple(a.name))
    if kind == "cast":
        c = e.cast
        if c.WhichOneof("cast_to_type") == "type":
            target = data_type_from_proto(c.type)
        else:
            from ..sql.parser import parse_data_type
            target = parse_data_type(c.type_str)
        try_ = (c.eval_mode == epb.Expression.Cast.EVAL_MODE_TRY)
        return ex.Cast(expr_from_proto(c.expr), target, try_)
    if kind == "sort_order":
        return sort_order_from_proto(e.sort_order)
    if kind == "lambda_function":
        lf = e.lambda_function
        return ex.LambdaFunction(
            expr_from_proto(lf.function),
            tuple(v.name_parts[0] for v in lf.arguments))
    if kind == "unresolved_named_lambda_variable":
        return ex.LambdaVariable(e.unresolved_named_lambda_variable.name_parts[0])
    if kind == "window":
        w = e.window
        frame = None
        if w.HasField("frame_spec"):
            fs = w.frame_spec
            ft = "range" if fs.frame_type == \
                epb.Expression.Window.WindowFrame.FRAME_TYPE_RANGE else "rows"
            frame = ex.WindowFrame(ft, _window_frame_bound(fs.lower),
                                   _window_frame_bound(fs.upper))
        return ex.Window(
            expr_from_proto(w.window_function),
            tuple(expr_from_proto(p) for p in w.partition_spec),
            tuple(sort_order_from_proto(o) for o in w.order_spec),
            frame)
    if kind == "unresolved_extract_value":
        uev = e.unresolved_extract_value
        child = expr_from_proto(uev.child)
        extraction = expr_from_proto(uev.extraction)
        return ex.Function("element_at", (child, extraction))
    if kind == "call_function":
        cf = e.call_function
        return ex.Function(cf.function_name.lower(),
                           tuple(expr_from_proto(a) for a in cf.arguments))
    if kind == "common_inline_user_defined_function":
        from .wire_udf import udf_expr_from_proto
        return udf_expr_from_proto(e.common_inline_user_defined_function)
    raise ConvertError(f"unsupported expression kind: {kind}")


def _split_attribute(name: str) -> Tuple[str, ...]:
    """Split a (possibly backquoted) dotted identifier."""
    parts = []
    cur = []
    in_bq = False
    i = 0
    while i < len(name):
        ch = name[i]
        if ch == "`":
            if in_bq and i + 1 < len(name) and name[i + 1] == "`":
                cur.append("`")
                i += 2
                continue
            in_bq = not in_bq
        elif ch == "." and not in_bq:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    parts.append("".join(cur))
    return tuple(parts)


# ---------------------------------------------------------------------------
# Relations
# ---------------------------------------------------------------------------

_JOIN_TYPES = {
    rpb.Join.JOIN_TYPE_INNER: "inner",
    rpb.Join.JOIN_TYPE_FULL_OUTER: "full",
    rpb.Join.JOIN_TYPE_LEFT_OUTER: "left",
    rpb.Join.JOIN_TYPE_RIGHT_OUTER: "right",
    rpb.Join.JOIN_TYPE_LEFT_ANTI: "anti",
    rpb.Join.JOIN_TYPE_LEFT_SEMI: "semi",
    rpb.Join.JOIN_TYPE_CROSS: "cross",
}

_SET_OPS = {
    rpb.SetOperation.SET_OP_TYPE_UNION: "union",
    rpb.SetOperation.SET_OP_TYPE_INTERSECT: "intersect",
    rpb.SetOperation.SET_OP_TYPE_EXCEPT: "except",
}


def relation_from_proto(r: rpb.Relation) -> sp.QueryPlan:
    kind = r.WhichOneof("rel_type")
    if kind == "sql":
        from ..sql import parse_one
        plan = parse_one(r.sql.query)
        if not isinstance(plan, sp.QueryPlan):
            raise ConvertError("SQL relation must be a query (commands go "
                               "through SqlCommand)")
        return plan
    if kind == "read":
        rd = r.read
        which = rd.WhichOneof("read_type")
        if which == "named_table":
            name = _split_attribute(rd.named_table.unparsed_identifier)
            return sp.ReadNamedTable(
                name, None, tuple(sorted(rd.named_table.options.items())))
        ds = rd.data_source
        schema = None
        if ds.HasField("schema") and ds.schema:
            schema = schema_from_string(ds.schema)
        return sp.ReadDataSource(
            ds.format if ds.HasField("format") else "parquet",
            tuple(ds.paths), schema, tuple(sorted(ds.options.items())))
    if kind == "project":
        p = r.project
        child = relation_from_proto(p.input) if p.HasField("input") \
            else sp.OneRow()
        return sp.Project(child,
                          tuple(expr_from_proto(x) for x in p.expressions))
    if kind == "filter":
        return sp.Filter(relation_from_proto(r.filter.input),
                         expr_from_proto(r.filter.condition))
    if kind == "join":
        j = r.join
        jt = _JOIN_TYPES.get(j.join_type, "inner")
        cond = expr_from_proto(j.join_condition) \
            if j.HasField("join_condition") else None
        return sp.Join(relation_from_proto(j.left),
                       relation_from_proto(j.right), jt, cond,
                       tuple(j.using_columns))
    if kind == "set_op":
        s = r.set_op
        return sp.SetOperation(relation_from_proto(s.left_input),
                               relation_from_proto(s.right_input),
                               _SET_OPS.get(s.set_op_type, "union"),
                               bool(s.is_all), bool(s.by_name))
    if kind == "sort":
        s = r.sort
        return sp.Sort(relation_from_proto(s.input),
                       tuple(sort_order_from_proto(o) for o in s.order),
                       bool(s.is_global) if s.HasField("is_global") else True)
    if kind == "limit":
        return sp.Limit(relation_from_proto(r.limit.input), r.limit.limit)
    if kind == "offset":
        return sp.Offset(relation_from_proto(r.offset.input), r.offset.offset)
    if kind == "tail":
        return sp.Tail(relation_from_proto(r.tail.input), r.tail.limit)
    if kind == "aggregate":
        a = r.aggregate
        child = relation_from_proto(a.input)
        group = tuple(expr_from_proto(g) for g in a.grouping_expressions)
        aggs = tuple(expr_from_proto(x) for x in a.aggregate_expressions)
        if a.group_type == rpb.Aggregate.GROUP_TYPE_PIVOT:
            return sp.Pivot(child, group, aggs,
                            expr_from_proto(a.pivot.col),
                            tuple(ex.Literal(literal_value_from_proto(v))
                                  for v in a.pivot.values))
        rollup = a.group_type == rpb.Aggregate.GROUP_TYPE_ROLLUP
        cube = a.group_type == rpb.Aggregate.GROUP_TYPE_CUBE
        gsets = None
        if a.group_type == rpb.Aggregate.GROUP_TYPE_GROUPING_SETS:
            gsets = tuple(tuple(expr_from_proto(g) for g in s.grouping_set)
                          for s in a.grouping_sets)
        # Spark's aggregate output = grouping exprs ++ aggregate exprs
        return sp.Aggregate(child, group, group + aggs, None, gsets,
                            rollup, cube)
    if kind == "local_relation":
        lr = r.local_relation
        table = None
        schema = None
        if lr.HasField("data"):
            import pyarrow as pa
            table = pa.ipc.open_stream(lr.data).read_all()
        if lr.HasField("schema") and lr.schema:
            schema = schema_from_string(lr.schema)
        return sp.LocalRelation(table, schema)
    if kind == "range":
        rg = r.range
        return sp.Range(rg.start, rg.end, rg.step,
                        rg.num_partitions if rg.HasField("num_partitions")
                        else None)
    if kind == "sample":
        s = r.sample
        return sp.Sample(relation_from_proto(s.input), s.lower_bound,
                         s.upper_bound, bool(s.with_replacement),
                         s.seed if s.HasField("seed") else None)
    if kind == "deduplicate":
        d = r.deduplicate
        cols = () if d.all_columns_as_keys else tuple(d.column_names)
        return sp.Deduplicate(relation_from_proto(d.input), cols,
                              bool(d.within_watermark))
    if kind == "subquery_alias":
        sa = r.subquery_alias
        return sp.SubqueryAlias(relation_from_proto(sa.input), sa.alias,
                                tuple(sa.qualifier))
    if kind == "repartition":
        rp = r.repartition
        return sp.Repartition(relation_from_proto(rp.input),
                              rp.num_partitions)
    if kind == "repartition_by_expression":
        rp = r.repartition_by_expression
        return sp.Repartition(
            relation_from_proto(rp.input),
            rp.num_partitions if rp.HasField("num_partitions") else None,
            tuple(expr_from_proto(x) for x in rp.partition_exprs))
    if kind == "to_df":
        td = r.to_df
        return _rename_positional(relation_from_proto(td.input),
                                  tuple(td.column_names))
    if kind == "to_schema":
        ts = r.to_schema
        return sp.ToSchema(relation_from_proto(ts.input),
                           data_type_from_proto(ts.schema))
    if kind == "with_columns":
        wc = r.with_columns
        return sp.WithColumns(relation_from_proto(wc.input),
                              tuple(expr_from_proto(a) for a in wc.aliases))
    if kind == "with_columns_renamed":
        wcr = r.with_columns_renamed
        renames = tuple((k, v)
                        for k, v in sorted(wcr.rename_columns_map.items()))
        if not renames and wcr.renames:
            renames = tuple((rn.col_name, rn.new_col_name)
                            for rn in wcr.renames)
        return sp.WithColumnsRenamed(relation_from_proto(wcr.input), renames)
    if kind == "drop":
        d = r.drop
        names = tuple(d.column_names)
        if not names:
            names = tuple(
                c.unresolved_attribute.unparsed_identifier for c in d.columns)
        return sp.Drop(relation_from_proto(d.input), names)
    if kind == "common_inline_user_defined_table_function":
        from .wire_udf import udtf_from_proto
        tf = r.common_inline_user_defined_table_function
        handler, rt = udtf_from_proto(tf)
        return sp.UdtfCall(handler,
                           tuple(expr_from_proto(a) for a in tf.arguments),
                           rt, tf.function_name or "udtf")
    if kind == "group_map":
        from .wire_udf import relation_udf_from_proto
        gm = r.group_map
        return sp.GroupMap(
            relation_from_proto(gm.input),
            tuple(expr_from_proto(e) for e in gm.grouping_expressions),
            relation_udf_from_proto(gm.func, {"grouped_map"}))
    if kind == "co_group_map":
        from .wire_udf import relation_udf_from_proto
        cg = r.co_group_map
        return sp.CoGroupMap(
            relation_from_proto(cg.input),
            relation_from_proto(cg.other),
            tuple(expr_from_proto(e) for e in cg.input_grouping_expressions),
            tuple(expr_from_proto(e) for e in cg.other_grouping_expressions),
            relation_udf_from_proto(cg.func, {"cogrouped_map"}))
    if kind == "map_partitions":
        from .wire_udf import relation_udf_from_proto
        mp = r.map_partitions
        return sp.MapPartitions(
            relation_from_proto(mp.input),
            relation_udf_from_proto(mp.func, {"map_pandas", "map_arrow"}),
            bool(mp.is_barrier) if mp.HasField("is_barrier") else False)
    if kind == "show_string":
        # executed eagerly by the service; represent as the child
        return relation_from_proto(r.show_string.input)
    if kind == "hint":
        return relation_from_proto(r.hint.input)  # hints are advisory
    if kind == "unpivot":
        u = r.unpivot
        values = tuple(expr_from_proto(v) for v in u.values.values) \
            if u.HasField("values") else ()
        return sp.Unpivot(relation_from_proto(u.input),
                          tuple(expr_from_proto(i) for i in u.ids),
                          values, u.variable_column_name,
                          u.value_column_name)
    raise ConvertError(f"unsupported relation kind: {kind}")


def _rename_positional(child: sp.QueryPlan,
                       names: Tuple[str, ...]) -> sp.QueryPlan:
    """toDF(*names): positional rename via ToSchema-style projection.

    Without input schema knowledge at conversion time, emit a
    WithColumnsRenamed marker the resolver understands positionally —
    represented as SubqueryAlias with column renames.
    """
    return sp.SubqueryAlias(child, "__to_df__", (), names)
